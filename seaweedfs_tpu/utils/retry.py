"""Unified failure discipline: RetryPolicy, per-host circuit breaker,
request deadline budget.

Before this module every intra-cluster client had its own ad-hoc loop:
the HA master rotation in client.py, the stale-socket retry in
cache/http_pool, the per-peer "gRPC dead" timestamps in the volume
server's shard fetcher.  They are now all instances of one policy:

* :class:`RetryPolicy` — jittered exponential backoff
  (``base * mult^attempt``, ±``jitter`` fraction), bounded by
  ``max_delay`` and by the ambient deadline budget.
* :class:`CircuitBreaker` — per-host three-state breaker.  After
  ``failure_threshold`` consecutive failures a host opens: calls fail
  fast (microseconds, no dial) until ``open_seconds`` pass, then exactly
  one half-open probe is admitted; its success closes the breaker, its
  failure re-opens the clock.  One process-wide instance
  (:func:`shared_breaker`) is shared by every sync client so evidence of
  a dead peer collected on the read path also protects the write path.
* Deadline budget — a caller's overall time budget rides the
  ``X-Seaweed-Deadline`` header as the *remaining seconds* (relative,
  like a grpc deadline — an absolute wall-clock stamp would corrupt
  every budget by the cross-node clock skew).  Servers rebase it onto
  their own clock into a contextvar (:func:`bind_deadline`); outbound
  requests re-inject what's left and cap their socket timeouts to it,
  so a 2s user-facing request can never spend 30s in a nested retry
  loop.
"""

from __future__ import annotations

import contextvars
import random
import threading
import time
from typing import Optional

DEADLINE_HEADER = "X-Seaweed-Deadline"

# statuses worth retrying after a pause: transient overload (429/503 —
# the admission plane's shed answers), bad gateway / gateway timeout
# from a proxy mid-failover
RETRYABLE_STATUSES = frozenset({429, 502, 503, 504})

# cap on how long a client will honor a server-sent Retry-After: a
# buggy or hostile header must not park a retry loop for an hour
MAX_RETRY_AFTER_S = 30.0


def parse_retry_after(value) -> Optional[float]:
    """Retry-After header -> seconds (delta-seconds or HTTP-date form),
    clamped to [0, MAX_RETRY_AFTER_S]; None when absent/unparseable."""
    if not value:
        return None
    try:
        return min(max(0.0, float(value)), MAX_RETRY_AFTER_S)
    except (TypeError, ValueError):
        pass
    try:
        from email.utils import parsedate_to_datetime
        dt = parsedate_to_datetime(value)
        return min(max(0.0, dt.timestamp() - time.time()),
                   MAX_RETRY_AFTER_S)
    except (TypeError, ValueError):
        return None


def is_shed(status: int, headers) -> bool:
    """True when a response is the overload plane's shed answer
    (``X-Seaweed-Shed: 1`` on a 429/503): the host is ALIVE and asked us
    to back off — it must not be charged as a circuit-breaker failure,
    or a load spike trips every breaker and becomes a capacity
    collapse."""
    if status not in (429, 503) or headers is None:
        return False
    v = headers.get("x-seaweed-shed", "") or headers.get(
        "X-Seaweed-Shed", "")
    return str(v).strip() == "1"

_deadline: contextvars.ContextVar[float] = contextvars.ContextVar(
    "sw_deadline", default=0.0)


class BreakerOpen(ConnectionError):
    """Fast-failure for a host whose circuit breaker is open. Subclasses
    ConnectionError so existing replica-rotation handlers treat it like
    any other connection failure (move on to the next host)."""


class DeadlineExceeded(TimeoutError):
    """The request's propagated deadline budget is exhausted."""


# --- deadline budget ---

def bind_deadline(headers) -> Optional[contextvars.Token]:
    """Bind an incoming X-Seaweed-Deadline (remaining seconds, relative)
    into the ambient context, rebased onto THIS node's clock; returns
    the reset token (None if absent/bad). Relative-per-hop means clock
    skew never corrupts the budget — only network latency leaks in,
    exactly grpc's deadline tradeoff."""
    raw = headers.get(DEADLINE_HEADER, "") if headers else ""
    if not raw:
        return None
    try:
        left = float(raw)
    except ValueError:
        return None
    return _deadline.set(time.time() + max(left, 0.0))


def reset_deadline(token) -> None:
    if token is not None:
        _deadline.reset(token)


def set_deadline(seconds_from_now: float) -> contextvars.Token:
    """Start a fresh budget (entry-point clients)."""
    return _deadline.set(time.time() + seconds_from_now)


def current_deadline() -> float:
    """Ambient absolute deadline, 0.0 when none is set."""
    return _deadline.get()


def remaining_budget() -> Optional[float]:
    """Seconds left in the ambient budget (None = unbounded). Clamped at
    0.0 — callers decide whether that is an error."""
    dl = _deadline.get()
    if not dl:
        return None
    return max(0.0, dl - time.time())


def inject_deadline(headers: dict) -> dict:
    """Add the ambient budget's REMAINING seconds to an outbound header
    dict (no-op when no budget is active)."""
    dl = _deadline.get()
    if dl:
        headers.setdefault(DEADLINE_HEADER,
                           repr(max(dl - time.time(), 0.0)))
    return headers


def cap_timeout(timeout: Optional[float],
                floor: float = 0.001) -> Optional[float]:
    """The smaller of a socket timeout and the remaining budget. Raises
    DeadlineExceeded when the budget is already gone — better to fail
    before the dial than to hand a 0-second timeout to the socket
    layer."""
    left = remaining_budget()
    if left is None:
        return timeout
    if left <= 0.0:
        raise DeadlineExceeded("deadline budget exhausted")
    left = max(left, floor)
    return left if timeout is None else min(timeout, left)


# --- circuit breaker ---

class _HostState:
    __slots__ = ("failures", "opened_at", "probing", "probe_started")

    def __init__(self):
        self.failures = 0
        self.opened_at = 0.0     # 0 = closed
        self.probing = False     # a half-open probe is in flight
        self.probe_started = 0.0


class CircuitBreaker:
    """Per-host breaker. Thread-safe; keys are opaque strings (host:port
    urls in practice)."""

    def __init__(self, failure_threshold: int = 5,
                 open_seconds: float = 15.0, metrics=None):
        self.failure_threshold = failure_threshold
        self.open_seconds = open_seconds
        self.metrics = metrics
        self._lock = threading.Lock()
        self._hosts: dict[str, _HostState] = {}

    def _count(self, name: str) -> None:
        if self.metrics is not None:
            self.metrics.count(f"breaker_{name}")

    def check(self, host: str) -> None:
        """Raise BreakerOpen when `host` is open (and no probe slot is
        available). An expired open window admits exactly one half-open
        probe; concurrent callers keep failing fast until it resolves.
        A probe that never reports back (its caller raised past both
        record_* calls) forfeits the slot after another open window, so
        a lost probe can't wedge the host fast-failing forever."""
        with self._lock:
            st = self._hosts.get(host)
            if st is None or not st.opened_at:
                return
            now = time.monotonic()
            if now - st.opened_at >= self.open_seconds and (
                    not st.probing
                    or now - st.probe_started >= self.open_seconds):
                st.probing = True  # this caller is the probe
                st.probe_started = now
                self._count("half_open")
                return
        self._count("fast_fail")
        raise BreakerOpen(f"circuit breaker open for {host}")

    def record_success(self, host: str) -> None:
        with self._lock:
            st = self._hosts.get(host)
            if st is None:
                return
            if st.opened_at:
                self._count("closed")
            st.failures = 0
            st.opened_at = 0.0
            st.probing = False

    def record_failure(self, host: str) -> None:
        with self._lock:
            st = self._hosts.setdefault(host, _HostState())
            if st.probing:
                # failed half-open probe: restart the open window
                st.probing = False
                st.opened_at = time.monotonic()
                self._count("reopened")
                return
            st.failures += 1
            if not st.opened_at and st.failures >= self.failure_threshold:
                st.opened_at = time.monotonic()
                self._count("opened")

    def is_open(self, host: str) -> bool:
        with self._lock:
            st = self._hosts.get(host)
            return bool(st and st.opened_at)

    def reset(self, host: Optional[str] = None) -> None:
        with self._lock:
            if host is None:
                self._hosts.clear()
            else:
                self._hosts.pop(host, None)


_shared_breaker: Optional[CircuitBreaker] = None
_shared_lock = threading.Lock()


def shared_breaker() -> CircuitBreaker:
    """Process-wide breaker shared by the sync intra-cluster clients
    (http_pool, client.py, the volume server's shard fetcher)."""
    global _shared_breaker
    with _shared_lock:
        if _shared_breaker is None:
            from . import metrics as metrics_mod
            _shared_breaker = CircuitBreaker(
                metrics=metrics_mod.shared("cluster"))
        return _shared_breaker


# --- retry policy ---

class RetryPolicy:
    """Jittered exponential backoff schedule, deadline-aware.

    ``delays()`` yields the sleep before each RETRY (so ``max_attempts=3``
    yields twice).  Sleeps are capped to the remaining ambient budget and
    the iterator stops early once the budget cannot cover another sleep —
    a retry that would start already-expired is pointless work.
    """

    def __init__(self, max_attempts: int = 3, base_delay: float = 0.05,
                 max_delay: float = 2.0, multiplier: float = 2.0,
                 jitter: float = 0.5,
                 rng: Optional[random.Random] = None):
        self.max_attempts = max(1, max_attempts)
        self.base_delay = base_delay
        self.max_delay = max_delay
        self.multiplier = multiplier
        self.jitter = jitter
        self._rng = rng or random

    def backoff(self, attempt: int) -> float:
        """Sleep before retry number `attempt` (0-based)."""
        d = min(self.base_delay * (self.multiplier ** attempt),
                self.max_delay)
        if self.jitter:
            d *= 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
        return max(0.0, d)

    def delays(self):
        from ..observe import wideevents
        for attempt in range(self.max_attempts - 1):
            d = self.backoff(attempt)
            left = remaining_budget()
            if left is not None:
                if left <= d:
                    return  # budget can't cover the sleep, let alone a try
                d = min(d, left)
            # each yielded delay is one retry the caller is about to make:
            # count it on the ambient request's wide event (no-op outside)
            wideevents.annotate_add("retries", 1)
            yield d

    def call(self, fn, *args, retry_on=(ConnectionError, OSError),
             host: str = "", breaker: Optional[CircuitBreaker] = None,
             on_retry=None, **kwargs):
        """Run fn with retries (sync). With `host` + `breaker`, each
        attempt is breaker-gated and recorded; BreakerOpen itself is
        never retried against the same host — it IS the fast path."""
        last: Optional[Exception] = None
        attempt = 0
        while True:
            if breaker is not None and host:
                breaker.check(host)  # BreakerOpen propagates immediately
            try:
                out = fn(*args, **kwargs)
            except retry_on as e:
                if breaker is not None and host:
                    breaker.record_failure(host)
                last = e
            else:
                if breaker is not None and host:
                    breaker.record_success(host)
                return out
            attempt += 1
            if attempt >= self.max_attempts:
                raise last
            d = self.backoff(attempt - 1)
            left = remaining_budget()  # the budget gates each RETRY live
            if left is not None:
                if left <= d:
                    raise last
                d = min(d, left)
            if on_retry is not None:
                on_retry(attempt, last)
            from ..observe import wideevents
            wideevents.annotate_add("retries", 1)
            time.sleep(d)


DEFAULT_POLICY = RetryPolicy()
