"""AES-256-GCM chunk encryption (weed/util/cipher.go).

Same scheme as the reference: a fresh random 256-bit key per chunk, the
12-byte nonce prepended to the ciphertext, key stored (not the data) in the
filer's chunk metadata. Backed by the `cryptography` package's AESGCM
(OpenSSL EVP under the hood — the native path SURVEY §2.12 calls for).
"""

from __future__ import annotations

import base64
import os

# gated: hosts without the `cryptography` wheel can still import every
# module that reaches cipher helpers transitively (filer server, tests);
# only actually encrypting/decrypting requires the dependency
try:
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
    HAVE_AESGCM = True
except ImportError:  # pragma: no cover - env-dependent
    AESGCM = None
    HAVE_AESGCM = False

KEY_SIZE = 32
NONCE_SIZE = 12


def _require() -> None:
    if not HAVE_AESGCM:
        raise RuntimeError(
            "chunk encryption requires the 'cryptography' package")


def encrypt(data: bytes) -> tuple[bytes, bytes]:
    """Encrypt with a fresh key; returns (nonce||ciphertext||tag, key)."""
    _require()
    key = os.urandom(KEY_SIZE)
    nonce = os.urandom(NONCE_SIZE)
    ct = AESGCM(key).encrypt(nonce, data, None)
    return nonce + ct, key


def decrypt(payload: bytes, key: bytes) -> bytes:
    _require()
    if len(payload) < NONCE_SIZE:
        raise ValueError("cipher payload too short")
    nonce, ct = payload[:NONCE_SIZE], payload[NONCE_SIZE:]
    return AESGCM(key).decrypt(nonce, ct, None)


def key_to_str(key: bytes) -> str:
    return base64.b64encode(key).decode()


def key_from_str(s: str) -> bytes:
    return base64.b64decode(s)
