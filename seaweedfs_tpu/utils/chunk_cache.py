"""Back-compat shim: the chunk cache moved to the read-path performance
tier (``seaweedfs_tpu.cache.tiered``) where it grew size-class
accounting, an optional on-disk tier, TTL invalidation, and metrics/span
emission. The old import path and constructor keep working."""

from ..cache.tiered import TieredChunkCache as ChunkCache

__all__ = ["ChunkCache"]
