"""In-memory LRU chunk cache (role of weed/util/chunk_cache: the filer's
ChunkReaderAt keeps hot chunks close so repeated/ranged reads don't re-hit
volume servers).

Byte-budgeted LRU keyed by fid; whole chunks only (partial ranges are
sliced by the caller). Thread-safe — the filer serves from an asyncio loop
plus executor threads.
"""

from __future__ import annotations

import collections
import threading
from typing import Optional


class ChunkCache:
    def __init__(self, max_bytes: int = 64 * 1024 * 1024,
                 max_chunk_bytes: int = 8 * 1024 * 1024):
        self.max_bytes = max_bytes
        # chunks bigger than this aren't worth caching (they'd evict
        # everything else); the reference tiers by chunk size similarly
        self.max_chunk_bytes = max_chunk_bytes
        self._lock = threading.Lock()
        self._data: "collections.OrderedDict[str, bytes]" = \
            collections.OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0

    def get(self, fid: str) -> Optional[bytes]:
        with self._lock:
            data = self._data.get(fid)
            if data is None:
                self.misses += 1
                return None
            self._data.move_to_end(fid)
            self.hits += 1
            return data

    def put(self, fid: str, data: bytes) -> None:
        if len(data) > self.max_chunk_bytes:
            return
        with self._lock:
            old = self._data.pop(fid, None)
            if old is not None:
                self._bytes -= len(old)
            self._data[fid] = data
            self._bytes += len(data)
            while self._bytes > self.max_bytes and self._data:
                _, evicted = self._data.popitem(last=False)
                self._bytes -= len(evicted)

    def drop(self, fid: str) -> None:
        with self._lock:
            old = self._data.pop(fid, None)
            if old is not None:
                self._bytes -= len(old)

    def stats(self) -> dict:
        with self._lock:
            return {"bytes": self._bytes, "chunks": len(self._data),
                    "hits": self.hits, "misses": self.misses}
