"""Commented default TOML config templates (`weed scaffold` equivalent,
weed/command/scaffold.go:30). Any key can be overridden by env var
WEED_<SECTION>_<KEY> (dots -> underscores, upper-cased)."""

SECURITY_TOML = """\
# security.toml — put in ./ , ~/.seaweedfs/ , or /etc/seaweedfs/
# Any key can be overridden by env, e.g. WEED_JWT_SIGNING_KEY=...

[jwt.signing]
# when set, the master signs a per-fid write token on /dir/assign and the
# volume server requires it on POST/PUT/DELETE
key = ""
expires_after_seconds = 10

[jwt.signing.read]
# when set, reads also require a token
key = ""
expires_after_seconds = 60

[guard]
# comma-separated IPs / CIDRs allowed to talk to servers; empty = open.
# NOTE: the whitelist guards every master route including /heartbeat, so
# it MUST include the volume servers' IPs or they cannot register.
# Peer masters listed in -peers are trusted implicitly (raft + proxying).
white_list = ""

[tls]
# when cert_file+key_file are set every server terminates TLS on its HTTP
# port and its gRPC port; verify_client additionally demands a client
# certificate signed by ca_file (mutual TLS) — weed/security/tls.go
ca_file = ""
cert_file = ""
key_file = ""
verify_client = false
# https additionally wraps the HTTP listeners; with certs set, the gRPC
# plane (all intra-cluster RPC) is always secured
https = false
"""

FILER_TOML = """\
# filer.toml — metadata store selection; the first enabled store wins
# (reference: weed/filer/configuration.go)

[memory]
enabled = false

[sqlite]
enabled = true
path = "./filer.db"

[leveldb2]
# sharded sqlite, 8-way by dir hash
enabled = false
dir = "./filerldb2"
"""

MASTER_TOML = """\
# master.toml

[master.maintenance]
# periodic admin scripts, run by the master on a timer
scripts = \"\"\"
  ec.encode -fullPercent=95 -quietFor=1h
  ec.rebuild -force
  ec.balance -force
  volume.balance -force
\"\"\"
sleep_minutes = 17

[master.sequencer]
type = "memory"  # memory | snowflake
"""

NOTIFICATION_TOML = """\
# notification.toml — outbound queue for filer metadata events

[notification.log]
enabled = false

[notification.file]
enabled = false
directory = "./notifications"
"""

REPLICATION_TOML = """\
# replication.toml — cross-cluster replication sink

[sink.filer]
enabled = false
grpcAddress = "localhost:8888"
directory = "/backup"

[sink.local]
enabled = false
directory = "./replicated"

# queue-fed mode (weed filer.replicate -from_queue): consume events from a
# queue the source filer's notification layer feeds, instead of a live
# subscribe (the reference's Kafka/SQS-fed mode, weed/replication/sub)
[source.file]
enabled = false
directory = "./filer_events"     # the notification FileQueue spool
position_path = ""               # consume position (default: in-spool)

[source.broker]
enabled = false
brokers = "localhost:17777"      # messaging brokers (Kafka-class)
namespace = "notifications"
topic = "filer"
position_path = ""
"""

TEMPLATES = {
    "security": SECURITY_TOML,
    "filer": FILER_TOML,
    "master": MASTER_TOML,
    "notification": NOTIFICATION_TOML,
    "replication": REPLICATION_TOML,
}
