"""In-memory message log with time-based offsets, subscriber fanout, and
segment flush — the role of weed/util/log_buffer/log_buffer.go:41.

Entries are (ts_ns, key, value, headers). A flush callback receives full
segments (list of entries) when the buffer exceeds its size threshold or
on explicit flush; readers replay memory since a timestamp and register
for live fanout. The filer's meta log and the messaging broker's topic
partitions both sit on this structure in the reference.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional


@dataclass
class LogEntry:
    ts_ns: int
    key: bytes
    value: bytes
    headers: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        import base64
        return {"ts": self.ts_ns,
                "key": base64.b64encode(self.key).decode(),
                "value": base64.b64encode(self.value).decode(),
                "headers": self.headers}

    @classmethod
    def from_dict(cls, d: dict) -> "LogEntry":
        import base64
        return cls(ts_ns=int(d["ts"]),
                   key=base64.b64decode(d.get("key", "")),
                   value=base64.b64decode(d.get("value", "")),
                   headers=d.get("headers", {}))


class LogBuffer:
    def __init__(self,
                 flush_fn: Optional[Callable[[list[LogEntry]], None]] = None,
                 flush_bytes: int = 4 * 1024 * 1024,
                 retention: int = 65536):
        self._entries: list[LogEntry] = []
        self._bytes = 0
        self._lock = threading.Lock()
        self._subscribers: list[Callable[[LogEntry], None]] = []
        self.flush_fn = flush_fn
        self.flush_bytes = flush_bytes
        self.retention = retention
        self.last_ts_ns = 0

    def add(self, key: bytes, value: bytes,
            headers: Optional[dict] = None,
            ts_ns: int = 0) -> LogEntry:
        with self._lock:
            ts = ts_ns or time.time_ns()
            # strictly monotonic so a timestamp is a unique offset
            if ts <= self.last_ts_ns:
                ts = self.last_ts_ns + 1
            self.last_ts_ns = ts
            e = LogEntry(ts, key, value, headers or {})
            self._entries.append(e)
            self._bytes += len(key) + len(value) + 32
            flush_now = (self.flush_fn is not None
                         and self._bytes >= self.flush_bytes)
            if flush_now:
                segment, self._entries = self._entries, []
                self._bytes = 0
            if len(self._entries) > self.retention:
                self._entries = self._entries[-self.retention:]
            subs = list(self._subscribers)
        if flush_now:
            self.flush_fn(segment)
        for fn in subs:
            try:
                fn(e)
            except Exception:
                pass
        return e

    def flush(self) -> None:
        with self._lock:
            if self.flush_fn is None or not self._entries:
                return
            segment, self._entries = self._entries, []
            self._bytes = 0
        self.flush_fn(segment)

    def read_since(self, ts_ns: int) -> list[LogEntry]:
        with self._lock:
            return [e for e in self._entries if e.ts_ns > ts_ns]

    def subscribe(self, fn: Callable[[LogEntry], None]) -> None:
        with self._lock:
            self._subscribers.append(fn)

    def unsubscribe(self, fn: Callable[[LogEntry], None]) -> None:
        with self._lock:
            if fn in self._subscribers:
                self._subscribers.remove(fn)
