"""Profiling hooks (role of weed/util/grace/pprof.go + net/http/pprof).

- setup_cpu_profile(path): process-wide cProfile started now, dumped at
  exit — the -cpuprofile flag every server command takes (the reference
  routes the same flag through grace.SetupProfiling).
- profile_handler: an aiohttp handler factory serving /debug/profile?
  seconds=N — samples the process with cProfile for N seconds and returns
  pstats text (the /debug/pprof/profile analog).
- trace_annotation(name): JAX profiler annotation context for kernel
  launches; no-op when the profiler is idle, visible in TensorBoard/
  Perfetto traces when one is active.
"""

from __future__ import annotations

import atexit
import cProfile
import io
import pstats
from typing import Optional

_active: Optional[cProfile.Profile] = None


def setup_cpu_profile(path: str) -> None:
    """Start profiling the whole process; write pstats to `path` at exit
    (grace.SetupProfiling, weed/util/grace/pprof.go:11)."""
    global _active
    if not path or _active is not None:
        return
    prof = cProfile.Profile()
    prof.enable()
    _active = prof

    def dump() -> None:
        prof.disable()
        prof.dump_stats(path)

    atexit.register(dump)


def profile_handler():
    """aiohttp handler: GET /debug/profile?seconds=5 returns pstats text
    for that window (net/http/pprof's /debug/pprof/profile analog).
    cProfile allows one active profiler per process, so the endpoint
    answers 409 while -cpuprofile or another window is running."""
    import asyncio
    import threading

    from aiohttp import web

    busy = threading.Lock()

    async def handler(request: web.Request) -> web.Response:
        if _active is not None:
            return web.Response(
                status=409,
                text="process-wide -cpuprofile is active; "
                     "only one profiler can run at a time\n")
        if not busy.acquire(blocking=False):
            return web.Response(status=409,
                                text="another profile window is running\n")
        try:
            seconds = min(float(request.query.get("seconds", 5)), 60.0)
            prof = cProfile.Profile()
            prof.enable()
            await asyncio.sleep(seconds)
            prof.disable()
        finally:
            busy.release()
        out = io.StringIO()
        stats = pstats.Stats(prof, stream=out)
        stats.sort_stats("cumulative").print_stats(60)
        return web.Response(text=out.getvalue(),
                            content_type="text/plain")

    return handler


def trace_annotation(name: str):
    """JAX trace annotation around kernel launches; inert without an
    active profiler session."""
    try:
        import jax.profiler
        return jax.profiler.TraceAnnotation(name)
    except Exception:
        import contextlib
        return contextlib.nullcontext()
