"""Minimal Prometheus-text metrics registry.

Counterpart of the reference's central registry (weed/stats/metrics.go:19-118)
— counters, gauges and duration histograms rendered in Prometheus exposition
format at /metrics (scrape model; the reference also supports push).
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict

_BUCKETS = [0.0001, 0.001, 0.01, 0.1, 1.0, 10.0]


class Registry:
    def __init__(self, subsystem: str):
        self.subsystem = subsystem
        self._lock = threading.Lock()
        self._counters: dict[str, float] = defaultdict(float)
        self._gauges: dict[str, float] = {}
        self._hist: dict[str, list[int]] = {}
        self._hist_sum: dict[str, float] = defaultdict(float)
        self._hist_count: dict[str, int] = defaultdict(int)

    def count(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] += value

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def observe(self, name: str, seconds: float) -> None:
        with self._lock:
            buckets = self._hist.setdefault(name, [0] * (len(_BUCKETS) + 1))
            for i, b in enumerate(_BUCKETS):
                if seconds <= b:
                    buckets[i] += 1
                    break
            else:
                buckets[-1] += 1
            self._hist_sum[name] += seconds
            self._hist_count[name] += 1

    def timed(self, name: str):
        registry = self

        class _Timer:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                registry.observe(name, time.perf_counter() - self.t0)

        return _Timer()

    def render(self) -> str:
        with self._lock:
            lines = []
            p = f"seaweedfs_tpu_{self.subsystem}"
            for name, v in sorted(self._counters.items()):
                lines.append(f"# TYPE {p}_{name}_total counter")
                lines.append(f"{p}_{name}_total {v}")
            for name, v in sorted(self._gauges.items()):
                lines.append(f"# TYPE {p}_{name} gauge")
                lines.append(f"{p}_{name} {v}")
            for name, buckets in sorted(self._hist.items()):
                lines.append(f"# TYPE {p}_{name}_seconds histogram")
                acc = 0
                for i, b in enumerate(_BUCKETS):
                    acc += buckets[i]
                    lines.append(
                        f'{p}_{name}_seconds_bucket{{le="{b}"}} {acc}')
                acc += buckets[-1]
                lines.append(f'{p}_{name}_seconds_bucket{{le="+Inf"}} {acc}')
                lines.append(
                    f"{p}_{name}_seconds_sum {self._hist_sum[name]}")
                lines.append(
                    f"{p}_{name}_seconds_count {self._hist_count[name]}")
            return "\n".join(lines) + "\n"
