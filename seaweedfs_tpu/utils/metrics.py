"""Minimal Prometheus-text metrics registry.

Counterpart of the reference's central registry (weed/stats/metrics.go:19-118)
— counters, gauges and duration histograms rendered in Prometheus exposition
format at /metrics, with optional label sets
(`count("read", labels={"collection": "c"})`) and a push-gateway loop
(LoopPushingMetric, metrics.go:140).
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict

_BUCKETS = [0.0001, 0.001, 0.01, 0.1, 1.0, 10.0]


def _escape(value) -> str:
    """Prometheus exposition label-value escaping: backslash, quote,
    newline (labels carry user-chosen collection names)."""
    return (str(value).replace("\\", r"\\").replace('"', r"\"")
            .replace("\n", r"\n"))


def _key(name: str, labels: dict | None) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{_escape(v)}"'
                     for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}"


class _Timer:
    """Context manager feeding Registry.observe — module-level so the
    per-request hot path never rebuilds a class object."""

    __slots__ = ("_registry", "_name", "t0")

    def __init__(self, registry, name: str):
        self._registry = registry
        self._name = name

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._registry.observe(self._name, time.perf_counter() - self.t0)


class Registry:
    def __init__(self, subsystem: str):
        self.subsystem = subsystem
        self._lock = threading.Lock()
        self._counters: dict[str, float] = defaultdict(float)
        self._gauges: dict[str, float] = {}
        self._hist: dict[str, list[int]] = {}
        self._hist_sum: dict[str, float] = defaultdict(float)
        self._hist_count: dict[str, int] = defaultdict(int)

    def count(self, name: str, value: float = 1.0,
              labels: dict | None = None) -> None:
        with self._lock:
            self._counters[_key(name, labels)] += value

    def gauge(self, name: str, value: float,
              labels: dict | None = None) -> None:
        with self._lock:
            self._gauges[_key(name, labels)] = value

    def observe(self, name: str, seconds: float) -> None:
        with self._lock:
            buckets = self._hist.setdefault(name, [0] * (len(_BUCKETS) + 1))
            for i, b in enumerate(_BUCKETS):
                if seconds <= b:
                    buckets[i] += 1
                    break
            else:
                buckets[-1] += 1
            self._hist_sum[name] += seconds
            self._hist_count[name] += 1

    async def push_loop(self, gateway_url: str, job: str,
                        interval_seconds: float = 15.0) -> None:
        """Push-gateway mode (LoopPushingMetric, weed/stats/metrics.go:140):
        POST the exposition text to <gateway>/metrics/job/<job> forever."""
        import aiohttp
        async with aiohttp.ClientSession() as session:
            while True:
                try:
                    async with session.post(
                            f"{gateway_url.rstrip('/')}/metrics/job/{job}",
                            data=self.render(),
                            headers={"Content-Type": "text/plain"}) as r:
                        await r.read()
                except Exception:
                    pass  # the gateway being down must never hurt serving
                import asyncio
                await asyncio.sleep(interval_seconds)

    def timed(self, name: str):
        return _Timer(self, name)

    @staticmethod
    def _split(key: str) -> tuple[str, str]:
        """'read{a="b"}' -> ('read', '{a="b"}')."""
        if "{" in key:
            name, _, rest = key.partition("{")
            return name, "{" + rest
        return key, ""

    def render(self) -> str:
        with self._lock:
            lines = []
            p = f"seaweedfs_tpu_{self.subsystem}"
            typed: set[str] = set()
            for key, v in sorted(self._counters.items()):
                name, lbl = self._split(key)
                if name not in typed:
                    typed.add(name)
                    lines.append(f"# TYPE {p}_{name}_total counter")
                lines.append(f"{p}_{name}_total{lbl} {v}")
            for key, v in sorted(self._gauges.items()):
                name, lbl = self._split(key)
                if ("g", name) not in typed:
                    typed.add(("g", name))
                    lines.append(f"# TYPE {p}_{name} gauge")
                lines.append(f"{p}_{name}{lbl} {v}")
            for name, buckets in sorted(self._hist.items()):
                lines.append(f"# TYPE {p}_{name}_seconds histogram")
                acc = 0
                for i, b in enumerate(_BUCKETS):
                    acc += buckets[i]
                    lines.append(
                        f'{p}_{name}_seconds_bucket{{le="{b}"}} {acc}')
                acc += buckets[-1]
                lines.append(f'{p}_{name}_seconds_bucket{{le="+Inf"}} {acc}')
                lines.append(
                    f"{p}_{name}_seconds_sum {self._hist_sum[name]}")
                lines.append(
                    f"{p}_{name}_seconds_count {self._hist_count[name]}")
            return "\n".join(lines) + "\n"
