"""Minimal Prometheus-text metrics registry.

Counterpart of the reference's central registry (weed/stats/metrics.go:19-118)
— counters, gauges and duration histograms rendered in Prometheus exposition
format at /metrics, with optional label sets
(`count("read", labels={"collection": "c"})`,
`observe("read", dt, labels={"collection": "c"})`) and a push-gateway loop
(LoopPushingMetric, metrics.go:140).
"""

from __future__ import annotations

import asyncio
import random
import threading
import time
from collections import defaultdict

_BUCKETS = [0.0001, 0.001, 0.01, 0.1, 1.0, 10.0]

# Pluggable exemplar source: a zero-arg callable returning the ambient
# request's trace id ("" when none).  observe/ installs one at import
# time; keeping it injected (rather than importing observe here) keeps
# utils/ free of an upward dependency.  Exemplars let a p99 histogram
# bucket link straight to a concrete trace in /debug/trace.
_exemplar_source = None


def set_exemplar_source(fn) -> None:
    global _exemplar_source
    _exemplar_source = fn


def _escape(value) -> str:
    """Prometheus exposition label-value escaping: backslash, quote,
    newline (labels carry user-chosen collection names)."""
    return (str(value).replace("\\", r"\\").replace('"', r"\"")
            .replace("\n", r"\n"))


def _key(name: str, labels: dict | None) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{_escape(v)}"'
                     for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}"


class _Timer:
    """Context manager feeding Registry.observe — module-level so the
    per-request hot path never rebuilds a class object."""

    __slots__ = ("_registry", "_name", "_labels", "t0")

    def __init__(self, registry, name: str, labels: dict | None = None):
        self._registry = registry
        self._name = name
        self._labels = labels

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._registry.observe(self._name, time.perf_counter() - self.t0,
                               labels=self._labels)


class Registry:
    def __init__(self, subsystem: str):
        self.subsystem = subsystem
        self._lock = threading.Lock()
        self._counters: dict[str, float] = defaultdict(float)
        self._gauges: dict[str, float] = {}
        self._hist: dict[str, list[int]] = {}
        self._hist_sum: dict[str, float] = defaultdict(float)
        self._hist_count: dict[str, int] = defaultdict(int)
        # key -> per-bucket [(trace_id, seconds) | None]: the most recent
        # traced observation that landed in each bucket
        self._hist_ex: dict[str, list] = {}

    def count(self, name: str, value: float = 1.0,
              labels: dict | None = None) -> None:
        with self._lock:
            self._counters[_key(name, labels)] += value

    def gauge(self, name: str, value: float,
              labels: dict | None = None) -> None:
        with self._lock:
            self._gauges[_key(name, labels)] = value

    def observe(self, name: str, seconds: float,
                labels: dict | None = None) -> None:
        key = _key(name, labels)
        # read the trace id OUTSIDE the lock (contextvar, cheap, and a
        # misbehaving source callable must not run under our lock)
        trace = ""
        if _exemplar_source is not None:
            try:
                trace = _exemplar_source() or ""
            except Exception:
                trace = ""
        with self._lock:
            buckets = self._hist.setdefault(key, [0] * (len(_BUCKETS) + 1))
            for i, b in enumerate(_BUCKETS):
                if seconds <= b:
                    buckets[i] += 1
                    idx = i
                    break
            else:
                buckets[-1] += 1
                idx = len(_BUCKETS)
            self._hist_sum[key] += seconds
            self._hist_count[key] += 1
            if trace:
                ex = self._hist_ex.setdefault(
                    key, [None] * (len(_BUCKETS) + 1))
                ex[idx] = (trace, seconds)

    async def push_loop(self, gateway_url: str, job: str,
                        interval_seconds: float = 15.0) -> None:
        """Push-gateway mode (LoopPushingMetric, weed/stats/metrics.go:140):
        POST the exposition text to <gateway>/metrics/job/<job> forever.
        Failures back off exponentially with jitter so a flapping gateway
        isn't hammered in lockstep by every server in the cluster."""
        import aiohttp
        failures = 0
        # the push gateway lives OUTSIDE the trace domain: no request
        # context exists in this daemon and the gateway would only see
        # (and store) meaningless per-push trace ids
        async with aiohttp.ClientSession(  # weedlint: disable=ctx-propagation
                timeout=aiohttp.ClientTimeout(total=30)) as session:
            while True:
                try:
                    async with session.post(
                            f"{gateway_url.rstrip('/')}/metrics/job/{job}",
                            data=self.render(),
                            headers={"Content-Type": "text/plain"}) as r:
                        await r.read()
                    failures = 0
                except Exception:
                    # the gateway being down must never hurt serving
                    failures = min(failures + 1, 5)
                delay = interval_seconds * (2 ** failures if failures else 1)
                # +/-25% jitter de-synchronizes the fleet after an outage
                await asyncio.sleep(delay * (0.75 + 0.5 * random.random()))

    def timed(self, name: str, labels: dict | None = None):
        return _Timer(self, name, labels)

    def value(self, name: str, labels: dict | None = None,
              default: float = 0.0) -> float:
        """Current value of a counter or gauge — for tests and code that
        branches on its own counters (e.g. cache hit-rate probes)
        without re-parsing the exposition text."""
        key = _key(name, labels)
        with self._lock:
            if key in self._counters:
                return self._counters[key]
            return self._gauges.get(key, default)

    def snapshot(self, prefix: str = "") -> dict[str, float]:
        """Current counters + gauges as a flat {rendered_key: value}
        dict, optionally filtered by family-name prefix — the JSON face
        of the registry for admin status endpoints (ec.mesh.status)
        that must not re-parse exposition text."""
        with self._lock:
            out: dict[str, float] = {}
            for key, v in self._counters.items():
                if key.startswith(prefix):
                    out[key] = v
            for key, v in self._gauges.items():
                if key.startswith(prefix):
                    out[key] = v
            return out

    def exemplars(self, name: str,
                  labels: dict | None = None) -> list:
        """Per-bucket [(trace_id, seconds) | None] for one histogram —
        bucket i covers observations <= _BUCKETS[i], the last entry is
        the +Inf overflow.  Empty list when the histogram has never seen
        a traced observation."""
        key = _key(name, labels)
        with self._lock:
            ex = self._hist_ex.get(key)
            return list(ex) if ex else []

    @staticmethod
    def _split(key: str) -> tuple[str, str]:
        """'read{a="b"}' -> ('read', '{a="b"}')."""
        if "{" in key:
            name, _, rest = key.partition("{")
            return name, "{" + rest
        return key, ""

    @classmethod
    def _families(cls, keys) -> dict[str, list[str]]:
        """Group metric keys by family name, families and label sets both
        sorted — exposition format requires all samples of one family to
        be contiguous under a single # TYPE line."""
        fams: dict[str, list[str]] = {}
        for key in sorted(keys):
            fams.setdefault(cls._split(key)[0], []).append(key)
        return dict(sorted(fams.items()))

    def render(self, exemplars: bool = False) -> str:
        """Prometheus exposition text.  ``exemplars=True`` appends the
        OpenMetrics ``# {trace_id="..."} value`` exemplar suffix to each
        histogram bucket that has one (served at /metrics?exemplars=1 —
        off by default because plain-Prometheus scrapers reject it)."""
        with self._lock:
            lines = []
            p = f"seaweedfs_tpu_{self.subsystem}"
            # _families groups each kind's keys by unique family name, so
            # one # TYPE line at the top of each family iteration is
            # exactly once per family (the old flat-key loop needed a
            # seen-set that mixed str and tuple entries)
            for name, keys in self._families(self._counters).items():
                lines.append(f"# TYPE {p}_{name}_total counter")
                for key in keys:
                    _, lbl = self._split(key)
                    lines.append(f"{p}_{name}_total{lbl} "
                                 f"{self._counters[key]}")
            for name, keys in self._families(self._gauges).items():
                lines.append(f"# TYPE {p}_{name} gauge")
                for key in keys:
                    _, lbl = self._split(key)
                    lines.append(f"{p}_{name}{lbl} {self._gauges[key]}")
            for name, keys in self._families(self._hist).items():
                lines.append(f"# TYPE {p}_{name}_seconds histogram")
                for key in keys:
                    _, lbl = self._split(key)
                    # merge the key's labels with the per-bucket le label
                    inner = lbl[1:-1] + "," if lbl else ""
                    buckets = self._hist[key]
                    ex = (self._hist_ex.get(key)
                          if exemplars else None) or []
                    acc = 0
                    for i, b in enumerate(_BUCKETS):
                        acc += buckets[i]
                        line = (f"{p}_{name}_seconds_bucket"
                                f'{{{inner}le="{b}"}} {acc}')
                        if i < len(ex) and ex[i]:
                            line += (f' # {{trace_id="{ex[i][0]}"}}'
                                     f" {ex[i][1]}")
                        lines.append(line)
                    acc += buckets[-1]
                    line = (f"{p}_{name}_seconds_bucket"
                            f'{{{inner}le="+Inf"}} {acc}')
                    if len(ex) > len(_BUCKETS) and ex[-1]:
                        line += (f' # {{trace_id="{ex[-1][0]}"}}'
                                 f" {ex[-1][1]}")
                    lines.append(line)
                    lines.append(f"{p}_{name}_seconds_sum{lbl} "
                                 f"{self._hist_sum[key]}")
                    lines.append(f"{p}_{name}_seconds_count{lbl} "
                                 f"{self._hist_count[key]}")
            return "\n".join(lines) + "\n"

    def is_empty(self) -> bool:
        with self._lock:
            return not (self._counters or self._gauges or self._hist)


# --- process-wide shared registries ---
# Subsystems that are not servers (the EC feed governor, background
# maintenance) publish through whichever server process hosts them: they
# register here and every server's /metrics handler appends
# render_shared() to its own registry's exposition text. Family names
# can't collide across registries because each subsystem gets its own
# seaweedfs_tpu_<subsystem>_ prefix.

_shared: dict[str, "Registry"] = {}
_shared_lock = threading.Lock()


def shared(subsystem: str) -> "Registry":
    """The process-wide registry for `subsystem` (created on first use)."""
    with _shared_lock:
        reg = _shared.get(subsystem)
        if reg is None:
            reg = _shared[subsystem] = Registry(subsystem)
        return reg


def exposition(registry: "Registry", request) -> str:
    """The full /metrics body for one server: its own registry plus the
    shared subsystem registries, with OpenMetrics exemplars when the
    scrape asks for them (?exemplars=1)."""
    ex = request.query.get("exemplars", "") in ("1", "true")
    return registry.render(exemplars=ex) + render_shared(exemplars=ex)


def render_shared(exemplars: bool = False) -> str:
    """Exposition text of every non-empty shared registry, stable order."""
    with _shared_lock:
        regs = [_shared[name] for name in sorted(_shared)]
    return "".join(r.render(exemplars=exemplars)
                   for r in regs if not r.is_empty())
