"""Minimal Prometheus-text metrics registry.

Counterpart of the reference's central registry (weed/stats/metrics.go:19-118)
— counters, gauges and duration histograms rendered in Prometheus exposition
format at /metrics, with optional label sets
(`count("read", labels={"collection": "c"})`,
`observe("read", dt, labels={"collection": "c"})`) and a push-gateway loop
(LoopPushingMetric, metrics.go:140).
"""

from __future__ import annotations

import asyncio
import random
import threading
import time
from collections import defaultdict

_BUCKETS = [0.0001, 0.001, 0.01, 0.1, 1.0, 10.0]


def _escape(value) -> str:
    """Prometheus exposition label-value escaping: backslash, quote,
    newline (labels carry user-chosen collection names)."""
    return (str(value).replace("\\", r"\\").replace('"', r"\"")
            .replace("\n", r"\n"))


def _key(name: str, labels: dict | None) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{_escape(v)}"'
                     for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}"


class _Timer:
    """Context manager feeding Registry.observe — module-level so the
    per-request hot path never rebuilds a class object."""

    __slots__ = ("_registry", "_name", "_labels", "t0")

    def __init__(self, registry, name: str, labels: dict | None = None):
        self._registry = registry
        self._name = name
        self._labels = labels

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._registry.observe(self._name, time.perf_counter() - self.t0,
                               labels=self._labels)


class Registry:
    def __init__(self, subsystem: str):
        self.subsystem = subsystem
        self._lock = threading.Lock()
        self._counters: dict[str, float] = defaultdict(float)
        self._gauges: dict[str, float] = {}
        self._hist: dict[str, list[int]] = {}
        self._hist_sum: dict[str, float] = defaultdict(float)
        self._hist_count: dict[str, int] = defaultdict(int)

    def count(self, name: str, value: float = 1.0,
              labels: dict | None = None) -> None:
        with self._lock:
            self._counters[_key(name, labels)] += value

    def gauge(self, name: str, value: float,
              labels: dict | None = None) -> None:
        with self._lock:
            self._gauges[_key(name, labels)] = value

    def observe(self, name: str, seconds: float,
                labels: dict | None = None) -> None:
        key = _key(name, labels)
        with self._lock:
            buckets = self._hist.setdefault(key, [0] * (len(_BUCKETS) + 1))
            for i, b in enumerate(_BUCKETS):
                if seconds <= b:
                    buckets[i] += 1
                    break
            else:
                buckets[-1] += 1
            self._hist_sum[key] += seconds
            self._hist_count[key] += 1

    async def push_loop(self, gateway_url: str, job: str,
                        interval_seconds: float = 15.0) -> None:
        """Push-gateway mode (LoopPushingMetric, weed/stats/metrics.go:140):
        POST the exposition text to <gateway>/metrics/job/<job> forever.
        Failures back off exponentially with jitter so a flapping gateway
        isn't hammered in lockstep by every server in the cluster."""
        import aiohttp
        failures = 0
        # the push gateway lives OUTSIDE the trace domain: no request
        # context exists in this daemon and the gateway would only see
        # (and store) meaningless per-push trace ids
        async with aiohttp.ClientSession(  # weedlint: disable=ctx-propagation
                timeout=aiohttp.ClientTimeout(total=30)) as session:
            while True:
                try:
                    async with session.post(
                            f"{gateway_url.rstrip('/')}/metrics/job/{job}",
                            data=self.render(),
                            headers={"Content-Type": "text/plain"}) as r:
                        await r.read()
                    failures = 0
                except Exception:
                    # the gateway being down must never hurt serving
                    failures = min(failures + 1, 5)
                delay = interval_seconds * (2 ** failures if failures else 1)
                # +/-25% jitter de-synchronizes the fleet after an outage
                await asyncio.sleep(delay * (0.75 + 0.5 * random.random()))

    def timed(self, name: str, labels: dict | None = None):
        return _Timer(self, name, labels)

    def value(self, name: str, labels: dict | None = None,
              default: float = 0.0) -> float:
        """Current value of a counter or gauge — for tests and code that
        branches on its own counters (e.g. cache hit-rate probes)
        without re-parsing the exposition text."""
        key = _key(name, labels)
        with self._lock:
            if key in self._counters:
                return self._counters[key]
            return self._gauges.get(key, default)

    def snapshot(self, prefix: str = "") -> dict[str, float]:
        """Current counters + gauges as a flat {rendered_key: value}
        dict, optionally filtered by family-name prefix — the JSON face
        of the registry for admin status endpoints (ec.mesh.status)
        that must not re-parse exposition text."""
        with self._lock:
            out: dict[str, float] = {}
            for key, v in self._counters.items():
                if key.startswith(prefix):
                    out[key] = v
            for key, v in self._gauges.items():
                if key.startswith(prefix):
                    out[key] = v
            return out

    @staticmethod
    def _split(key: str) -> tuple[str, str]:
        """'read{a="b"}' -> ('read', '{a="b"}')."""
        if "{" in key:
            name, _, rest = key.partition("{")
            return name, "{" + rest
        return key, ""

    @classmethod
    def _families(cls, keys) -> dict[str, list[str]]:
        """Group metric keys by family name, families and label sets both
        sorted — exposition format requires all samples of one family to
        be contiguous under a single # TYPE line."""
        fams: dict[str, list[str]] = {}
        for key in sorted(keys):
            fams.setdefault(cls._split(key)[0], []).append(key)
        return dict(sorted(fams.items()))

    def render(self) -> str:
        with self._lock:
            lines = []
            p = f"seaweedfs_tpu_{self.subsystem}"
            # _families groups each kind's keys by unique family name, so
            # one # TYPE line at the top of each family iteration is
            # exactly once per family (the old flat-key loop needed a
            # seen-set that mixed str and tuple entries)
            for name, keys in self._families(self._counters).items():
                lines.append(f"# TYPE {p}_{name}_total counter")
                for key in keys:
                    _, lbl = self._split(key)
                    lines.append(f"{p}_{name}_total{lbl} "
                                 f"{self._counters[key]}")
            for name, keys in self._families(self._gauges).items():
                lines.append(f"# TYPE {p}_{name} gauge")
                for key in keys:
                    _, lbl = self._split(key)
                    lines.append(f"{p}_{name}{lbl} {self._gauges[key]}")
            for name, keys in self._families(self._hist).items():
                lines.append(f"# TYPE {p}_{name}_seconds histogram")
                for key in keys:
                    _, lbl = self._split(key)
                    # merge the key's labels with the per-bucket le label
                    inner = lbl[1:-1] + "," if lbl else ""
                    buckets = self._hist[key]
                    acc = 0
                    for i, b in enumerate(_BUCKETS):
                        acc += buckets[i]
                        lines.append(f"{p}_{name}_seconds_bucket"
                                     f'{{{inner}le="{b}"}} {acc}')
                    acc += buckets[-1]
                    lines.append(f"{p}_{name}_seconds_bucket"
                                 f'{{{inner}le="+Inf"}} {acc}')
                    lines.append(f"{p}_{name}_seconds_sum{lbl} "
                                 f"{self._hist_sum[key]}")
                    lines.append(f"{p}_{name}_seconds_count{lbl} "
                                 f"{self._hist_count[key]}")
            return "\n".join(lines) + "\n"

    def is_empty(self) -> bool:
        with self._lock:
            return not (self._counters or self._gauges or self._hist)


# --- process-wide shared registries ---
# Subsystems that are not servers (the EC feed governor, background
# maintenance) publish through whichever server process hosts them: they
# register here and every server's /metrics handler appends
# render_shared() to its own registry's exposition text. Family names
# can't collide across registries because each subsystem gets its own
# seaweedfs_tpu_<subsystem>_ prefix.

_shared: dict[str, "Registry"] = {}
_shared_lock = threading.Lock()


def shared(subsystem: str) -> "Registry":
    """The process-wide registry for `subsystem` (created on first use)."""
    with _shared_lock:
        reg = _shared.get(subsystem)
        if reg is None:
            reg = _shared[subsystem] = Registry(subsystem)
        return reg


def render_shared() -> str:
    """Exposition text of every non-empty shared registry, stable order."""
    with _shared_lock:
        regs = [_shared[name] for name in sorted(_shared)]
    return "".join(r.render() for r in regs if not r.is_empty())
