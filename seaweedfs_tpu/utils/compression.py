"""Gzip compression helpers (weed/util/compression.go).

The reference compresses needle payloads on the write path when the content
type is worth it and un-gzips on reads for clients that don't accept gzip.
zlib here is the C-backed implementation (the native-equivalent of the
reference's stdlib gzip per SURVEY §2.12).
"""

from __future__ import annotations

import gzip
import struct
import zlib

MIN_COMPRESS_SIZE = 128          # don't bother below this
GOOD_RATIO_NUM, GOOD_RATIO_DEN = 9, 10   # keep only if <90% of original

_COMPRESSABLE_EXT = {
    ".txt", ".htm", ".html", ".css", ".js", ".json", ".xml", ".csv",
    ".svg", ".md", ".log", ".conf", ".yaml", ".yml", ".toml", ".sql",
    ".go", ".py", ".java", ".c", ".h", ".cpp", ".ts", ".tsx", ".bin",
    ".dat", ".idx",
}
_UNCOMPRESSABLE_EXT = {
    ".jpg", ".jpeg", ".png", ".gif", ".webp", ".zip", ".gz", ".tgz",
    ".bz2", ".xz", ".zst", ".7z", ".rar", ".mp3", ".mp4", ".mkv", ".avi",
    ".mov", ".woff", ".woff2",
}


def is_gzipped(data: bytes) -> bool:
    return len(data) >= 2 and data[0] == 0x1F and data[1] == 0x8B


def is_compressable(ext: str, mime: str) -> bool:
    """Mirror of util.IsCompressableFileType (compression.go): compress
    text-ish content, never re-compress packed formats."""
    ext = ext.lower()
    if ext in _UNCOMPRESSABLE_EXT:
        return False
    if ext in _COMPRESSABLE_EXT:
        return True
    mime = (mime or "").split(";")[0].strip().lower()
    if mime.startswith("text/"):
        return True
    if mime in ("application/json", "application/xml",
                "application/javascript", "application/x-javascript",
                "application/wasm"):
        return True
    if mime.startswith(("image/", "video/", "audio/")):
        return False
    if mime in ("application/zip", "application/gzip",
                "application/x-gzip", "application/pdf"):
        return False
    return False


def compress(data: bytes, level: int = 3) -> bytes:
    """Gzip-container compress (GzipData). Level 3 ~ gzip.BestSpeed
    territory — the write path favors throughput like the reference.

    Hand-rolled container instead of gzip.compress: the stdlib routes
    every call through BytesIO + GzipFile, which the fused warm-down
    profile showed costing more than the deflate itself on small
    payloads (one call per needle). The bytes are identical — fixed
    10-byte header (mtime=0, XFL from level, OS=unknown like the
    stdlib's), the same zlib raw-deflate stream, CRC32 + ISIZE trailer —
    so records compressed before and after this change byte-match."""
    co = zlib.compressobj(level, zlib.DEFLATED, -zlib.MAX_WBITS,
                          zlib.DEF_MEM_LEVEL, 0)
    xfl = 2 if level == 9 else (4 if level == 1 else 0)
    return (b"\x1f\x8b\x08\x00\x00\x00\x00\x00" + bytes([xfl]) + b"\xff"
            + co.compress(data) + co.flush()
            + struct.pack("<II", zlib.crc32(data) & 0xFFFFFFFF,
                          len(data) & 0xFFFFFFFF))


def decompress(data: bytes) -> bytes:
    """UnCompressData: gzip or raw deflate."""
    if is_gzipped(data):
        return gzip.decompress(data)
    return zlib.decompress(data)


def maybe_compress(data: bytes, ext: str = "", mime: str = "") -> tuple[bytes, bool]:
    """Compress when worth it; returns (payload, is_compressed)."""
    if len(data) < MIN_COMPRESS_SIZE or is_gzipped(data):
        return data, False
    if not is_compressable(ext, mime):
        return data, False
    comp = compress(data)
    if len(comp) * GOOD_RATIO_DEN < len(data) * GOOD_RATIO_NUM:
        return comp, True
    return data, False
