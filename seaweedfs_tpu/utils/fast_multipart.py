"""Zero-dependency single-part multipart/form-data parser for the needle
write hot path.

aiohttp's multipart reader routes Content-Type and Content-Disposition
through email.parser/email.headerregistry — profiled at ~40% of volume
server write CPU at 1KB payloads (the reference's equivalent hot path,
weed/storage/needle/needle_parse_upload.go:79-139, is a hand-rolled
mime reader for the same reason). Uploads are overwhelmingly a single
part; this parses that shape with plain bytes.find and falls back to the
full reader (returning None) for anything irregular.
"""

from __future__ import annotations

from typing import NamedTuple, Optional


class Part(NamedTuple):
    data: bytes
    filename: str
    content_type: str
    content_encoding: str


def _header_params(value: str) -> dict:
    """name="x"; filename="y" -> {'name': 'x', 'filename': 'y'} (unquoting
    only the plain quoted form; irregular escapes punt to the caller)."""
    out = {}
    for seg in value.split(";")[1:]:
        if "=" not in seg:
            continue
        k, v = seg.split("=", 1)
        v = v.strip()
        if v.startswith('"'):
            if not v.endswith('"') or "\\" in v:
                raise ValueError(v)
            v = v[1:-1]
        out[k.strip().lower()] = v
    return out


def parse_single_part(body: bytes, content_type: str) -> Optional[Part]:
    """Parse a one-part multipart/form-data body; None = use the slow path
    (multi-part bodies, irregular quoting, missing terminal boundary)."""
    ct = content_type.split(";", 1)
    if ct[0].strip().lower() != "multipart/form-data" or len(ct) != 2:
        return None
    try:
        params = _header_params(content_type)
    except ValueError:
        return None
    boundary = params.get("boundary", "")
    if not boundary:
        return None
    delim = b"--" + boundary.encode("utf-8", "strict")
    # RFC 2046: body = delim CRLF part-headers CRLF CRLF part-data CRLF
    #           delim "--" (optional preamble/epilogue around them)
    start = body.find(delim)
    if start == -1:
        return None
    hdr_start = start + len(delim)
    if body[hdr_start:hdr_start + 2] != b"\r\n":
        return None
    hdr_start += 2
    hdr_end = body.find(b"\r\n\r\n", hdr_start)
    if hdr_end == -1:
        return None
    data_start = hdr_end + 4
    close = body.find(b"\r\n" + delim, data_start)
    if close == -1:
        return None
    # a second part means the body isn't single-part: slow path
    after = body[close + 2 + len(delim):close + 4 + len(delim)]
    if after != b"--":
        return None
    filename = ""
    part_ct = ""
    encoding = ""
    try:
        headers = body[hdr_start:hdr_end].decode("utf-8")
    except UnicodeDecodeError:
        return None
    for line in headers.split("\r\n"):
        name, _, value = line.partition(":")
        lname = name.strip().lower()
        if lname == "content-disposition":
            try:
                filename = _header_params(value).get("filename", "")
            except ValueError:
                return None
        elif lname == "content-type":
            part_ct = value.strip()
        elif lname == "content-transfer-encoding":
            # base64/quoted-printable parts need real decoding: slow path
            if value.strip().lower() not in ("", "binary", "7bit", "8bit"):
                return None
        elif lname == "content-encoding":
            encoding = value.strip()
    return Part(body[data_start:close], filename, part_ct, encoding)
