"""Self-validating write/read benchmark engine (weed/command/benchmark.go).

The harness must not be the bottleneck it measures: aiohttp's client costs
~1ms of CPU per request — on few-core hosts that halves the reported
req/s. This engine speaks minimal HTTP/1.1 over persistent per-thread
sockets (assign -> POST multipart -> GET, keep-alive throughout), the same
wire traffic as the reference benchmark at a fraction of the client CPU.

Payloads are seeded and unique; every read is hash-checked against the
write (benchmark.go's self-validation), so a wrong byte anywhere in the
path fails the run, not just slows it.
"""

from __future__ import annotations

import hashlib
import json
import random
import socket
import threading
import time
from typing import Optional


class _Conn:
    """One persistent HTTP/1.1 connection with minimal parsing."""

    def __init__(self, hostport: str):
        host, port = hostport.rsplit(":", 1)
        self.sock = socket.create_connection((host, int(port)), timeout=30)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._buf = b""

    def request(self, head: bytes, body: bytes = b"") -> tuple[int, bytes]:
        self.sock.sendall(head + body)
        while b"\r\n\r\n" not in self._buf:
            chunk = self.sock.recv(1 << 16)
            if not chunk:
                raise ConnectionError("server closed connection")
            self._buf += chunk
        header, _, rest = self._buf.partition(b"\r\n\r\n")
        status = int(header.split(b" ", 2)[1])
        length = 0
        chunked = False
        for line in header.split(b"\r\n")[1:]:
            k, _, v = line.partition(b":")
            lk = k.strip().lower()
            if lk == b"content-length":
                length = int(v)
            elif lk == b"transfer-encoding" and b"chunked" in v.lower():
                chunked = True
        if chunked:
            # servers here never chunk data-path responses; drain defensively
            while not rest.endswith(b"0\r\n\r\n"):
                chunk = self.sock.recv(1 << 16)
                if not chunk:
                    raise ConnectionError("connection closed mid-chunked body")
                rest += chunk
            self._buf = b""
            return status, rest
        while len(rest) < length:
            chunk = self.sock.recv(1 << 16)
            if not chunk:
                raise ConnectionError("short body")
            rest += chunk
        self._buf = rest[length:]
        return status, rest[:length]

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


def _percentiles(lat: list[float]) -> dict:
    lat = sorted(lat)
    return {
        "p50_ms": round(lat[len(lat) // 2] * 1e3, 2),
        "p95_ms": round(lat[int(len(lat) * 0.95)] * 1e3, 2),
        "p99_ms": round(lat[min(int(len(lat) * 0.99), len(lat) - 1)] * 1e3,
                        2),
    }


def run_benchmark(master: str, n: int = 1000, size: int = 1024,
                  concurrency: int = 16,
                  collection: str = "") -> dict:
    """Write n seeded files then read them all back hash-checked.

    Returns {"write": {...req/s, percentiles}, "read": {...},
    "corrupt": count}; raises nothing for per-request errors (they count
    as corrupt), so callers always get numbers.
    """
    rng = random.Random(42)
    blobs = [(i.to_bytes(8, "big") + rng.randbytes(max(size - 8, 0)))
             for i in range(n)]
    shas: dict[str, str] = {}
    shas_lock = threading.Lock()
    write_lat: list[float] = []
    errors = [0]

    def multipart(data: bytes, name: str) -> tuple[bytes, bytes]:
        body = (b'--benchBB\r\nContent-Disposition: form-data; '
                b'name="file"; filename="' + name.encode() + b'"\r\n'
                b'Content-Type: application/octet-stream\r\n\r\n'
                + data + b'\r\n--benchBB--\r\n')
        return body, b"multipart/form-data; boundary=benchBB"

    def write_worker(idx: int) -> None:
        try:
            mc = _Conn(master)
        except OSError:
            with shas_lock:
                errors[0] += len(range(idx, n, concurrency))
            return
        vcs: dict[str, _Conn] = {}
        local: list[tuple[str, str, float]] = []
        bad = 0
        for i in range(idx, n, concurrency):
            data = blobs[i]
            t0 = time.perf_counter()
            try:
                st, resp = mc.request(
                    b"GET /dir/assign"
                    + (f"?collection={collection}".encode()
                       if collection else b"")
                    + b" HTTP/1.1\r\nHost: m\r\n\r\n")
                a = json.loads(resp)
                fid, url = a["fid"], a["url"]
                auth = a.get("auth", "")
                vc = vcs.get(url)
                if vc is None:
                    vc = vcs[url] = _Conn(url)
                body, ctype = multipart(data, f"bench{i}")
                head = (f"POST /{fid} HTTP/1.1\r\nHost: v\r\n"
                        f"Content-Type: {ctype.decode()}\r\n"
                        + (f"Authorization: BEARER {auth}\r\n"
                           if auth else "")
                        + f"Content-Length: {len(body)}\r\n\r\n").encode()
                st, _ = vc.request(head, body)
                if st != 201:
                    bad += 1
                    continue
            except Exception:
                bad += 1
                continue
            dt = time.perf_counter() - t0
            local.append((fid, hashlib.sha256(data).hexdigest(), dt))
        with shas_lock:
            errors[0] += bad
            for fid, sha, dt in local:
                shas[fid] = sha
                write_lat.append(dt)
        mc.close()
        for vc in vcs.values():
            vc.close()

    t0 = time.perf_counter()
    threads = [threading.Thread(target=write_worker, args=(i,))
               for i in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    write_wall = time.perf_counter() - t0

    # read-back: lookup each volume once, then hash-checked GETs
    lookup_cache: dict[str, tuple[Optional[str], str]] = {}
    lookup_lock = threading.Lock()
    read_lat: list[float] = []
    corrupt = [0]
    all_fids = list(shas)

    def read_worker(idx: int) -> None:
        try:
            mc = _Conn(master)
        except OSError:
            with lookup_lock:
                corrupt[0] += len(range(idx, len(all_fids), concurrency))
            return
        vcs: dict[str, _Conn] = {}
        local_lat = []
        bad = 0
        for i in range(idx, len(all_fids), concurrency):
            fid = all_fids[i]
            t0 = time.perf_counter()
            try:
                vid = fid.split(",")[0]
                with lookup_lock:
                    loc = lookup_cache.get(vid)
                if loc is None:
                    st, resp = mc.request(
                        f"GET /dir/lookup?volumeId={vid} "
                        f"HTTP/1.1\r\nHost: m\r\n\r\n".encode())
                    body = json.loads(resp)
                    locs = body.get("locations", [])
                    loc = (locs[0]["url"] if locs else None,
                           body.get("auth", ""))
                    with lookup_lock:
                        lookup_cache[vid] = loc
                url, auth = loc
                if url is None:
                    bad += 1
                    continue
                vc = vcs.get(url)
                if vc is None:
                    vc = vcs[url] = _Conn(url)
                st, data = vc.request(
                    (f"GET /{fid} HTTP/1.1\r\nHost: v\r\n"
                     + (f"Authorization: BEARER {auth}\r\n" if auth else "")
                     + "\r\n").encode())
                if (st != 200
                        or hashlib.sha256(data).hexdigest() != shas[fid]):
                    bad += 1
                    continue
            except Exception:
                bad += 1
                continue
            local_lat.append(time.perf_counter() - t0)
        with lookup_lock:
            read_lat.extend(local_lat)
            corrupt[0] += bad
        mc.close()
        for vc in vcs.values():
            vc.close()

    t0 = time.perf_counter()
    threads = [threading.Thread(target=read_worker, args=(i,))
               for i in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    read_wall = time.perf_counter() - t0

    out = {
        "write": {"n": len(write_lat), "wall_s": round(write_wall, 2),
                  "req_s": round(len(write_lat) / write_wall, 1)
                  if write_wall else 0.0,
                  **(_percentiles(write_lat) if write_lat else {})},
        "read": {"n": len(read_lat), "wall_s": round(read_wall, 2),
                 "req_s": round(len(read_lat) / read_wall, 1)
                 if read_wall else 0.0,
                 **(_percentiles(read_lat) if read_lat else {})},
        "write_errors": errors[0],
        "corrupt": corrupt[0],
    }
    return out
