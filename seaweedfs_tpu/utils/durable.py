"""The atomic-replace recipe, extracted from cluster/raft.py.

Every persistence path that commits state by writing a temp file and
renaming it over the live one needs the SAME three barriers or a power
loss can undo it:

  1. fsync the temp file     — otherwise the rename can land while the
                               data pages are still dirty, surfacing an
                               empty or partial file after the crash;
  2. os.replace              — the atomic commit point;
  3. fsync the directory     — otherwise the rename itself is only in
                               the directory's dirty page and the OLD
                               file (or nothing) comes back.

raft._write_state carried the full dance since PR 4 because a vanished
vote breaks election safety; the `.ecm`/`.vif`/offset/snapshot writers
each re-invented the first two steps and skipped the third (or all
three). This module is the single home; the weedlint `atomic-replace`
rule holds every other `os.replace` in the tree to it.

The helpers are synchronous and block on fsync — event-loop callers
must run them in an executor (weedlint's blocking-call rules enforce
that side).
"""

from __future__ import annotations

import errno
import json
import os
from typing import Union

# filesystems that cannot fsync a directory at all answer one of these;
# a real write-barrier failure (EIO, ENOSPC, ...) is NOT in this set
_FSYNC_UNSUPPORTED = (errno.EINVAL, errno.ENOTSUP, errno.EBADF)


def fsync_dir(path: str) -> None:
    """fsync a directory so namespace ops (create/rename/unlink) inside
    it survive power loss. Only not-supported errnos are swallowed
    (exotic mounts with no directory barrier available — there is no
    stronger call to make there); a failing barrier (EIO) propagates:
    the caller must NOT report the rename as durable."""
    fd = os.open(path or ".", os.O_RDONLY)
    try:
        os.fsync(fd)
    except OSError as e:
        if e.errno not in _FSYNC_UNSUPPORTED:
            raise
    finally:
        os.close(fd)


def replace_atomic(tmp: str, dst: str, sync_file: bool = True) -> None:
    """fsync `tmp`, rename it over `dst`, fsync the directory.

    Pass sync_file=False only when the caller already fsynced the temp
    file through its own handle (e.g. right before closing it)."""
    if sync_file:
        fd = os.open(tmp, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    os.replace(tmp, dst)
    fsync_dir(os.path.dirname(dst))


def write_atomic(path: str, data: Union[bytes, str],
                 encoding: str = "utf-8") -> None:
    """Write `data` to `path` with full crash-consistency: temp file in
    the same directory, fsync, atomic rename, directory fsync. After
    this returns the new content is durable; a crash at any point leaves
    either the complete old file or the complete new one."""
    tmp = path + ".tmp"
    if isinstance(data, str):
        data = data.encode(encoding)
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    replace_atomic(tmp, path, sync_file=False)


def write_json_atomic(path: str, obj, **json_kwargs) -> None:
    """write_atomic for the many JSON sidecar/offset writers."""
    write_json = json.dumps(obj, **json_kwargs)
    write_atomic(path, write_json)
