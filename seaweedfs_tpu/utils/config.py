"""Two-tier configuration: per-command flags + TOML files with env override.

Mirrors the reference's Viper-based loader (weed/util/config.go:19-43):
TOML files are searched in ./, ~/.seaweedfs/, /etc/seaweedfs/ and any key
can be overridden by an environment variable named
``WEED_<SECTION>_<KEY>`` (dots become underscores, upper-cased), matching
weed/command/scaffold.go:18-22.
"""

from __future__ import annotations

import os
try:
    import tomllib
except ModuleNotFoundError:  # Python < 3.11: same API from tomli
    import tomli as tomllib
from typing import Any, Optional

SEARCH_PATHS = [".", os.path.expanduser("~/.seaweedfs"), "/etc/seaweedfs"]

ENV_PREFIX = "WEED_"


class Configuration:
    """A loaded TOML document with env-var override and dotted-key access."""

    def __init__(self, data: dict, name: str = ""):
        self._data = data
        self._name = name

    def get(self, key: str, default: Any = None) -> Any:
        env_key = ENV_PREFIX + key.replace(".", "_").replace("-", "_").upper()
        if env_key in os.environ:
            raw = os.environ[env_key]
            if isinstance(default, bool):
                return raw.lower() in ("1", "true", "yes", "on")
            if isinstance(default, int):
                try:
                    return int(raw)
                except ValueError:
                    return default
            if isinstance(default, float):
                try:
                    return float(raw)
                except ValueError:
                    return default
            return raw
        node: Any = self._data
        for part in key.split("."):
            if not isinstance(node, dict) or part not in node:
                return default
            node = node[part]
        return node

    def get_string(self, key: str, default: str = "") -> str:
        return str(self.get(key, default))

    def get_bool(self, key: str, default: bool = False) -> bool:
        return bool(self.get(key, default))

    def get_int(self, key: str, default: int = 0) -> int:
        return int(self.get(key, default))

    def section(self, key: str) -> "Configuration":
        val = self.get(key, {})
        return Configuration(val if isinstance(val, dict) else {}, self._name)

    def keys(self) -> list[str]:
        return list(self._data.keys())


def load_configuration(name: str, required: bool = False,
                       search_paths: Optional[list[str]] = None
                       ) -> Configuration:
    """Load ``<name>.toml`` from the standard search paths.

    Returns an empty Configuration (env overrides still apply) when the file
    is absent and not required, like LoadConfiguration
    (weed/util/config.go:19).
    """
    for d in (search_paths or SEARCH_PATHS):
        path = os.path.join(d, name + ".toml")
        if os.path.isfile(path):
            with open(path, "rb") as f:
                return Configuration(tomllib.load(f), name)
    if required:
        raise FileNotFoundError(
            f"missing required config {name}.toml in {search_paths or SEARCH_PATHS}")
    return Configuration({}, name)
