"""Tiny status pages (role of weed/server/*_ui/ templates)."""

from __future__ import annotations

import html
import json


def render_status(title: str, sections: dict) -> str:
    """One HTML page: a heading plus <pre> blocks per section."""
    parts = [
        "<!doctype html><html><head><meta charset='utf-8'>",
        f"<title>{html.escape(title)}</title>",
        "<style>body{font-family:monospace;margin:2em;background:#fafafa}"
        "h1{font-size:1.3em}h2{font-size:1.05em;margin-top:1.2em}"
        "pre{background:#fff;border:1px solid #ddd;padding:.8em;"
        "overflow-x:auto}</style></head><body>",
        f"<h1>{html.escape(title)}</h1>",
    ]
    for name, value in sections.items():
        body = (value if isinstance(value, str)
                else json.dumps(value, indent=1, default=str))
        parts.append(f"<h2>{html.escape(name)}</h2>"
                     f"<pre>{html.escape(body)}</pre>")
    parts.append("</body></html>")
    return "".join(parts)
