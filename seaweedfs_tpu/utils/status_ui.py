"""Server status pages (role of weed/server/master_ui/templates.go,
volume_server_ui/templates.go and the filer UI).

The reference ships real HTML status pages per server with volume and
EC-shard tables; render_status produces the same kind of page without a
template engine: a header bar, key/value summary cards, and striped
tables for list-shaped sections. Sections map:

  str                              -> <pre>
  {"columns": [...], "rows": [...]} -> <table>
  list[dict]                       -> <table> (columns = union of keys)
  dict                             -> key/value card table
"""

from __future__ import annotations

import html
import json

_STYLE = (
    "body{font-family:-apple-system,'Segoe UI',sans-serif;margin:0;"
    "background:#f4f5f7;color:#172b4d}"
    ".bar{background:#0747a6;color:#fff;padding:.8em 1.4em;"
    "font-size:1.15em;font-weight:600}"
    ".bar small{opacity:.75;font-weight:400;margin-left:.8em}"
    ".wrap{padding:1.2em 1.4em;max-width:1100px}"
    "h2{font-size:.95em;text-transform:uppercase;letter-spacing:.04em;"
    "color:#5e6c84;margin:1.4em 0 .4em}"
    "table{border-collapse:collapse;width:100%;background:#fff;"
    "box-shadow:0 1px 2px rgba(9,30,66,.12);font-size:.9em}"
    "th{background:#fafbfc;text-align:left;color:#5e6c84;"
    "font-weight:600}"
    "th,td{padding:.45em .8em;border-bottom:1px solid #ebecf0;"
    "font-variant-numeric:tabular-nums}"
    "tr:nth-child(even) td{background:#fafbfc}"
    "pre{background:#fff;border:1px solid #ebecf0;padding:.8em;"
    "overflow-x:auto;box-shadow:0 1px 2px rgba(9,30,66,.12)}"
    ".kv td:first-child{color:#5e6c84;width:14em}"
)


def _cell(v) -> str:
    if isinstance(v, float):
        v = round(v, 3)
    if isinstance(v, (dict, list)):
        v = json.dumps(v, default=str)
    return html.escape(str(v))


def _table(columns, rows) -> str:
    head = "".join(f"<th>{_cell(c)}</th>" for c in columns)
    body = "".join(
        "<tr>" + "".join(f"<td>{_cell(c)}</td>" for c in row) + "</tr>"
        for row in rows)
    return f"<table><tr>{head}</tr>{body}</table>"


def _section_html(value) -> str:
    if isinstance(value, str):
        return f"<pre>{html.escape(value)}</pre>"
    if isinstance(value, dict) and "columns" in value and "rows" in value:
        return _table(value["columns"], value["rows"])
    if (isinstance(value, list) and value
            and all(isinstance(r, dict) for r in value)):
        cols: list = []
        for r in value:
            for k in r:
                if k not in cols:
                    cols.append(k)
        return _table(cols, [[r.get(c, "") for c in cols]
                             for r in value])
    if isinstance(value, dict):
        rows = "".join(f"<tr><td>{_cell(k)}</td><td>{_cell(v)}</td></tr>"
                       for k, v in value.items())
        return f"<table class='kv'>{rows}</table>"
    return (f"<pre>{html.escape(json.dumps(value, indent=1, default=str))}"
            "</pre>")


def render_status(title: str, sections: dict, subtitle: str = "") -> str:
    parts = [
        "<!doctype html><html><head><meta charset='utf-8'>",
        f"<title>{html.escape(title)}</title>",
        f"<style>{_STYLE}</style></head><body>",
        f"<div class='bar'>{html.escape(title)}"
        + (f"<small>{html.escape(subtitle)}</small>" if subtitle else "")
        + "</div><div class='wrap'>",
    ]
    for name, value in sections.items():
        parts.append(f"<h2>{html.escape(name)}</h2>")
        parts.append(_section_html(value))
    parts.append("</div></body></html>")
    return "".join(parts)
