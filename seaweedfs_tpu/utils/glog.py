"""Leveled, vmodule-filtered logging (glog-style).

The reference vendors a glog fork (weed/glog/glog.go:283): verbosity levels
``V(0..4)`` gated by a global ``-v`` flag plus per-file overrides via
``-vmodule=file=N``. This is the same model on top of the stdlib logging
machinery, with optional rotating file output.
"""

from __future__ import annotations

import logging
import logging.handlers
import os
import sys
import threading
import time

_lock = threading.Lock()
_verbosity = 0
_vmodule: dict[str, int] = {}
_logger = logging.getLogger("seaweedfs_tpu")
_configured = False


class _GlogFormatter(logging.Formatter):
    """``Lmmdd hh:mm:ss.uuuuuu threadid file:line] msg`` like glog."""

    def format(self, record: logging.LogRecord) -> str:
        t = time.localtime(record.created)
        micros = int((record.created % 1) * 1e6)
        letter = {"DEBUG": "D", "INFO": "I", "WARNING": "W",
                  "ERROR": "E", "CRITICAL": "F"}.get(record.levelname, "I")
        out = (f"{letter}{t.tm_mon:02d}{t.tm_mday:02d} "
               f"{t.tm_hour:02d}:{t.tm_min:02d}:{t.tm_sec:02d}.{micros:06d} "
               f"{record.thread % 100000:5d} "
               f"{os.path.basename(record.pathname)}:{record.lineno}] "
               f"{record.getMessage()}")
        if record.exc_info and record.exc_info[0] is not None:
            # dropping exc_info here loses every handler traceback
            # (aiohttp logs 500s through this path)
            out += "\n" + self.formatException(record.exc_info)
        return out


def setup(verbosity: int = 0, vmodule: str = "", log_file: str = "",
          max_bytes: int = 64 << 20, backup_count: int = 5) -> None:
    """Configure global verbosity, per-file overrides, and outputs.

    vmodule syntax: ``file1=2,file2=4`` (basename without .py).
    """
    global _verbosity, _configured
    with _lock:
        _verbosity = verbosity
        _vmodule.clear()
        for pair in filter(None, vmodule.split(",")):
            mod, _, lvl = pair.partition("=")
            try:
                _vmodule[mod.strip()] = int(lvl)
            except ValueError:
                pass
        # configure the ROOT logger so every module logger ("master",
        # "volume", "filer", ...) lands in the same handlers/files — the
        # servers don't log through the glog API directly
        root = logging.getLogger()
        for h in list(root.handlers):
            root.removeHandler(h)
        fmt = _GlogFormatter()
        sh = logging.StreamHandler(sys.stderr)
        sh.setFormatter(fmt)
        root.addHandler(sh)
        if log_file:
            fh = logging.handlers.RotatingFileHandler(
                log_file, maxBytes=max_bytes, backupCount=backup_count)
            fh.setFormatter(fmt)
            root.addHandler(fh)
        root.setLevel(logging.DEBUG if verbosity > 0 else logging.INFO)
        _logger.setLevel(logging.DEBUG)
        _logger.propagate = True
        _configured = True


def _ensure() -> None:
    if not _configured:
        setup(int(os.environ.get("WEED_V", "0")))


def v(level: int) -> bool:
    """True when messages at this verbosity should be emitted (glog V(n))."""
    _ensure()
    frame = sys._getframe(1)
    mod = os.path.splitext(os.path.basename(frame.f_code.co_filename))[0]
    return level <= _vmodule.get(mod, _verbosity)


def vlog(level: int, msg: str, *args) -> None:
    _ensure()
    frame = sys._getframe(1)
    mod = os.path.splitext(os.path.basename(frame.f_code.co_filename))[0]
    if level <= _vmodule.get(mod, _verbosity):
        _logger.info(msg, *args, stacklevel=2)


def info(msg: str, *args) -> None:
    _ensure()
    _logger.info(msg, *args, stacklevel=2)


def warning(msg: str, *args) -> None:
    _ensure()
    _logger.warning(msg, *args, stacklevel=2)


def error(msg: str, *args) -> None:
    _ensure()
    _logger.error(msg, *args, stacklevel=2)


def fatal(msg: str, *args) -> None:
    _ensure()
    _logger.critical(msg, *args, stacklevel=2)
    raise SystemExit(255)


def watch_future(fut, what: str):
    """The blessed error path for a deliberately fire-and-forget future
    (asyncio or concurrent.futures): retrieves the exception in a done
    callback — so a failed background write is logged with context
    instead of surfacing as asyncio's anonymous 'exception was never
    retrieved' at GC time — and returns the future so the caller can
    keep the reference weedlint's task-leak rule requires."""
    def _done(f):
        try:
            exc = f.exception()
        except BaseException:       # cancelled: nothing to report
            return
        if exc is not None:
            error("background %s failed: %s", what, exc)

    fut.add_done_callback(_done)
    return fut
