"""Version-compat shims for the jax APIs the EC plane leans on.

One home for the cross-version glue so production modules
(parallel/mesh_coder.py, the sharded kernel demo) never reach into each
other's internals for it. Everything here imports jax lazily-at-call —
importing this module costs nothing in processes that never touch a
device.
"""

from __future__ import annotations


def shard_map_compat(step, mesh, in_specs, out_specs):
    """jax.shard_map across jax versions: 0.4.x carries it only under
    jax.experimental with the check_rep spelling; the top-level API
    first kept check_rep, then renamed it to check_vma. Replication
    checks are off either way — pallas_call outputs carry no vma/rep
    metadata."""
    import jax
    if hasattr(jax, "shard_map"):
        try:
            return jax.shard_map(step, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False)
        except TypeError:  # top-level but pre-rename: check_rep era
            return jax.shard_map(step, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_rep=False)
    from jax.experimental.shard_map import shard_map as sm
    return sm(step, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
              check_rep=False)
