"""Per-fid write/read JWTs (HS256), master-signed, volume-server-verified.

Mirrors weed/security/jwt.go: the master signs a short-lived token binding a
specific file id; the volume server requires it on writes (and reads when a
read key is configured). Claims: ``fid`` plus standard ``exp``. Keys come
from security.toml [jwt.signing] / [jwt.signing.read] (scaffold.go security
section), loaded via utils.config.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time


class JwtError(Exception):
    pass


def _b64(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _unb64(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


_HEADER = _b64(json.dumps({"alg": "HS256", "typ": "JWT"},
                          separators=(",", ":")).encode())


def GenJwt(signing_key: str, expires_seconds: int, fid: str) -> str:
    """Sign a token for one file id; empty key means auth disabled -> ''."""
    if not signing_key:
        return ""
    claims = {"fid": fid}
    if expires_seconds > 0:
        claims["exp"] = int(time.time()) + expires_seconds
    payload = _b64(json.dumps(claims, separators=(",", ":")).encode())
    msg = f"{_HEADER}.{payload}"
    sig = hmac.new(signing_key.encode(), msg.encode(), hashlib.sha256).digest()
    return f"{msg}.{_b64(sig)}"


def DecodeJwt(signing_key: str, token: str) -> dict:
    """Verify signature + expiry; returns the claims dict or raises JwtError."""
    parts = token.split(".")
    if len(parts) != 3:
        raise JwtError("malformed token")
    msg = f"{parts[0]}.{parts[1]}"
    want = hmac.new(signing_key.encode(), msg.encode(),
                    hashlib.sha256).digest()
    if not hmac.compare_digest(want, _unb64(parts[2])):
        raise JwtError("bad signature")
    try:
        claims = json.loads(_unb64(parts[1]))
    except Exception as e:
        raise JwtError(f"bad claims: {e}") from e
    exp = claims.get("exp")
    if exp is not None and time.time() > exp:
        raise JwtError("token expired")
    return claims


def VerifyFid(signing_key: str, token: str, fid: str) -> None:
    """Volume-server side check: token must be valid and bound to this fid
    (or to no fid, which the reference accepts for legacy tokens)."""
    claims = DecodeJwt(signing_key, token)
    bound = claims.get("fid", "")
    if bound and bound != fid:
        raise JwtError(f"token bound to {bound}, not {fid}")
