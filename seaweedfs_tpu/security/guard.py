"""Request guard: IP whitelist + JWT enforcement for HTTP handlers.

Mirrors weed/security/guard.go:53 — a handler wrapper that admits requests
from whitelisted IPs/CIDRs (empty whitelist = open) and, when a signing key
is set, requires a valid JWT on guarded mutation endpoints.
"""

from __future__ import annotations

import ipaddress
from typing import Optional

from . import jwt as jwt_mod


class Guard:
    def __init__(self, whitelist: Optional[list[str]] = None,
                 signing_key: str = "", expires_seconds: int = 10,
                 read_signing_key: str = "",
                 read_expires_seconds: int = 60):
        self.signing_key = signing_key
        self.expires_seconds = expires_seconds
        self.read_signing_key = read_signing_key
        self.read_expires_seconds = read_expires_seconds
        self._nets: list[ipaddress._BaseNetwork] = []
        self._ips: set[str] = set()
        for item in (whitelist or []):
            item = item.strip()
            if not item:
                continue
            if "/" in item:
                self._nets.append(ipaddress.ip_network(item, strict=False))
            else:
                self._ips.add(item)

    @property
    def is_open(self) -> bool:
        return not (self._ips or self._nets or self.signing_key)

    def check_whitelist(self, remote_ip: str) -> bool:
        if not self._ips and not self._nets:
            return True
        if remote_ip in self._ips:
            return True
        try:
            addr = ipaddress.ip_address(remote_ip)
        except ValueError:
            return False
        return any(addr in net for net in self._nets)

    def sign_write(self, fid: str) -> str:
        return jwt_mod.GenJwt(self.signing_key, self.expires_seconds, fid)

    def sign_read(self, fid: str) -> str:
        return jwt_mod.GenJwt(self.read_signing_key,
                              self.read_expires_seconds, fid)

    def verify_write(self, token: str, fid: str) -> Optional[str]:
        """None if ok, error string otherwise. No signing key -> open."""
        if not self.signing_key:
            return None
        if not token:
            return "missing jwt"
        try:
            jwt_mod.VerifyFid(self.signing_key, token, fid)
        except jwt_mod.JwtError as e:
            return str(e)
        return None

    def verify_read(self, token: str, fid: str) -> Optional[str]:
        if not self.read_signing_key:
            return None
        if not token:
            return "missing read jwt"
        try:
            jwt_mod.VerifyFid(self.read_signing_key, token, fid)
        except jwt_mod.JwtError as e:
            return str(e)
        return None


def token_from_request(headers, query) -> str:
    """Authorization: BEARER <t> header or ?jwt= query param
    (weed/security/jwt.go GetJwt)."""
    auth = headers.get("Authorization", "")
    if auth.lower().startswith("bearer "):
        return auth[7:].strip()
    return query.get("jwt", "")
