from .jwt import GenJwt, DecodeJwt, JwtError  # noqa: F401
from .guard import Guard  # noqa: F401
