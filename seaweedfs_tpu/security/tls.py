"""TLS/mTLS for the HTTP servers and the gRPC plane.

Role of the reference's security layer (weed/security/tls.go:15-70): when
security.toml carries a [tls] section, every server can terminate TLS and
— with verify_client — demand a client certificate signed by the
configured CA (mutual TLS). The JWT/whitelist guard plus TLS together form
the reference's full security envelope.

security.toml keys (scaffold `security` template):

    [tls]
    ca_file = "/etc/seaweedfs/ca.crt"
    cert_file = "/etc/seaweedfs/server.crt"
    key_file = "/etc/seaweedfs/server.key"
    verify_client = true     # mTLS: reject clients without a CA-signed cert
    https = false            # additionally terminate TLS on the HTTP ports

With certs configured, the gRPC plane (all intra-cluster RPC) is always
secured — every internal dial goes through pb.rpc.dial/aio_dial which
pick up these certs. `https` additionally wraps the HTTP listeners; the
HTTP data path between cluster nodes stays plaintext unless it is on
(matching the reference, whose TLS layer covers gRPC only).
"""

from __future__ import annotations

import ssl
from dataclasses import dataclass
from typing import Optional


@dataclass(frozen=True)
class TlsConfig:
    ca_file: str = ""
    cert_file: str = ""
    key_file: str = ""
    verify_client: bool = False
    https: bool = False

    @property
    def enabled(self) -> bool:
        return bool(self.cert_file and self.key_file)

    @classmethod
    def from_config(cls, cfg) -> "TlsConfig":
        """cfg: utils.config Configuration (security.toml)."""
        if cfg is None:
            return cls()
        return cls(
            ca_file=cfg.get_string("tls.ca_file", ""),
            cert_file=cfg.get_string("tls.cert_file", ""),
            key_file=cfg.get_string("tls.key_file", ""),
            verify_client=cfg.get_bool("tls.verify_client", False),
            https=cfg.get_bool("tls.https", False),
        )

    # --- HTTP (aiohttp TCPSite ssl_context) ---
    def server_ssl_context(self) -> Optional[ssl.SSLContext]:
        if not self.enabled or not self.https:
            return None
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(self.cert_file, self.key_file)
        if self.verify_client:
            ctx.verify_mode = ssl.CERT_REQUIRED
            ctx.load_verify_locations(self.ca_file)
        return ctx

    def client_ssl_context(self) -> Optional[ssl.SSLContext]:
        """For intra-cluster clients (peers): trusts the cluster CA and
        presents this node's own certificate when mTLS is on."""
        if not self.enabled:
            return None
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        if self.ca_file:
            ctx.load_verify_locations(self.ca_file)
            ctx.check_hostname = False  # cluster nodes dial by ip:port
        ctx.load_cert_chain(self.cert_file, self.key_file)
        return ctx

    # --- gRPC (secure port / channel credentials) ---
    def grpc_server_credentials(self):
        if not self.enabled:
            return None
        import grpc
        with open(self.key_file, "rb") as f:
            key = f.read()
        with open(self.cert_file, "rb") as f:
            cert = f.read()
        root = None
        if self.ca_file:
            with open(self.ca_file, "rb") as f:
                root = f.read()
        return grpc.ssl_server_credentials(
            [(key, cert)], root_certificates=root,
            require_client_auth=self.verify_client)

    def grpc_channel_credentials(self):
        if not self.enabled:
            return None
        import grpc
        root = None
        if self.ca_file:
            with open(self.ca_file, "rb") as f:
                root = f.read()
        with open(self.key_file, "rb") as f:
            key = f.read()
        with open(self.cert_file, "rb") as f:
            cert = f.read()
        return grpc.ssl_channel_credentials(
            root_certificates=root, private_key=key, certificate_chain=cert)


def load_tls_config() -> TlsConfig:
    from ..utils.config import load_configuration
    return TlsConfig.from_config(load_configuration("security"))
