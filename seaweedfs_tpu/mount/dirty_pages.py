"""Write-back cache intervals for the mount layer.

Mirrors weed/filesys/dirty_page_interval.go: written byte ranges are kept
as a list of non-overlapping intervals where NEWER writes win over older
overlapping data; contiguous runs are flushed as chunks. The interval
algebra here is the pure-logic core the reference unit-tests heavily
(dirty_page_interval_test.go) — kernel FUSE glue stays thin above it.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


@dataclass
class Interval:
    start: int          # inclusive byte offset
    data: bytes

    @property
    def stop(self) -> int:
        return self.start + len(self.data)


class ContinuousIntervals:
    """Non-overlapping, sorted intervals; AddInterval semantics of
    weed/filesys/dirty_page_interval.go:60 (new data overwrites old)."""

    def __init__(self):
        self.intervals: list[Interval] = []

    def add_interval(self, data: bytes, offset: int) -> None:
        if not data:
            return
        new = Interval(offset, bytes(data))
        out: list[Interval] = []
        for iv in self.intervals:
            if iv.stop <= new.start or iv.start >= new.stop:
                out.append(iv)
                continue
            # overlap: keep the non-overlapped parts of the OLD interval
            if iv.start < new.start:
                out.append(Interval(iv.start,
                                    iv.data[:new.start - iv.start]))
            if iv.stop > new.stop:
                out.append(Interval(new.stop,
                                    iv.data[new.stop - iv.start:]))
        out.append(new)
        out.sort(key=lambda i: i.start)
        # coalesce adjacent runs so flushes produce few large chunks
        merged: list[Interval] = []
        for iv in out:
            if merged and merged[-1].stop == iv.start:
                merged[-1] = Interval(merged[-1].start,
                                      merged[-1].data + iv.data)
            else:
                merged.append(iv)
        self.intervals = merged

    def total_size(self) -> int:
        return max((iv.stop for iv in self.intervals), default=0)

    def buffered_bytes(self) -> int:
        return sum(len(iv.data) for iv in self.intervals)

    def read_data_at(self, size: int, offset: int) -> bytes:
        """Assemble dirty data over [offset, offset+size); gaps are zeroes
        only where some later interval exists (reads merge with remote
        content above this layer)."""
        buf = bytearray(size)
        mask = bytearray(size)
        for iv in self.intervals:
            lo = max(iv.start, offset)
            hi = min(iv.stop, offset + size)
            if lo >= hi:
                continue
            buf[lo - offset:hi - offset] = iv.data[lo - iv.start:
                                                   hi - iv.start]
            for i in range(lo - offset, hi - offset):
                mask[i] = 1
        return bytes(buf), bytes(mask)

    def pop_largest_contiguous(self) -> Optional[Interval]:
        """Remove and return the largest interval (saveExistingLargestPage
        in dirty_page.go — flushed as one chunk when memory pressure
        demands)."""
        if not self.intervals:
            return None
        largest = max(self.intervals, key=lambda i: len(i.data))
        self.intervals.remove(largest)
        return largest

    def pop_all(self) -> list[Interval]:
        out, self.intervals = self.intervals, []
        return out
