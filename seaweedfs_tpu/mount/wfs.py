"""WFS: the mount's virtual filesystem over a filer.

Mirrors weed/filesys/wfs.go + file.go + dir.go + filehandle.go: a
path-based VFS with open-handle registry and write-back dirty pages.
Kernel FUSE glue (fuse_mount.py) calls these methods 1:1; every operation
here is also drictly testable without a kernel, which is exactly how the
reference tests its mount internals (pure-logic tests only,
dirty_page_interval_test.go / fscache_test.go).

Write path (wfs_write.go + dirty_page.go): writes land in per-handle
ContinuousIntervals; when buffered bytes exceed the chunk size the largest
run flushes early; flush()/release() uploads the rest — each run becomes
one chunk via filer-proxied assign + volume server POST — then the entry
is saved with the merged chunk list.
"""

from __future__ import annotations

import http.client
import json
import os
import threading
import time
import urllib.error
import urllib.parse
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

from ..cache import Singleflight, TieredChunkCache, TTLCache, shared_pool
from ..filer.assign_lease import AssignLeasePool
from .dirty_pages import ContinuousIntervals
from .meta_cache import MetaCache


class FuseError(OSError):
    def __init__(self, errno_: int, msg: str = ""):
        super().__init__(errno_, msg)


def _norm(path: str) -> str:
    path = "/" + path.strip("/")
    while "//" in path:
        path = path.replace("//", "/")
    return path


class FilerClient:
    """Thin sync HTTP client for the filer's meta + data endpoints.
    Intra-cluster requests ride pooled keep-alive connections; direct
    chunk reads go through a local chunk cache with singleflight so N
    threads re-reading one hot chunk cost one volume-server fetch."""

    def __init__(self, filer_url: str,
                 chunk_cache: Optional[TieredChunkCache] = None):
        self.filer = filer_url.rstrip("/")
        self._pool = shared_pool()
        self._vid_cache = TTLCache(ttl=60.0)
        self.chunk_cache = chunk_cache if chunk_cache is not None \
            else TieredChunkCache(max_bytes=32 * 1024 * 1024)
        self._read_flight = Singleflight("mount.read_chunk")
        # set after the first 401: subsequent chunk reads fetch the read
        # token up front instead of paying a guaranteed-401 round trip
        self._read_auth_needed = False
        self._fid_auth: dict[str, tuple[str, float]] = {}
        # bulk fid lease over the filer's assign proxy: flush fan-outs
        # draw N write targets from one /__meta__/assign?count=N trip
        self._lease = AssignLeasePool(self._assign_fetch)

    def _get_json(self, path_qs: str) -> Optional[dict]:
        r = self._pool.request("GET", f"http://{self.filer}{path_qs}",
                               timeout=60)
        if r.status == 404:
            return None
        if r.status >= 400:
            raise urllib.error.HTTPError(
                f"http://{self.filer}{path_qs}", r.status, "filer error",
                None, None)
        return r.json()

    def lookup(self, path: str) -> Optional[dict]:
        return self._get_json("/__meta__/lookup?"
                              + urllib.parse.urlencode({"path": path}))

    def list_dir(self, path: str, limit: int = 100000) -> list[dict]:
        out = self._get_json("/__meta__/list?" + urllib.parse.urlencode(
            {"dir": path, "limit": str(limit)}))
        return out.get("entries", []) if out else []

    def _post(self, path_qs: str, body: Optional[bytes] = None) -> None:
        headers = {"Content-Type": "application/json"} if body else {}
        r = self._pool.request("POST", f"http://{self.filer}{path_qs}",
                               body=body, headers=headers, timeout=60)
        if r.status >= 400:
            raise IOError(f"POST {path_qs}: HTTP {r.status} "
                          f"{r.data[:200]!r}")

    def create_entry(self, entry: dict, free_old_chunks: bool = True) -> None:
        self._post("/__meta__/create_entry",
                   json.dumps({"entry": entry,
                               "free_old_chunks": free_old_chunks}).encode())

    def update_entry(self, entry: dict) -> None:
        self._post("/__meta__/update_entry",
                   json.dumps({"entry": entry}).encode())

    def delete(self, path: str, recursive: bool = False) -> None:
        self._post("/__meta__/delete",
                   json.dumps({"path": path,
                               "recursive": recursive}).encode())

    def rename(self, old: str, new: str) -> None:
        self._post(urllib.parse.quote(old) + "?"
                   + urllib.parse.urlencode({"mv.to": new}))

    def _assign_fetch(self, params: dict, count: int) -> dict:
        """Lease refill: one real assignment through the filer proxy
        (?count=N reaches the master's bulk path)."""
        p = dict(params)
        if count > 1:
            p["count"] = str(count)
        qs = urllib.parse.urlencode(p)
        out = self._get_json("/__meta__/assign" + (f"?{qs}" if qs else ""))
        if out is None or "error" in out:
            raise IOError(f"assign failed: {out}")
        return out

    def assign(self, collection: str = "", replication: str = "",
               ttl: str = "") -> dict:
        """One write target from the bulk lease (zero round trips while
        the lease is live)."""
        return self._lease.get(collection, replication, ttl)

    def assign_direct(self, collection: str = "", replication: str = "",
                      ttl: str = "") -> dict:
        """A genuinely fresh master assignment: direct=true makes the
        filer proxy bypass ITS lease pool too (which may still hold fids
        on the volume whose failure triggered this retry)."""
        params = {k: v for k, v in (("collection", collection),
                                    ("replication", replication),
                                    ("ttl", ttl),
                                    ("direct", "true")) if v}
        return self._assign_fetch(params, 1)

    def upload_chunk(self, assign: dict, data: bytes) -> None:
        headers = {"Content-Type": "application/octet-stream"}
        if assign.get("auth"):
            headers["Authorization"] = f"BEARER {assign['auth']}"
        try:
            r = self._pool.request(
                "POST", f"http://{assign['url']}/{assign['fid']}",
                body=data, headers=headers, timeout=300)
        except (OSError, http.client.HTTPException):
            # conn refused / breaker open: this volume is a bad target
            self._lease.invalidate(assign["fid"])
            raise
        if r.status in (404, 409):
            # volume gone or sealed read-only: the lease is stale
            self._lease.invalidate(assign["fid"])
        if r.status >= 300:
            raise IOError(f"upload {assign['fid']}: HTTP {r.status}")

    def delete_blob(self, assign: dict) -> None:
        """Best-effort delete of one assigned blob (the retry path's
        reap: a failed POST may still have landed on the server)."""
        headers = ({"Authorization": f"BEARER {assign['auth']}"}
                   if assign.get("auth") else {})
        try:
            self._pool.request(
                "DELETE", f"http://{assign['url']}/{assign['fid']}",
                headers=headers, timeout=30)
        except (OSError, http.client.HTTPException):
            pass

    def read_range(self, path: str, offset: int, size: int) -> bytes:
        r = self._pool.request(
            "GET", f"http://{self.filer}" + urllib.parse.quote(path),
            headers={"Range": f"bytes={offset}-{offset + size - 1}"},
            timeout=300)
        if r.status in (404, 416):
            return b""
        if r.status >= 400:
            raise IOError(f"read {path}: HTTP {r.status}")
        data = r.data
        if r.status == 200:
            data = data[offset:offset + size]
        return data

    def lookup_volume(self, vid: int) -> list[str]:
        cached = self._vid_cache.get(vid)
        if cached:
            return cached
        out = self._get_json(f"/__meta__/lookup_volume?volumeId={vid}")
        urls = [loc["url"] for loc in (out or {}).get("locations", [])]
        if urls:
            self._vid_cache.put(vid, urls)
        return urls

    def _cache_fid_auth(self, fid: str, auth: str) -> None:
        """Bounded short-TTL token cache: fids are unbounded on a
        long-lived mount, so stale entries are swept when the cache
        grows instead of leaking forever."""
        now = time.time()
        if len(self._fid_auth) >= 4096:
            self._fid_auth = {f: (a, ts) for f, (a, ts)
                              in self._fid_auth.items() if now - ts < 30.0}
            if len(self._fid_auth) >= 4096:
                self._fid_auth.clear()
        self._fid_auth[fid] = (auth, now)

    def lookup_fid_with_auth(self, fid: str) -> tuple[list[str], str]:
        """Per-fid lookup via the filer — returns (urls, read_jwt); the
        filer passes through the master's read token when a read key is
        configured."""
        out = self._get_json("/__meta__/lookup_volume?"
                             + urllib.parse.urlencode({"fileId": fid}))
        urls = [loc["url"] for loc in (out or {}).get("locations", [])]
        return urls, (out or {}).get("auth", "")

    def read_chunk(self, fid: str, offset_in_chunk: int, size: int) -> bytes:
        """Fetch a sub-range of one chunk straight from a volume server —
        used for handle-local chunks the filer doesn't know about yet.
        Cached per view, and N concurrent readers of one cold view
        coalesce into one backend fetch."""
        key = f"{fid}@{offset_in_chunk}:{size}"
        cached = self.chunk_cache.get(key)
        if cached is not None:
            return cached

        def fetch() -> bytes:
            data = self._read_chunk_backend(fid, offset_in_chunk, size)
            self.chunk_cache.put(key, data)
            return data

        return self._read_flight.do(key, fetch)

    def _read_chunk_backend(self, fid: str, offset_in_chunk: int,
                            size: int) -> bytes:
        """One volume-server round trip (pooled); falls back to a per-fid
        read-jwt lookup on 401."""
        vid = int(fid.split(",")[0])
        last: Optional[Exception] = None
        urls, auth = self.lookup_volume(vid), ""
        if self._read_auth_needed:
            cached = self._fid_auth.get(fid)
            if cached and time.time() - cached[1] < 30.0:
                auth = cached[0]
            else:
                fid_urls, auth = self.lookup_fid_with_auth(fid)
                urls = fid_urls or urls
                if auth:
                    self._cache_fid_auth(fid, auth)
        for attempt in range(2):
            denied = False
            for url in urls:
                headers = {"Range": f"bytes={offset_in_chunk}-"
                                    f"{offset_in_chunk + size - 1}"}
                if auth:
                    headers["Authorization"] = f"BEARER {auth}"
                try:
                    r = self._pool.request("GET", f"http://{url}/{fid}",
                                           headers=headers, timeout=300)
                except Exception as e:
                    last = e
                    self._vid_cache.pop(vid)
                    continue
                if r.status in (200, 206):
                    data = r.data
                    if r.status == 200:
                        data = data[offset_in_chunk:
                                    offset_in_chunk + size]
                    return data
                last = IOError(f"{url}/{fid}: HTTP {r.status}")
                if r.status == 401 and attempt == 0:
                    denied = True
                    break  # acquire a read token and retry
            if denied:
                self._read_auth_needed = True
                fid_urls, auth = self.lookup_fid_with_auth(fid)
                urls = fid_urls or urls
                if auth:
                    self._cache_fid_auth(fid, auth)
                continue
            break
        raise IOError(f"read chunk {fid}: {last}")


class FileHandle:
    """One open file: read-through + write-back dirty pages
    (weed/filesys/filehandle.go + dirty_page.go)."""

    def __init__(self, wfs: "WFS", path: str, entry: dict,
                 flags_write: bool = True):
        self.wfs = wfs
        self.path = path
        self.entry = entry
        self.dirty = ContinuousIntervals()
        self.flags_write = flags_write
        self._lock = threading.Lock()
        self.ref_count = 1
        # True while the handle holds early-flushed chunks the filer
        # doesn't know about yet; reads then use the handle's chunk view
        self._has_local_chunks = False

    # --- size helpers ---
    def _entry_size(self) -> int:
        chunks = self.entry.get("chunks", [])
        return max((c["offset"] + c["size"] for c in chunks), default=0)

    def size(self) -> int:
        return max(self._entry_size(), self.dirty.total_size())

    # --- io ---
    def write(self, data: bytes, offset: int) -> int:
        if not self.flags_write:
            raise FuseError(9, "handle not open for write")  # EBADF
        with self._lock:
            self.dirty.add_interval(data, offset)
            if self.dirty.buffered_bytes() >= self.wfs.chunk_size:
                self._flush_largest_locked()
        return len(data)

    def read(self, size: int, offset: int) -> bytes:
        with self._lock:
            dirty_data, mask = self.dirty.read_data_at(size, offset)
        file_size = self.size()
        if offset >= file_size:
            return b""
        size = min(size, file_size - offset)
        if all(mask[:size]):
            return dirty_data[:size]
        # Mid-write (handle holds early-flushed chunks the filer doesn't
        # know about yet): serve non-dirty ranges from the handle's own
        # chunk list so read-your-writes holds between an auto-flush and
        # flush() without persisting intermediate entries cluster-wide
        # (the reference likewise reads via the handle's chunk view,
        # weed/filesys/filehandle.go). Otherwise read through the filer
        # path, which stays fresh w.r.t. writes by other clients.
        buf = bytearray(size)
        if self._entry_size() > offset:
            if self._has_local_chunks:
                from ..filer.chunks import FileChunk as FC, read_plan
                chunks = [FC.from_dict(c)
                          for c in self.entry.get("chunks", [])]
                for view in read_plan(chunks, offset, size):
                    data = self.wfs.client.read_chunk(
                        view.fid, view.offset_in_chunk, view.size)
                    pos = view.logic_offset - offset
                    buf[pos:pos + len(data)] = data
            else:
                remote = self.wfs.client.read_range(self.path, offset, size)
                buf[:len(remote)] = remote
        for i in range(size):
            if mask[i]:
                buf[i] = dirty_data[i]
        return bytes(buf)

    # --- flush ---
    def _upload_interval(self, iv) -> dict:
        client = self.wfs.client
        a = client.assign(self.wfs.collection, self.wfs.replication)
        try:
            client.upload_chunk(a, iv.data)
        except (OSError, http.client.HTTPException):
            # the leased target failed (upload_chunk already invalidated
            # the lease): best-effort reap of the fid (the POST may have
            # landed before the error) and retry once against a fresh
            # direct assignment — a new fid, so the re-POST can't
            # double-write
            client.delete_blob(a)
            a = client.assign_direct(self.wfs.collection,
                                     self.wfs.replication)
            client.upload_chunk(a, iv.data)
        return {"fid": a["fid"], "offset": iv.start, "size": len(iv.data),
                "mtime": time.time_ns(), "etag": ""}

    def _upload_intervals(self, ivs: list) -> tuple[list[dict],
                                                    Optional[Exception]]:
        """Fan dirty-run uploads through the mount's bounded upload
        window (same WEED_FILER_UPLOAD_CONCURRENCY knob as the filer's
        pipelined PUT). Returns (successful chunks in interval order,
        first error): the caller must KEEP the successes even on partial
        failure — the intervals are already popped from the dirty set,
        so dropping a landed chunk would silently lose its bytes."""
        if len(ivs) <= 1:
            try:
                return [self._upload_interval(iv) for iv in ivs], None
            except Exception as e:
                return [], e
        futures = [self.wfs.flush_pool.submit(self._upload_interval, iv)
                   for iv in ivs]
        results, first_err = [], None
        for f in futures:
            try:
                results.append(f.result())
            except Exception as e:
                first_err = first_err or e
        return results, first_err

    def _flush_largest_locked(self) -> None:
        # early-flushed chunks stay handle-local until flush(); read()
        # serves them from the handle's chunk list, so mid-write state
        # is never visible cluster-wide
        iv = self.dirty.pop_largest_contiguous()
        if iv is not None:
            self.entry.setdefault("chunks", []).append(
                self._upload_interval(iv))
            self._has_local_chunks = True

    def flush(self) -> None:
        """Upload remaining dirty runs and save the entry
        (FileHandle.Flush, filehandle.go)."""
        with self._lock:
            results, err = self._upload_intervals(self.dirty.pop_all())
            # landed chunks join the entry even when a sibling failed:
            # a later flush()/release() then saves them (the old serial
            # loop appended each success before the failure, same
            # guarantee)
            self.entry.setdefault("chunks", []).extend(results)
            if err is not None:
                self._has_local_chunks = self._has_local_chunks \
                    or bool(results)
                raise err
            self.entry.setdefault("attr", {})["mtime"] = time.time()
            self.wfs.client.create_entry(self.entry, free_old_chunks=False)
            self._has_local_chunks = False
            self.wfs.meta_cache.invalidate(self.path)

    def release(self) -> None:
        self.flush()


class WFS:
    """The filesystem: path ops + open-handle registry (wfs.go:77)."""

    def __init__(self, filer_url: str, collection: str = "",
                 replication: str = "", chunk_size: int = 8 * 1024 * 1024,
                 cache_ttl: float = 60.0, subscribe: bool = False):
        self.client = FilerClient(filer_url)
        self.collection = collection
        self.replication = replication
        self.chunk_size = chunk_size
        self.meta_cache = MetaCache(ttl=cache_ttl)
        self.handles: dict[int, FileHandle] = {}
        self._next_fh = 1
        self._lock = threading.Lock()
        # bounded window for flush fan-out: dirty-run chunk uploads from
        # one handle overlap instead of paying their latencies end to end
        workers = max(1, int(os.environ.get(
            "WEED_FILER_UPLOAD_CONCURRENCY", "") or 4))
        self.flush_pool = ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="wfs-flush")
        if subscribe:
            self.meta_cache.start_subscriber(filer_url)

    # --- lookup / attr ---
    def lookup(self, path: str) -> Optional[dict]:
        path = _norm(path)
        hit = self.meta_cache.get(path)
        if hit is not None:
            return hit[0]
        entry = self.client.lookup(path)
        self.meta_cache.put(path, entry)
        return entry

    def getattr(self, path: str) -> dict:
        entry = self.lookup(path)
        if entry is None:
            raise FuseError(2, path)  # ENOENT
        attr = entry.get("attr", {})
        size = max(
            (c["offset"] + c["size"] for c in entry.get("chunks", [])),
            default=0)
        # open write handles know a newer size
        for fh in self.handles.values():
            if fh.path == _norm(path):
                size = max(size, fh.size())
        return {"mode": attr.get("mode", 0o660), "size": size,
                "mtime": attr.get("mtime", 0), "uid": attr.get("uid", 0),
                "gid": attr.get("gid", 0)}

    def readdir(self, path: str) -> list[str]:
        path = _norm(path)
        entry = self.lookup(path)
        if entry is None:
            raise FuseError(2, path)
        listing = self.meta_cache.get_listing(path)
        if listing is None:
            listing = self.client.list_dir(path)
            self.meta_cache.put_listing(path, listing)
        return [e["path"].rsplit("/", 1)[-1] for e in listing]

    # --- file lifecycle ---
    def create(self, path: str, mode: int = 0o660) -> int:
        path = _norm(path)
        entry = {"path": path,
                 "attr": {"mode": mode, "mtime": time.time(),
                          "crtime": time.time(), "uid": 0, "gid": 0,
                          "mime": "application/octet-stream"},
                 "chunks": []}
        self.client.create_entry(entry)
        self.meta_cache.invalidate(path)
        return self._open_handle(path, entry)

    def open(self, path: str, for_write: bool = False) -> int:
        path = _norm(path)
        entry = self.lookup(path)
        if entry is None:
            raise FuseError(2, path)
        return self._open_handle(path, dict(entry), for_write)

    def _open_handle(self, path: str, entry: dict,
                     for_write: bool = True) -> int:
        with self._lock:
            fh = self._next_fh
            self._next_fh += 1
            self.handles[fh] = FileHandle(self, path, entry, for_write)
            return fh

    def write(self, fh: int, data: bytes, offset: int) -> int:
        return self._handle(fh).write(data, offset)

    def read(self, fh: int, size: int, offset: int) -> bytes:
        return self._handle(fh).read(size, offset)

    def flush(self, fh: int) -> None:
        self._handle(fh).flush()

    def release(self, fh: int) -> None:
        with self._lock:
            handle = self.handles.pop(fh, None)
        if handle is not None:
            handle.release()

    def _handle(self, fh: int) -> FileHandle:
        h = self.handles.get(fh)
        if h is None:
            raise FuseError(9, f"bad handle {fh}")  # EBADF
        return h

    # --- namespace ops ---
    def mkdir(self, path: str, mode: int = 0o770) -> None:
        path = _norm(path)
        entry = {"path": path,
                 "attr": {"mode": 0o040000 | (mode & 0o777),
                          "mtime": time.time(), "crtime": time.time()},
                 "chunks": []}
        self.client.create_entry(entry)
        self.meta_cache.invalidate(path)

    def unlink(self, path: str) -> None:
        path = _norm(path)
        if self.lookup(path) is None:
            raise FuseError(2, path)
        self.client.delete(path)
        self.meta_cache.invalidate(path)

    def rmdir(self, path: str) -> None:
        path = _norm(path)
        if self.client.list_dir(path, limit=1):
            raise FuseError(39, path)  # ENOTEMPTY
        self.client.delete(path, recursive=True)
        self.meta_cache.invalidate(path)

    def rename(self, old: str, new: str) -> None:
        old, new = _norm(old), _norm(new)
        self.client.rename(old, new)
        self.meta_cache.invalidate(old)
        self.meta_cache.invalidate(new)

    def truncate(self, path: str, length: int) -> None:
        """ftruncate semantics: drop/trim chunks past length
        (file.go Setattr size change)."""
        path = _norm(path)
        entry = self.lookup(path)
        if entry is None:
            raise FuseError(2, path)
        entry = dict(entry)
        if length == 0:
            entry["chunks"] = []
        else:
            kept = []
            for c in entry.get("chunks", []):
                if c["offset"] >= length:
                    continue
                c = dict(c)
                c["size"] = min(c["size"], length - c["offset"])
                kept.append(c)
            entry["chunks"] = kept
        self.client.create_entry(entry)
        self.meta_cache.invalidate(path)

    # --- extended attributes (weed/filesys/xattr.go; stored in the
    #     entry's extended map) ---
    def setxattr(self, path: str, name: str, value: bytes) -> None:
        import base64
        path = _norm(path)
        entry = self.lookup(path)
        if entry is None:
            raise FuseError(2, path)
        entry = dict(entry)
        extended = dict(entry.get("extended") or {})
        extended["xattr-" + name] = base64.b64encode(value).decode()
        entry["extended"] = extended
        self.client.update_entry(entry)
        self.meta_cache.invalidate(path)

    def getxattr(self, path: str, name: str) -> bytes:
        import base64
        entry = self.lookup(_norm(path))
        if entry is None:
            raise FuseError(2, path)
        raw = (entry.get("extended") or {}).get("xattr-" + name)
        if raw is None:
            raise FuseError(61, name)  # ENODATA
        return base64.b64decode(raw)

    def listxattr(self, path: str) -> list[str]:
        entry = self.lookup(_norm(path))
        if entry is None:
            raise FuseError(2, path)
        return [k[len("xattr-"):] for k in (entry.get("extended") or {})
                if k.startswith("xattr-")]

    def removexattr(self, path: str, name: str) -> None:
        path = _norm(path)
        entry = self.lookup(path)
        if entry is None:
            raise FuseError(2, path)
        extended = dict(entry.get("extended") or {})
        if extended.pop("xattr-" + name, None) is None:
            raise FuseError(61, name)
        entry = dict(entry)
        entry["extended"] = extended
        self.client.update_entry(entry)
        self.meta_cache.invalidate(path)

    # --- hard links (weed/filer/filerstore_hardlink.go: linked entries
    #     share a hard_link_id and the chunk list rides it) ---
    def link(self, target: str, link_path: str) -> None:
        target, link_path = _norm(target), _norm(link_path)
        entry = self.lookup(target)
        if entry is None:
            raise FuseError(2, target)
        if entry.get("attr", {}).get("mode", 0) & 0o040000:
            raise FuseError(1, "cannot hardlink directories")  # EPERM
        entry = dict(entry)
        hlid = entry.get("hard_link_id")
        if not hlid:
            import uuid as uuid_mod
            hlid = uuid_mod.uuid4().hex
            entry["hard_link_id"] = hlid
            self.client.update_entry(entry)
        link_entry = dict(entry)
        link_entry["path"] = link_path
        self.client.create_entry(link_entry, free_old_chunks=False)
        self.meta_cache.invalidate(link_path)
        self.meta_cache.invalidate(target)

    def statfs(self) -> dict:
        return {"bsize": 1024 * 1024, "blocks": 1 << 30, "bfree": 1 << 30}

    def destroy(self) -> None:
        for fh in list(self.handles):
            self.release(fh)
        self.meta_cache.stop()
        self.flush_pool.shutdown(wait=False)
