from .dirty_pages import ContinuousIntervals  # noqa: F401
from .wfs import WFS, FileHandle  # noqa: F401
