"""Kernel FUSE adapter for WFS (`weed mount` equivalent,
weed/command/mount_std.go:51).

Thin: every FUSE callback delegates to the corresponding WFS method. Two
bindings are supported — fusepy when installed, otherwise the built-in
ctypes binding to libfuse2 (mount/fuse_ctypes.py). All mount logic lives
(and is unit-tested) in wfs.py / dirty_pages.py, mirroring how the
reference splits weed/filesys/ from the bazil.org/fuse glue.
"""

from __future__ import annotations

import errno
import stat


class _WfsAdapter:
    """WFS with the couple of shims the kernel surface needs."""

    def __init__(self, wfs):
        self._w = wfs

    def __getattr__(self, name):
        return getattr(self._w, name)

    def getattr(self, path: str) -> dict:
        a = self._w.getattr(path)
        mode = a["mode"]
        if stat.S_IFMT(mode) == 0:
            mode |= stat.S_IFREG
        return {**a, "mode": mode}


def mount(filer_url: str, mountpoint: str, collection: str = "",
          replication: str = "", chunk_size: int = 8 * 1024 * 1024,
          foreground: bool = True) -> None:
    from .wfs import WFS

    wfs = _WfsAdapter(WFS(filer_url, collection=collection,
                          replication=replication,
                          chunk_size=chunk_size, subscribe=True))
    try:
        _mount_fusepy(wfs, mountpoint, foreground)
        return
    except ImportError:
        pass
    # built-in ctypes binding (the image has libfuse2 + /dev/fuse but no
    # fusepy)
    from .fuse_ctypes import fuse_main
    try:
        code = fuse_main(mountpoint, wfs, foreground=foreground)
        if code != 0:
            raise SystemExit(f"fuse_main exited with {code}")
    finally:
        wfs.destroy()


def _mount_fusepy(wfs, mountpoint: str, foreground: bool) -> None:
    from fuse import FUSE, FuseOSError, Operations  # fusepy

    from .wfs import FuseError

    class WeedFuse(Operations):
        def _wrap(self, fn, *args):
            try:
                return fn(*args)
            except FuseError as e:
                raise FuseOSError(e.errno or errno.EIO)

        def getattr(self, path, fh=None):
            # wfs is the _WfsAdapter: the S_IFREG mode shim lives there
            a = self._wrap(wfs.getattr, path)
            return {"st_mode": a["mode"], "st_size": a["size"],
                    "st_mtime": a["mtime"], "st_uid": a["uid"],
                    "st_gid": a["gid"], "st_nlink": 1}

        def readdir(self, path, fh):
            return [".", ".."] + self._wrap(wfs.readdir, path)

        def create(self, path, mode, fi=None):
            return self._wrap(wfs.create, path, mode)

        def open(self, path, flags):
            import os
            writable = bool(flags & (os.O_WRONLY | os.O_RDWR))
            return self._wrap(wfs.open, path, writable)

        def read(self, path, size, offset, fh):
            return self._wrap(wfs.read, fh, size, offset)

        def write(self, path, data, offset, fh):
            return self._wrap(wfs.write, fh, data, offset)

        def flush(self, path, fh):
            return self._wrap(wfs.flush, fh)

        def release(self, path, fh):
            return self._wrap(wfs.release, fh)

        def mkdir(self, path, mode):
            return self._wrap(wfs.mkdir, path, mode)

        def unlink(self, path):
            return self._wrap(wfs.unlink, path)

        def rmdir(self, path):
            return self._wrap(wfs.rmdir, path)

        def rename(self, old, new):
            return self._wrap(wfs.rename, old, new)

        def truncate(self, path, length, fh=None):
            return self._wrap(wfs.truncate, path, length)

        def link(self, link_path, target):
            # fusepy argument order is (new, existing)
            return self._wrap(wfs.link, target, link_path)

        def setxattr(self, path, name, value, options, position=0):
            return self._wrap(wfs.setxattr, path, name, value)

        def getxattr(self, path, name, position=0):
            return self._wrap(wfs.getxattr, path, name)

        def listxattr(self, path):
            return self._wrap(wfs.listxattr, path)

        def removexattr(self, path, name):
            return self._wrap(wfs.removexattr, path, name)

        def statfs(self, path):
            s = wfs.statfs()
            return {"f_bsize": s["bsize"], "f_blocks": s["blocks"],
                    "f_bavail": s["bfree"], "f_bfree": s["bfree"]}

        def destroy(self, path):
            wfs.destroy()

    FUSE(WeedFuse(), mountpoint, foreground=foreground, nothreads=False,
         big_writes=True)
