"""Kernel FUSE adapter for WFS (`weed mount` equivalent,
weed/command/mount_std.go:51).

Thin: every FUSE callback delegates to the corresponding WFS method. The
binding library is optional — this container images neither fusepy nor a
/dev/fuse it could use, so the adapter imports lazily and `weed mount`
reports a clear error when unavailable. All mount logic lives (and is
tested) in wfs.py / dirty_pages.py, mirroring how the reference only
unit-tests the pure-logic layers of weed/filesys/.
"""

from __future__ import annotations

import errno
import stat


def mount(filer_url: str, mountpoint: str, collection: str = "",
          replication: str = "", chunk_size: int = 8 * 1024 * 1024,
          foreground: bool = True) -> None:
    try:
        from fuse import FUSE, FuseOSError, Operations  # fusepy
    except ImportError as e:
        raise SystemExit(
            "FUSE mount needs the 'fusepy' package and a /dev/fuse device; "
            "neither ships in this environment. The full mount VFS is "
            "available programmatically via seaweedfs_tpu.mount.WFS."
        ) from e

    from .wfs import WFS, FuseError

    wfs = WFS(filer_url, collection=collection, replication=replication,
              chunk_size=chunk_size, subscribe=True)

    class WeedFuse(Operations):
        def _wrap(self, fn, *args):
            try:
                return fn(*args)
            except FuseError as e:
                raise FuseOSError(e.errno or errno.EIO)

        def getattr(self, path, fh=None):
            a = self._wrap(wfs.getattr, path)
            mode = a["mode"]
            if stat.S_IFMT(mode) == 0:
                mode |= stat.S_IFREG
            return {"st_mode": mode, "st_size": a["size"],
                    "st_mtime": a["mtime"], "st_uid": a["uid"],
                    "st_gid": a["gid"], "st_nlink": 1}

        def readdir(self, path, fh):
            return [".", ".."] + self._wrap(wfs.readdir, path)

        def create(self, path, mode, fi=None):
            return self._wrap(wfs.create, path, mode)

        def open(self, path, flags):
            import os
            writable = bool(flags & (os.O_WRONLY | os.O_RDWR))
            return self._wrap(wfs.open, path, writable)

        def read(self, path, size, offset, fh):
            return self._wrap(wfs.read, fh, size, offset)

        def write(self, path, data, offset, fh):
            return self._wrap(wfs.write, fh, data, offset)

        def flush(self, path, fh):
            return self._wrap(wfs.flush, fh)

        def release(self, path, fh):
            return self._wrap(wfs.release, fh)

        def mkdir(self, path, mode):
            return self._wrap(wfs.mkdir, path, mode)

        def unlink(self, path):
            return self._wrap(wfs.unlink, path)

        def rmdir(self, path):
            return self._wrap(wfs.rmdir, path)

        def rename(self, old, new):
            return self._wrap(wfs.rename, old, new)

        def truncate(self, path, length, fh=None):
            return self._wrap(wfs.truncate, path, length)

        def statfs(self, path):
            s = wfs.statfs()
            return {"f_bsize": s["bsize"], "f_blocks": s["blocks"],
                    "f_bavail": s["bfree"], "f_bfree": s["bfree"]}

        def destroy(self, path):
            wfs.destroy()

    FUSE(WeedFuse(), mountpoint, foreground=foreground, nothreads=False,
         big_writes=True)
