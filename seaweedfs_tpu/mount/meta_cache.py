"""Local metadata cache for the mount (weed/filesys/meta_cache/).

Caches filer entries per path with TTL, invalidated by the filer's
metadata subscribe stream (the reference mirrors the mounted subtree into
a local leveldb kept fresh by SubscribeMetadata; here an in-memory dict
plus the same subscription wiring)."""

from __future__ import annotations

import json
import threading
import time
import urllib.parse
import urllib.request
from typing import Callable, Optional

from ..utils import retry


class MetaCache:
    def __init__(self, ttl: float = 60.0):
        self.ttl = ttl
        self._entries: dict[str, tuple[Optional[dict], float]] = {}
        self._listings: dict[str, tuple[list[dict], float]] = {}
        self._lock = threading.Lock()
        self._sub_thread: Optional[threading.Thread] = None
        self._stop = False

    def get(self, path: str) -> Optional[tuple[Optional[dict], float]]:
        with self._lock:
            hit = self._entries.get(path)
            if hit and time.time() - hit[1] < self.ttl:
                return hit
            return None

    def put(self, path: str, entry: Optional[dict]) -> None:
        with self._lock:
            self._entries[path] = (entry, time.time())

    def get_listing(self, dir_path: str) -> Optional[list[dict]]:
        with self._lock:
            hit = self._listings.get(dir_path)
            if hit and time.time() - hit[1] < self.ttl:
                return hit[0]
            return None

    def put_listing(self, dir_path: str, entries: list[dict]) -> None:
        with self._lock:
            self._listings[dir_path] = (entries, time.time())

    def invalidate(self, path: str) -> None:
        with self._lock:
            self._entries.pop(path, None)
            parent = path.rsplit("/", 1)[0] or "/"
            self._listings.pop(parent, None)
            self._listings.pop(path, None)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._listings.clear()

    # --- freshness via the filer's subscribe stream ---
    def start_subscriber(self, filer_url: str, prefix: str = "/") -> None:
        def run() -> None:
            since = time.time_ns()
            while not self._stop:
                url = (f"http://{filer_url}/__meta__/subscribe?"
                       + urllib.parse.urlencode({"since": str(since),
                                                 "prefix": prefix}))
                try:
                    req = urllib.request.Request(
                        url, headers=retry.inject_deadline({}))
                    # long-lived tail: timeout=None is deliberate — the
                    # stream lives as long as the mount, and the daemon
                    # thread carries no ambient budget to cap it with
                    with urllib.request.urlopen(req, timeout=None) as r:
                        for line in r:
                            if self._stop:
                                return
                            try:
                                d = json.loads(line)
                            except Exception:
                                continue
                            since = max(since, int(d.get("tsns", since)))
                            for side in ("old", "new"):
                                ent = d.get(side)
                                if ent and ent.get("path"):
                                    self.invalidate(ent["path"])
                except Exception:
                    time.sleep(1.0)

        self._sub_thread = threading.Thread(target=run, daemon=True)
        self._sub_thread.start()

    def stop(self) -> None:
        self._stop = True
