"""Minimal ctypes binding to libfuse 2.9 (high-level, path-based API).

The image ships /dev/fuse + libfuse.so.2 but no fusepy, so this module is
the kernel-mount glue for `weed mount` (role of bazil.org/fuse in the
reference): a fuse_operations struct of CFUNCTYPE trampolines dispatching
into a python operations object (WFS), run via fuse_main_real.

Scope: the operations the filer mount needs — getattr/readdir/create/
open/read/write/flush/release/truncate/unlink/mkdir/rmdir/rename/link/
xattr/statfs. Layouts are x86-64 Linux (struct stat, fuse_file_info,
FUSE_USE_VERSION 26 fuse_operations).
"""

from __future__ import annotations

import ctypes
import ctypes.util
import errno
import logging
import os

log = logging.getLogger("fuse")

c_stat_time = ctypes.c_long * 2  # struct timespec


class Stat(ctypes.Structure):
    _fields_ = [
        ("st_dev", ctypes.c_ulong),
        ("st_ino", ctypes.c_ulong),
        ("st_nlink", ctypes.c_ulong),
        ("st_mode", ctypes.c_uint),
        ("st_uid", ctypes.c_uint),
        ("st_gid", ctypes.c_uint),
        ("__pad0", ctypes.c_uint),
        ("st_rdev", ctypes.c_ulong),
        ("st_size", ctypes.c_long),
        ("st_blksize", ctypes.c_long),
        ("st_blocks", ctypes.c_long),
        ("st_atim", c_stat_time),
        ("st_mtim", c_stat_time),
        ("st_ctim", c_stat_time),
        ("__reserved", ctypes.c_long * 3),
    ]


# callback prototypes (x86-64, FUSE_USE_VERSION 26)
GETATTR_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p,
                             ctypes.POINTER(Stat))
READLINK_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p,
                              ctypes.POINTER(ctypes.c_char),
                              ctypes.c_size_t)
MK_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p, ctypes.c_uint)
PATH_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p)
PATH2_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p)
CHOWN_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p, ctypes.c_uint,
                           ctypes.c_uint)
TRUNCATE_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p,
                              ctypes.c_long)
FI_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p, ctypes.c_void_p)
RW_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p,
                        ctypes.POINTER(ctypes.c_char), ctypes.c_size_t,
                        ctypes.c_long, ctypes.c_void_p)
FILLER_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p, ctypes.c_char_p,
                            ctypes.POINTER(Stat), ctypes.c_long)
READDIR_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p,
                             ctypes.c_void_p, FILLER_T, ctypes.c_long,
                             ctypes.c_void_p)
CREATE_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p, ctypes.c_uint,
                            ctypes.c_void_p)
SETXATTR_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p,
                              ctypes.c_char_p,
                              ctypes.POINTER(ctypes.c_char),
                              ctypes.c_size_t, ctypes.c_int)
GETXATTR_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p,
                              ctypes.c_char_p,
                              ctypes.POINTER(ctypes.c_char),
                              ctypes.c_size_t)
LISTXATTR_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p,
                               ctypes.POINTER(ctypes.c_char),
                               ctypes.c_size_t)
UTIMENS_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p,
                             ctypes.c_void_p)
VOID_T = ctypes.c_void_p


class FuseOperations(ctypes.Structure):
    _fields_ = [
        ("getattr", GETATTR_T),
        ("readlink", READLINK_T),
        ("getdir", VOID_T),
        ("mknod", VOID_T),
        ("mkdir", MK_T),
        ("unlink", PATH_T),
        ("rmdir", PATH_T),
        ("symlink", PATH2_T),
        ("rename", PATH2_T),
        ("link", PATH2_T),
        ("chmod", MK_T),
        ("chown", CHOWN_T),
        ("truncate", TRUNCATE_T),
        ("utime", VOID_T),
        ("open", FI_T),
        ("read", RW_T),
        ("write", RW_T),
        ("statfs", VOID_T),
        ("flush", FI_T),
        ("release", FI_T),
        ("fsync", VOID_T),
        ("setxattr", SETXATTR_T),
        ("getxattr", GETXATTR_T),
        ("listxattr", LISTXATTR_T),
        ("removexattr", PATH2_T),
        ("opendir", VOID_T),
        ("readdir", READDIR_T),
        ("releasedir", VOID_T),
        ("fsyncdir", VOID_T),
        ("init", VOID_T),
        ("destroy", VOID_T),
        ("access", VOID_T),
        ("create", CREATE_T),
        ("ftruncate", VOID_T),
        ("fgetattr", VOID_T),
        ("lock", VOID_T),
        ("utimens", UTIMENS_T),
        ("bmap", VOID_T),
        ("flags", ctypes.c_uint),
        ("ioctl", VOID_T),
        ("poll", VOID_T),
        ("write_buf", VOID_T),
        ("read_buf", VOID_T),
        ("flock", VOID_T),
        ("fallocate", VOID_T),
    ]


def _errno_of(exc: BaseException) -> int:
    if isinstance(exc, OSError) and exc.errno:
        return -exc.errno
    return -errno.EIO


# fuse_file_info.fh offset on x86-64 (flags 4 + pad 4 + fh_old 8 +
# writepage 4 + bitfield 4)
_FH_OFFSET = 24


def _get_fh(fi: int) -> int:
    if not fi:
        return 0
    return ctypes.cast(fi + _FH_OFFSET,
                       ctypes.POINTER(ctypes.c_uint64)).contents.value


def _set_fh(fi: int, fh: int) -> None:
    if fi:
        ctypes.cast(fi + _FH_OFFSET,
                    ctypes.POINTER(ctypes.c_uint64)).contents.value = fh


def fuse_main(mountpoint: str, ops, foreground: bool = True,
              options: str = "") -> int:
    """Mount `ops` (a WFS-style object) at mountpoint and serve until
    unmounted. Blocks; returns libfuse's exit code."""
    import platform
    if platform.machine() != "x86_64":
        raise RuntimeError(
            f"built-in fuse binding only knows x86-64 struct layouts "
            f"(this is {platform.machine()}); install fusepy instead")
    libname = ctypes.util.find_library("fuse")
    if libname is None:
        raise RuntimeError("libfuse not found")
    libfuse = ctypes.CDLL(libname)

    kept = []  # keep trampolines alive for the mount's lifetime

    def wrap(factory, fn):
        cb = factory(fn)
        kept.append(cb)
        return cb

    def _getattr(path, stbuf):
        try:
            ctypes.memset(stbuf, 0, ctypes.sizeof(Stat))
            st = ops.getattr(path.decode())
            s = stbuf.contents
            s.st_mode = st["mode"]
            s.st_nlink = st.get("nlink", 1)
            s.st_size = st.get("size", 0)
            s.st_uid = st.get("uid") or os.getuid()
            s.st_gid = st.get("gid") or os.getgid()
            mtime = int(st.get("mtime", 0))
            s.st_mtim[0] = mtime
            s.st_ctim[0] = mtime
            s.st_atim[0] = mtime
            s.st_blocks = (st.get("size", 0) + 511) // 512
            s.st_blksize = 4096
            return 0
        except Exception as e:
            return _errno_of(e)

    def _readdir(path, buf, filler, offset, fi):
        try:
            filler(buf, b".", None, 0)
            filler(buf, b"..", None, 0)
            for name in ops.readdir(path.decode()):
                filler(buf, name.encode(), None, 0)
            return 0
        except Exception as e:
            return _errno_of(e)

    def _create(path, mode, fi):
        try:
            _set_fh(fi, ops.create(path.decode(), mode))
            return 0
        except Exception as e:
            return _errno_of(e)

    def _open(path, fi):
        try:
            flags = (ctypes.cast(fi, ctypes.POINTER(ctypes.c_int))
                     .contents.value if fi else 0)
            writable = bool(flags & (os.O_WRONLY | os.O_RDWR))
            _set_fh(fi, ops.open(path.decode(), for_write=writable))
            return 0
        except Exception as e:
            return _errno_of(e)

    def _read(path, buf, size, offset, fi):
        try:
            data = ops.read(_get_fh(fi), size, offset)
            ctypes.memmove(buf, data, len(data))
            return len(data)
        except Exception as e:
            return _errno_of(e)

    def _write(path, buf, size, offset, fi):
        try:
            data = ctypes.string_at(buf, size)
            return ops.write(_get_fh(fi), data, offset)
        except Exception as e:
            return _errno_of(e)

    def _flush(path, fi):
        try:
            ops.flush(_get_fh(fi))
            return 0
        except Exception as e:
            return _errno_of(e)

    def _release(path, fi):
        try:
            ops.release(_get_fh(fi))
            return 0
        except Exception as e:
            return _errno_of(e)

    def _truncate(path, length):
        try:
            ops.truncate(path.decode(), length)
            return 0
        except Exception as e:
            return _errno_of(e)

    def _unlink(path):
        try:
            ops.unlink(path.decode())
            return 0
        except Exception as e:
            return _errno_of(e)

    def _mkdir(path, mode):
        try:
            ops.mkdir(path.decode(), mode)
            return 0
        except Exception as e:
            return _errno_of(e)

    def _rmdir(path):
        try:
            ops.rmdir(path.decode())
            return 0
        except Exception as e:
            return _errno_of(e)

    def _rename(old, new):
        try:
            ops.rename(old.decode(), new.decode())
            return 0
        except Exception as e:
            return _errno_of(e)

    def _link(target, link_path):
        try:
            ops.link(target.decode(), link_path.decode())
            return 0
        except Exception as e:
            return _errno_of(e)

    def _setxattr(path, name, value, size, flags):
        try:
            ops.setxattr(path.decode(), name.decode(),
                         ctypes.string_at(value, size))
            return 0
        except Exception as e:
            return _errno_of(e)

    def _getxattr(path, name, buf, size):
        try:
            value = ops.getxattr(path.decode(), name.decode())
            if size == 0:
                return len(value)
            if size < len(value):
                return -errno.ERANGE
            ctypes.memmove(buf, value, len(value))
            return len(value)
        except Exception as e:
            return _errno_of(e)

    def _listxattr(path, buf, size):
        try:
            names = b"".join(n.encode() + b"\x00"
                             for n in ops.listxattr(path.decode()))
            if size == 0:
                return len(names)
            if size < len(names):
                return -errno.ERANGE
            ctypes.memmove(buf, names, len(names))
            return len(names)
        except Exception as e:
            return _errno_of(e)

    def _removexattr(path, name):
        try:
            ops.removexattr(path.decode(), name.decode())
            return 0
        except Exception as e:
            return _errno_of(e)

    def _ok(*args):
        return 0

    operations = FuseOperations()
    operations.getattr = wrap(GETATTR_T, _getattr)
    operations.readdir = wrap(READDIR_T, _readdir)
    operations.create = wrap(CREATE_T, _create)
    operations.open = wrap(FI_T, _open)
    operations.read = wrap(RW_T, _read)
    operations.write = wrap(RW_T, _write)
    operations.flush = wrap(FI_T, _flush)
    operations.release = wrap(FI_T, _release)
    operations.truncate = wrap(TRUNCATE_T, _truncate)
    operations.unlink = wrap(PATH_T, _unlink)
    operations.mkdir = wrap(MK_T, _mkdir)
    operations.rmdir = wrap(PATH_T, _rmdir)
    operations.rename = wrap(PATH2_T, _rename)
    operations.link = wrap(PATH2_T, _link)
    operations.setxattr = wrap(SETXATTR_T, _setxattr)
    operations.getxattr = wrap(GETXATTR_T, _getxattr)
    operations.listxattr = wrap(LISTXATTR_T, _listxattr)
    operations.removexattr = wrap(PATH2_T, _removexattr)
    operations.chmod = wrap(MK_T, _ok)
    operations.chown = wrap(CHOWN_T, _ok)
    operations.utimens = wrap(UTIMENS_T, _ok)

    argv = [b"seaweedfs-tpu", mountpoint.encode()]
    if foreground:
        argv.append(b"-f")
    argv.append(b"-s")  # single-threaded: WFS handles are loop-free sync
    if options:
        argv += [b"-o", options.encode()]
    argc = len(argv)
    argv_arr = (ctypes.c_char_p * argc)(*argv)

    libfuse.fuse_main_real.restype = ctypes.c_int
    return libfuse.fuse_main_real(
        argc, argv_arr, ctypes.byref(operations),
        ctypes.sizeof(operations), None)
