"""Command-line interface: the `weed`-equivalent entry point.

Subcommands mirror the reference CLI (weed/command/command.go:10-33):
master, volume, server (master+volume in one process), upload, download,
delete, benchmark, shell ops (ec.encode / ec.rebuild / ec.balance /
ec.decode, volume.vacuum), status.

  python -m seaweedfs_tpu.cli master -port 9333
  python -m seaweedfs_tpu.cli volume -port 8080 -dir /data -mserver localhost:9333
  python -m seaweedfs_tpu.cli upload -server localhost:9333 FILE...
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import sys


def _run_forever(coro) -> None:
    loop = asyncio.new_event_loop()
    loop.run_until_complete(coro)
    try:
        loop.run_forever()
    except KeyboardInterrupt:
        pass


def _maybe_sharded(boot_fn) -> None:
    """Run ``boot_fn(shard_ctx) -> coroutine`` across the WEED_SERVE_SHARDS
    fleet.  The fork MUST happen here, before _run_forever news an event
    loop — a pre-fork epoll fd would be shared by every child (weedlint's
    fork-then-asyncio rule pins the ordering).  One shard (the default)
    skips all of it: boot_fn(None) on today's proven path."""
    from .server import sharded
    n = sharded.shards_from_env()
    if n <= 1:
        _run_forever(boot_fn(None))
        return
    import secrets
    ctx = sharded.ShardContext.create(n, secrets.token_hex(16))
    sharded.run_sharded(ctx, lambda c: _run_forever(boot_fn(c)))


def _load_guard():
    """Build a security Guard from security.toml (weed/command/scaffold.go
    security section; keys jwt.signing.key etc.)."""
    from .security.guard import Guard
    from .utils.config import load_configuration
    cfg = load_configuration("security")
    white = cfg.get_string("guard.white_list", "")
    return Guard(
        whitelist=[w for w in white.split(",") if w],
        signing_key=cfg.get_string("jwt.signing.key", ""),
        expires_seconds=cfg.get_int("jwt.signing.expires_after_seconds", 10),
        read_signing_key=cfg.get_string("jwt.signing.read.key", ""),
        read_expires_seconds=cfg.get_int(
            "jwt.signing.read.expires_after_seconds", 60))


def _load_tls():
    """TLS config from security.toml [tls]; None when not configured."""
    from .security.tls import load_tls_config
    cfg = load_tls_config()
    return cfg if cfg.enabled else None


def cmd_master(args) -> None:
    from .server.master import run_master
    url = f"{args.ip}:{args.port}"
    peers = [p.strip() for p in args.peers.split(",") if p.strip()]
    sequencer = None
    if args.sequencer_kv:
        # external atomic-counter sequencer (etcd_sequencer.go role):
        # redis-protocol INCRBY key-range leases
        from .topology.sequence import KvSequencer
        host, _, port = args.sequencer_kv.rpartition(":")
        if not host or not port.isdigit():
            raise SystemExit(
                f"-sequencer_kv must be host:port, got {args.sequencer_kv!r}")
        sequencer = KvSequencer(host, int(port))
    _run_forever(run_master(
        args.ip, args.port,
        volume_size_limit_mb=args.volume_size_limit_mb,
        default_replication=args.default_replication,
        pulse_seconds=args.pulse,
        guard=_load_guard(),
        tls=_load_tls(),
        url=url,
        peers=peers or None,
        sequencer=sequencer,
        raft_state_dir=args.mdir or None,
        grpc_port=(args.port + 10000 if args.grpc_port < 0
                   else args.grpc_port),
        maintenance_interval_seconds=(None if args.maintenance_interval < 0
                                      else args.maintenance_interval),
        repair_concurrency=args.repair_concurrency))


def cmd_volume(args) -> None:
    def boot(shard_ctx):
        from .ec.geometry import Geometry
        from .server.volume_server import run_volume_server
        from .storage.store import Store
        dirs = args.dir.split(",")
        if shard_ctx is not None and shard_ctx.index > 0:
            # share-nothing: every shard owns private volume dirs;
            # shard 0 keeps the base dirs so pre-sharding (legacy)
            # volumes stay served where they already live
            dirs = [os.path.join(d, f"shard{shard_ctx.index}")
                    for d in dirs]
            for d in dirs:
                os.makedirs(d, exist_ok=True)
        geometry = Geometry(
            large_block_size=args.ec_large_block,
            small_block_size=args.ec_small_block)
        store = Store(dirs,
                      max_volume_counts=[args.max] * len(dirs),
                      coder_name=args.coder, geometry=geometry,
                      needle_map_kind=args.index,
                      min_free_space_percent=args.min_free_space_percent,
                      preallocate=args.preallocate * 1024 * 1024)
        shard0 = shard_ctx is None or shard_ctx.index == 0
        return run_volume_server(
            args.ip, args.port, store, args.mserver,
            data_center=args.data_center, rack=args.rack,
            pulse_seconds=args.pulse, guard=_load_guard(), tls=_load_tls(),
            # the gRPC surfaces bind fixed ports: shard 0 owns them,
            # siblings serve HTTP/fastpath only
            use_grpc_heartbeat=args.grpc_heartbeat and shard0,
            grpc_port=((args.port + 10000 if args.grpc_port < 0
                        else args.grpc_port) if shard0 else 0),
            internal_token=(shard_ctx.token if shard_ctx else None),
            shard_ctx=shard_ctx)

    _maybe_sharded(boot)


def cmd_server(args) -> None:
    """master + volume (+ filer + s3) in one process
    (weed/command/server.go:117-221)."""
    from .ec.geometry import Geometry
    from .server.master import run_master
    from .server.volume_server import run_volume_server
    from .storage.store import Store

    async def boot():
        guard = _load_guard()
        tls = _load_tls()
        master_url = f"{args.ip}:{args.master_port}"
        await run_master(args.ip, args.master_port,
                         default_replication=args.default_replication,
                         guard=guard, url=master_url, tls=tls,
                         grpc_port=args.master_port + 10000)
        geometry = Geometry(large_block_size=args.ec_large_block,
                            small_block_size=args.ec_small_block)
        store = Store(args.dir.split(","), coder_name=args.coder,
                      geometry=geometry)
        await run_volume_server(args.ip, args.port, store, master_url,
                                guard=guard, tls=tls,
                                grpc_port=args.port + 10000)
        if getattr(args, "volume_workers", 1) > 1:
            # share-nothing worker processes: each owns its volumes; the
            # master balances assigns across them like any other nodes
            import atexit
            import subprocess
            procs = []
            base_dir = args.dir.split(",")[0]
            for k in range(1, args.volume_workers):
                wdir = os.path.join(base_dir, f"worker{k}")
                os.makedirs(wdir, exist_ok=True)
                procs.append(subprocess.Popen(
                    [sys.executable, "-m", "seaweedfs_tpu.cli", "volume",
                     "-ip", args.ip, "-port", str(args.port + k),
                     "-dir", wdir, "-mserver", master_url,
                     "-coder", args.coder,
                     # geometry must match the parent's, or shard sets
                     # from different workers misaddress on rebuild/copy
                     "-ec_large_block", str(args.ec_large_block),
                     "-ec_small_block", str(args.ec_small_block)]))
            atexit.register(lambda: [p.terminate() for p in procs])
        if args.filer:
            from .server.filer_server import run_filer
            await run_filer(args.ip, args.filer_port, master_url,
                            store_name="sqlite",
                            store_kwargs={"path": args.filer_db},
                            guard=guard, tls=tls,
                            grpc_port=args.filer_port + 10000)
        if args.s3:
            if not args.filer:
                raise SystemExit("-s3 needs -filer")
            from .s3.s3_server import run_s3
            iam = None
            if args.s3_config:
                from .s3.auth import Iam
                iam = Iam.from_file(args.s3_config)
            await run_s3(args.ip, args.s3_port,
                         f"{args.ip}:{args.filer_port}", iam=iam)

    _run_forever(boot())


def cmd_filer(args) -> None:
    from .notification.queues import load_notifier
    from .server.filer_server import run_filer
    from .utils.config import load_configuration
    store_kwargs = {}
    if args.store in ("sqlite", "leveldb", "leveldb2"):
        store_kwargs["path"] = args.store_path
    if args.store_servers:
        if args.store in ("redis", "redis2", "mongodb", "cassandra"):
            host, _, port = args.store_servers.rpartition(":")
            store_kwargs["host"], store_kwargs["port"] = host, int(port)
        elif args.store in ("etcd", "elastic"):
            store_kwargs["servers"] = args.store_servers
    notifier = load_notifier(load_configuration("notification"))
    ring_config = None
    if args.ring_peers:
        from .metaring import RingConfig
        base = RingConfig.from_env()
        ring_config = RingConfig(
            peers=[p for p in args.ring_peers.split(",") if p],
            vnodes=base.vnodes, replicas=base.replicas)
    def boot(shard_ctx):
        shard0 = shard_ctx is None or shard_ctx.index == 0
        return run_filer(
            args.ip, args.port, args.mserver, store_name=args.store,
            store_kwargs=store_kwargs,
            chunk_size=args.chunk_size_mb * 1024 * 1024,
            default_replication=args.default_replication,
            default_collection=args.collection,
            meta_log_path=args.meta_log,
            peers=[p for p in args.peers.split(",") if p],
            notifier=notifier, guard=_load_guard(), tls=_load_tls(),
            cipher=args.encrypt_volume_data,
            url=f"{args.ip}:{args.port}",
            ring_config=ring_config,
            grpc_port=((args.port + 10000 if args.grpc_port < 0
                        else args.grpc_port) if shard0 else 0),
            shard_ctx=shard_ctx)

    _maybe_sharded(boot)


def cmd_filer_copy(args) -> None:
    """Parallel file/tree upload through a filer (weed filer.copy,
    weed/command/filer_copy.go:78,365 — there a goroutine worker pool per
    file; here a thread pool driving the filer's autochunk PUT)."""
    import fnmatch
    import mimetypes
    import time as time_mod
    import urllib.request
    from concurrent.futures import ThreadPoolExecutor
    from urllib.parse import quote, urlparse

    dest = args.dest
    u = urlparse(dest)
    if not u.scheme.startswith("http") or not u.netloc:
        raise SystemExit("destination must be http://filer:port/path/")
    if not u.path.endswith("/"):
        raise SystemExit('destination should be a folder ending with "/"')

    jobs: list[tuple[str, str]] = []  # (local path, filer-relative path)
    for src in args.sources:
        if os.path.isdir(src):
            base = os.path.basename(os.path.normpath(src))
            for root, _dirs, fnames in os.walk(src):
                for fn in sorted(fnames):
                    if args.include and not fnmatch.fnmatch(fn,
                                                            args.include):
                        continue
                    full = os.path.join(root, fn)
                    rel = os.path.join(base,
                                       os.path.relpath(full, src))
                    jobs.append((full, rel))
        elif os.path.exists(src):
            jobs.append((src, os.path.basename(src)))
        else:
            raise SystemExit(f"no such file or directory: {src}")

    total = [0]
    errors: list[str] = []
    t0 = time_mod.perf_counter()

    def one(job: tuple[str, str]) -> None:
        full, rel = job
        target = (f"{u.scheme}://{u.netloc}{u.path}"
                  f"{quote(rel.replace(os.sep, '/'))}")
        if args.collection:
            target += f"?collection={args.collection}"
        mime = mimetypes.guess_type(full)[0] or "application/octet-stream"
        try:
            with open(full, "rb") as f:
                data = f.read()
            req = urllib.request.Request(
                target, data=data, method="PUT",
                headers={"Content-Type": mime})
            with urllib.request.urlopen(req, timeout=120) as r:
                r.read()
            total[0] += len(data)
        except Exception as e:
            errors.append(f"{full}: {e}")

    with ThreadPoolExecutor(max_workers=args.concurrency) as pool:
        list(pool.map(one, jobs))
    dt = time_mod.perf_counter() - t0
    for err in errors:
        print(f"error: {err}", file=sys.stderr)
    print(f"copied {len(jobs) - len(errors)}/{len(jobs)} files, "
          f"{total[0]} bytes in {dt:.2f}s "
          f"({total[0] / max(dt, 1e-9) / 1e6:.1f} MB/s, "
          f"c={args.concurrency})")
    if errors:
        raise SystemExit(1)


def cmd_watch(args) -> None:
    """Live-tail filer metadata events (weed watch,
    weed/command/watch.go:36)."""
    from .replication.replicator import Replicator
    r = Replicator(args.filer, None, args.path_prefix)
    for e in r.subscribe_events(since=args.since):
        if e.directory.startswith(args.path_prefix):
            print(json.dumps(e.to_dict()), flush=True)


def _offset_path(stem: str, *parts: str) -> str:
    """Default resume-offset file: stable per-user directory (not CWD, so
    daemon restarts with a different working dir still resume) +
    human-readable first part + a hash of the full job identity
    (source, sink, prefix) so distinct jobs never share an offset
    (filer_sync.go setOffset/getOffset keys by signature)."""
    import hashlib
    base = os.path.join(os.path.expanduser("~"), ".seaweedfs_tpu", "offsets")
    os.makedirs(base, exist_ok=True)
    job_key = hashlib.sha256("|".join(parts).encode()).hexdigest()[:12]
    human = parts[0].replace(":", "_").replace("/", "_") if parts else ""
    return os.path.join(base, f"{stem}.{human}.{job_key}")


def cmd_filer_replicate(args) -> None:
    """Continuously replicate one filer into a sink configured by
    replication.toml (weed filer.replicate). With -from_queue the events
    come from the configured [source.*] queue (file spool or messaging
    broker) instead of a live subscribe stream — the reference's
    Kafka/SQS-fed mode (weed/replication/sub)."""
    import time as _time

    from .replication.replicator import Replicator, run_from_queue
    from .replication.sink import load_sink
    from .utils.config import load_configuration
    cfg = load_configuration("replication")
    sink = load_sink(cfg)
    if sink is None:
        raise SystemExit("no enabled [sink.*] in replication.toml "
                         "(run scaffold -config replication)")
    offset = args.offset_file or _offset_path(
        "replicate_offset", args.filer, sink.identity(), args.path_prefix)
    r = Replicator(args.filer, sink, args.path_prefix, offset_path=offset)
    if args.from_queue:
        from .replication.sub import load_notification_input
        inp = load_notification_input(cfg)
        if inp is None:
            raise SystemExit("-from_queue needs an enabled [source.*] in "
                             "replication.toml")
        while True:
            run_from_queue(r, inp, idle_timeout=2.0)
            _time.sleep(1.0)
    else:
        r.run()


def cmd_filer_sync(args) -> None:
    """Active-active sync of two filers with signature loop prevention
    (weed filer.sync, weed/command/filer_sync.go:81-330)."""
    import threading
    import urllib.request

    from .replication.replicator import Replicator
    from .replication.sink import FilerSink

    def signature_of(filer: str) -> int:
        with urllib.request.urlopen(
                f"http://{filer}/__meta__/info", timeout=10) as r:
            return int(json.load(r)["signature"])

    sig_a, sig_b = signature_of(args.a), signature_of(args.b)

    def one_direction(src: str, dst: str, dst_sig: int) -> None:
        # exclude events the destination already processed — the loop break
        # of filer.sync (filer_sync.go signature filtering); per-direction
        # offsets (keyed by src, dst AND prefix) persisted so restarts
        # resume instead of full replay
        if args.offset_file:
            # sanitize only the per-direction suffix, never the user path
            suffix = f"{src}_{dst}".replace(":", "_").replace("/", "_")
            offset = f"{args.offset_file}.{suffix}"
        else:
            offset = _offset_path("sync_offset", src, dst, args.path_prefix)
        Replicator(src, FilerSink(dst), args.path_prefix,
                   offset_path=offset).run(exclude_sig=dst_sig)

    ta = threading.Thread(target=one_direction,
                          args=(args.a, args.b, sig_b), daemon=True)
    ta.start()
    one_direction(args.b, args.a, sig_a)


def cmd_s3(args) -> None:
    from .s3.s3_server import run_s3
    if bool(args.access_key) != bool(args.secret_key):
        raise SystemExit(
            "-access_key and -secret_key must be provided together "
            "(omit both for anonymous mode)")
    iam = None
    if args.config:
        from .s3.auth import Iam
        iam = Iam.from_file(args.config)
    _maybe_sharded(lambda shard_ctx: run_s3(
        args.ip, args.port, args.filer,
        access_key=args.access_key,
        secret_key=args.secret_key,
        iam=iam, shard_ctx=shard_ctx))


def cmd_upload(args) -> None:
    from .client import Client
    c = Client(args.server)
    out = []
    for path in args.files:
        with open(path, "rb") as f:
            data = f.read()
        fid = c.upload(data, filename=os.path.basename(path),
                       collection=args.collection,
                       replication=args.replication, ttl=args.ttl)
        out.append({"file": path, "fid": fid, "size": len(data)})
        print(json.dumps(out[-1]))


def cmd_download(args) -> None:
    from .client import Client
    c = Client(args.server)
    data = c.download(args.fid)
    if args.output == "-":
        sys.stdout.buffer.write(data)
    else:
        with open(args.output, "wb") as f:
            f.write(data)
        print(f"{args.fid} -> {args.output} ({len(data)} bytes)")


def cmd_delete(args) -> None:
    from .client import Client
    c = Client(args.server, guard=_load_guard())
    for fid in args.fids:
        c.delete(fid)
        print(f"deleted {fid}")


def cmd_shell(args) -> None:
    """Admin shell: one-shot `weed shell <cmd> [args]` or interactive REPL
    (weed/shell/shell_liner.go)."""
    from .client import Client
    from .ec.geometry import Geometry
    from .shell import commands as shell_commands
    from .shell.commands import CommandEnv, COMMANDS, run_command
    shell_commands._register_all()
    c = Client(args.server)

    # back-compat with the round-1 flag style (`shell ec.encode -volume N
    # -ec_large_block B`): argparse REMAINDER swallows those flags, so
    # fold them back into the geometry / new-style argv here
    large, small = args.ec_large_block, args.ec_small_block
    argv: list[str] = []
    raw = list(args.cmd or [])
    i = 0
    while i < len(raw):
        tok = raw[i]
        needs_value = tok in ("-volume", "-ec_large_block",
                              "-ec_small_block")
        if needs_value and i + 1 >= len(raw):
            raise SystemExit(f"shell: flag {tok} needs a value")
        try:
            if tok == "-volume":
                argv += ["-volumeId", raw[i + 1]]
                i += 2
            elif tok == "-dry_run":
                argv.append("-dryRun")
                i += 1
            elif tok == "-ec_large_block":
                large = int(raw[i + 1])
                i += 2
            elif tok == "-ec_small_block":
                small = int(raw[i + 1])
                i += 2
            else:
                argv.append(tok)
                i += 1
        except ValueError:
            raise SystemExit(f"shell: bad value for {tok}: {raw[i + 1]!r}")
    if argv and args.volume:
        argv += ["-volumeId", str(args.volume)]
    if argv and args.collection:
        argv += ["-collection", args.collection]
    if argv and args.dry_run:
        argv.append("-dryRun")

    geometry = Geometry(large_block_size=large, small_block_size=small)
    env = CommandEnv(c, geometry, filer=args.filer)

    def show(result) -> None:
        if isinstance(result, bytes):
            import sys as sys_mod
            sys_mod.stdout.buffer.write(result)
            sys_mod.stdout.buffer.flush()
        else:
            print(json.dumps(result, indent=None, default=str))

    if argv:
        show(run_command(env, argv))
        return

    # REPL
    import sys as sys_mod
    print(f"seaweedfs-tpu shell: {len(COMMANDS)} commands; "
          "'help' lists them, ctrl-d exits", file=sys_mod.stderr)
    while True:
        try:
            line = input("> ")
        except EOFError:
            break
        line = line.strip()
        if not line:
            continue
        if line in ("exit", "quit"):
            break
        try:
            show(run_command(env, line))
        except Exception as e:
            print(json.dumps({"error": str(e)}))
    if env.locked:
        env.release_lock()


def cmd_backup(args) -> None:
    """Incrementally pull a volume into a local replica directory
    (weed backup, weed/command/backup.go:64)."""
    from .client import Client
    from .storage import volume_backup
    from .storage.volume import Volume
    import os
    c = Client(args.server)
    os.makedirs(args.dir, exist_ok=True)
    create = not os.path.exists(
        os.path.join(args.dir, (f"{args.collection}_" if args.collection
                                else "") + f"{args.volumeId}.dat"))
    v = Volume(args.dir, args.collection, args.volumeId, create=create)
    applied = volume_backup.incremental_backup(
        v, 0, lambda since: c.tail_volume(args.volumeId, since))
    print(json.dumps({"volume": args.volumeId, "applied": applied,
                      "file_count": v.file_count()}))
    v.close()


def cmd_fix(args) -> None:
    """Rebuild .idx by scanning .dat (weed fix, weed/command/fix.go:61)."""
    from .storage import volume_backup
    count = volume_backup.rebuild_idx(args.dir, args.collection,
                                      args.volumeId)
    print(json.dumps({"volume": args.volumeId, "live_needles": count}))


def cmd_export(args) -> None:
    """Export a volume's live needles to a tar archive
    (weed export, weed/command/export.go:149)."""
    import tarfile
    import io
    from .storage.volume import Volume
    v = Volume(args.dir, args.collection, args.volumeId)
    n_out = 0
    with tarfile.open(args.output, "w") as tar:
        from .storage import types as t

        def visit(n, byte_offset):
            nonlocal n_out
            if len(n.data) == 0:
                return
            nv = v.nm.get(n.id)
            if nv is None or nv.size < 0:
                return  # deleted
            if t.stored_to_offset(nv.offset) != byte_offset:
                return  # superseded by a later version of the same fid
            name = (n.name.decode("utf-8", "replace")
                    if n.name else f"{v.vid}_{n.id:x}")
            info = tarfile.TarInfo(name=name)
            info.size = len(n.data)
            info.mtime = n.last_modified
            tar.addfile(info, io.BytesIO(n.data))
            n_out += 1
        v.scan(visit)
    v.close()
    print(json.dumps({"volume": args.volumeId, "files": n_out,
                      "tar": args.output}))


def cmd_compact(args) -> None:
    """Offline vacuum of one volume (weed compact, weed/command/compact.go)."""
    from .storage.volume import Volume
    v = Volume(args.dir, args.collection, args.volumeId)
    before = v.data_file_size()
    v.compact()
    after = v.data_file_size()
    v.close()
    print(json.dumps({"volume": args.volumeId, "bytes_before": before,
                      "bytes_after": after, "reclaimed": before - after}))


def cmd_status(args) -> None:
    from .client import Client
    print(json.dumps(Client(args.server).cluster_status(), indent=2))


def cmd_benchmark(args) -> None:
    """Self-validating write/read benchmark (weed/command/benchmark.go):
    seeded unique payloads, hash-checked on read-back, latency
    percentiles. Raw-socket keep-alive engine (utils/bench_client.py) so
    the harness is not the bottleneck it measures."""
    from .utils.bench_client import run_benchmark

    master = args.server.split(",")[0]
    out = run_benchmark(master, n=args.n, size=args.size,
                        concurrency=args.concurrency)
    w, r = out["write"], out["read"]
    print(f"writes: {w['n']} in {w['wall_s']}s -> {w['req_s']} req/s, "
          f"p50={w.get('p50_ms')}ms p95={w.get('p95_ms')}ms "
          f"p99={w.get('p99_ms')}ms ({out['write_errors']} errors)")
    print(f"reads: {r['n']} in {r['wall_s']}s -> {r['req_s']} req/s, "
          f"{out['corrupt']} corrupt")
    if out["corrupt"] or out["write_errors"]:
        raise SystemExit(1)


def cmd_mount(args) -> None:
    from .mount.fuse_mount import mount
    mount(args.filer, args.dir, collection=args.collection,
          replication=args.replication,
          chunk_size=args.chunk_size_mb * 1024 * 1024)


def cmd_webdav(args) -> None:
    from .server.webdav_server import run_webdav
    _run_forever(run_webdav(args.ip, args.port, args.filer))


def cmd_msg_broker(args) -> None:
    from .messaging.broker import run_broker
    _run_forever(run_broker(args.ip, args.port, filer_url=args.filer,
                            tls=_load_tls()))


def cmd_scaffold(args) -> None:
    """Emit commented default TOML templates (weed/command/scaffold.go:30)."""
    from .utils.scaffold import TEMPLATES
    name = args.config
    if name not in TEMPLATES:
        raise SystemExit(f"unknown config {name}; one of {list(TEMPLATES)}")
    text = TEMPLATES[name]
    if args.output:
        with open(os.path.join(args.output, name + ".toml"), "w") as f:
            f.write(text)
    else:
        sys.stdout.write(text)


def cmd_version(args) -> None:
    from . import __version__
    print(f"seaweedfs-tpu {__version__}")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="seaweedfs-tpu")
    p.add_argument("-v", type=int, default=0, dest="verbosity",
                   help="glog verbosity level")
    p.add_argument("-vmodule", default="",
                   help="per-file verbosity, e.g. volume=2,store=4")
    p.add_argument("-logFile", default="", dest="log_file")
    p.add_argument("-cpuprofile", default="",
                   help="write a cProfile dump here at exit "
                        "(grace.SetupProfiling analog)")
    sub = p.add_subparsers(dest="cmd", required=True)

    m = sub.add_parser("master", help="run a master server")
    m.add_argument("-ip", default="127.0.0.1")
    m.add_argument("-port", type=int, default=9333)
    m.add_argument("-volume_size_limit_mb", type=int, default=30 * 1024)
    m.add_argument("-default_replication", default="000")
    m.add_argument("-peers", default="",
                   help="comma-separated ip:port of ALL masters (incl. self)"
                        " for raft HA (weed master -peers)")
    m.add_argument("-sequencer_kv", default="",
                   help="host:port of a redis-protocol KV; file keys are "
                        "leased from its atomic counter (etcd-sequencer "
                        "role) instead of the in-memory sequencer")
    m.add_argument("-mdir", default="",
                   help="directory for persisted raft state")
    m.add_argument("-pulse", type=float, default=5.0,
                   help="expected heartbeat interval (drives dead-node "
                        "pruning)")
    m.add_argument("-grpc_port", type=int, default=-1,
                   help="gRPC control-plane port (default HTTP+10000; "
                        "0 disables)")
    m.add_argument("-maintenance_interval", type=float, default=-1.0,
                   help="seconds between maintenance-daemon passes "
                        "(prune + repair planner; default: pulse, "
                        "0 disables the daemon)")
    m.add_argument("-repair_concurrency", type=int, default=None,
                   help="max concurrent repairs (re-replication / "
                        "auto ec.rebuild / lifecycle encodes) the "
                        "daemon drives; default WEED_EC_ENCODE_WORKERS "
                        "or 2")
    m.set_defaults(fn=cmd_master)

    v = sub.add_parser("volume", help="run a volume server")
    v.add_argument("-ip", default="127.0.0.1")
    v.add_argument("-port", type=int, default=8080)
    v.add_argument("-dir", default="./data")
    v.add_argument("-max", type=int, default=8)
    v.add_argument("-mserver", default="127.0.0.1:9333")
    v.add_argument("-dataCenter", dest="data_center", default="")
    v.add_argument("-rack", default="")
    v.add_argument("-pulse", type=float, default=5.0)
    v.add_argument("-coder", default="auto")
    v.add_argument("-index", default="memory", choices=["memory", "compact", "leveldb", "leveldbMedium", "leveldbLarge"],
                   help="needle map kind (weed volume -index)")
    v.add_argument("-minFreeSpacePercent", dest="min_free_space_percent",
                   type=float, default=1.0)
    v.add_argument("-preallocate", type=int, default=0,
                   help="MB to fallocate per new volume "
                        "(volume_create_linux.go)")
    v.add_argument("-grpc_heartbeat", action="store_true",
                   help="stream heartbeats over gRPC instead of HTTP "
                        "polling")
    v.add_argument("-grpc_port", type=int, default=-1,
                   help="gRPC admin/stream port (default HTTP+10000; "
                        "0 disables)")
    v.add_argument("-ec_large_block", type=int, default=1024 * 1024 * 1024)
    v.add_argument("-ec_small_block", type=int, default=1024 * 1024)
    v.set_defaults(fn=cmd_volume)

    s = sub.add_parser("server",
                       help="master + volume (+ filer + s3) in one process")
    s.add_argument("-ip", default="127.0.0.1")
    s.add_argument("-master_port", type=int, default=9333)
    s.add_argument("-port", type=int, default=8080)
    s.add_argument("-dir", default="./data")
    s.add_argument("-default_replication", default="000")
    s.add_argument("-coder", default="auto")
    s.add_argument("-ec_large_block", type=int, default=1024 * 1024 * 1024)
    s.add_argument("-ec_small_block", type=int, default=1024 * 1024)
    s.add_argument("-filer", action="store_true",
                   help="also run a filer (weed server -filer)")
    s.add_argument("-filer_port", type=int, default=8888)
    s.add_argument("-filer_db", default="./filer.db")
    s.add_argument("-s3", action="store_true",
                   help="also run the S3 gateway (needs -filer)")
    s.add_argument("-s3_port", type=int, default=8333)
    s.add_argument("-s3_config", default="",
                   help="JSON identities file for the embedded S3 gateway"
                        " (anonymous without it, like `weed s3`)")
    s.add_argument("-volume_workers", type=int, default=1,
                   help="extra volume-server worker PROCESSES (ports "
                        "port+1..port+N-1, own dirs): CPython's analog of "
                        "the reference's one multi-core Go server — "
                        "req/s scales with cores, the master spreads "
                        "assigns across workers")
    s.set_defaults(fn=cmd_server)

    f = sub.add_parser("filer", help="run a filer server")
    f.add_argument("-ip", default="127.0.0.1")
    f.add_argument("-port", type=int, default=8888)
    f.add_argument("-mserver", default="127.0.0.1:9333")
    f.add_argument("-store", default="sqlite",
                   help="metadata store: sqlite | memory | leveldb | "
                        "leveldb2 | redis | redis2 | etcd | mongodb | "
                        "elastic | cassandra")
    f.add_argument("-store_path", default="./filer.db")
    f.add_argument("-store_servers", default="",
                   help="host:port (or URL) for network stores (redis, "
                        "redis2, etcd, mongodb, elastic, cassandra)")
    f.add_argument("-chunk_size_mb", type=int, default=8)
    f.add_argument("-default_replication", default="")
    f.add_argument("-collection", default="")
    f.add_argument("-meta_log", default="",
                   help="path for the persisted metadata event log")
    f.add_argument("-encryptVolumeData", dest="encrypt_volume_data",
                   action="store_true",
                   help="AES-256-GCM encrypt chunk data on volume servers"
                        " (weed filer -encryptVolumeData)")
    f.add_argument("-peers", default="",
                   help="comma-separated peer filer host:port for "
                        "active-active metadata sync")
    f.add_argument("-ring_peers", default="",
                   help="comma-separated filer host:port members of the"
                        " metadata scale-out ring (partitioned"
                        " namespace; see also WEED_FILER_RING_*)")
    f.add_argument("-grpc_port", type=int, default=-1,
                   help="gRPC meta-plane port (default HTTP+10000; "
                        "0 disables)")
    f.set_defaults(fn=cmd_filer)

    w = sub.add_parser("watch", help="live-tail filer metadata events")
    w.add_argument("-filer", default="127.0.0.1:8888")
    w.add_argument("-pathPrefix", dest="path_prefix", default="/")
    w.add_argument("-since", type=int, default=0)
    w.set_defaults(fn=cmd_watch)

    fc = sub.add_parser("filer.copy",
                        help="copy files or whole folders to a filer "
                             "folder (weed filer.copy)")
    fc.add_argument("sources", nargs="+",
                    help="files or directories to upload")
    fc.add_argument("dest",
                    help="http://filer:port/path/to/folder/ (must end /)")
    fc.add_argument("-include", default="",
                    help="file name pattern, e.g. *.pdf")
    fc.add_argument("-concurrency", type=int, default=8)
    fc.add_argument("-collection", default="")
    fc.set_defaults(fn=cmd_filer_copy)

    fr = sub.add_parser("filer.replicate",
                        help="replicate filer changes into a sink "
                             "(replication.toml)")
    fr.add_argument("-filer", default="127.0.0.1:8888")
    fr.add_argument("-pathPrefix", dest="path_prefix", default="/")
    fr.add_argument("-offsetFile", dest="offset_file", default="",
                    help="resume-offset file (default derived from -filer)")
    fr.add_argument("-from_queue", action="store_true",
                    help="consume events from the [source.*] queue in "
                         "replication.toml instead of a live subscribe")
    fr.set_defaults(fn=cmd_filer_replicate)

    fsync = sub.add_parser("filer.sync",
                           help="active-active sync between two filers")
    fsync.add_argument("-a", required=True, help="filer A host:port")
    fsync.add_argument("-b", required=True, help="filer B host:port")
    fsync.add_argument("-pathPrefix", dest="path_prefix", default="/")
    fsync.add_argument("-offsetFile", dest="offset_file", default="",
                       help="resume-offset file stem (default: "
                            "~/.seaweedfs_tpu/offsets/, keyed by job)")
    fsync.set_defaults(fn=cmd_filer_sync)

    mt = sub.add_parser("mount", help="FUSE-mount a filer path")
    mt.add_argument("-filer", default="127.0.0.1:8888")
    mt.add_argument("-dir", required=True, help="local mountpoint")
    mt.add_argument("-collection", default="")
    mt.add_argument("-replication", default="")
    mt.add_argument("-chunk_size_mb", type=int, default=8)
    mt.set_defaults(fn=cmd_mount)

    wd = sub.add_parser("webdav", help="run the WebDAV gateway")
    wd.add_argument("-ip", default="127.0.0.1")
    wd.add_argument("-port", type=int, default=7333)
    wd.add_argument("-filer", default="127.0.0.1:8888")
    wd.set_defaults(fn=cmd_webdav)

    mb = sub.add_parser("msg.broker", help="run a pub/sub message broker")
    mb.add_argument("-ip", default="127.0.0.1")
    mb.add_argument("-port", type=int, default=17777)
    mb.add_argument("-filer", default="",
                    help="filer host:port for segment persistence "
                         "(empty: memory only)")
    mb.set_defaults(fn=cmd_msg_broker)

    s3p = sub.add_parser("s3", help="run the S3 gateway")
    s3p.add_argument("-ip", default="127.0.0.1")
    s3p.add_argument("-port", type=int, default=8333)
    s3p.add_argument("-filer", default="127.0.0.1:8888")
    s3p.add_argument("-access_key", default="")
    s3p.add_argument("-secret_key", default="")
    s3p.add_argument("-config", default="",
                     help="JSON identities file with per-action ACLs "
                          "(weed s3 -config)")
    s3p.set_defaults(fn=cmd_s3)

    u = sub.add_parser("upload", help="upload files")
    u.add_argument("-server", default="127.0.0.1:9333")
    u.add_argument("-collection", default="")
    u.add_argument("-replication", default="")
    u.add_argument("-ttl", default="")
    u.add_argument("files", nargs="+")
    u.set_defaults(fn=cmd_upload)

    d = sub.add_parser("download", help="download a file by fid")
    d.add_argument("-server", default="127.0.0.1:9333")
    d.add_argument("-output", default="-")
    d.add_argument("fid")
    d.set_defaults(fn=cmd_download)

    rm = sub.add_parser("delete", help="delete fids")
    rm.add_argument("-server", default="127.0.0.1:9333")
    rm.add_argument("fids", nargs="+")
    rm.set_defaults(fn=cmd_delete)

    sh = sub.add_parser("shell", help="admin shell (REPL or one-shot)")
    sh.add_argument("-server", default="127.0.0.1:9333")
    sh.add_argument("-filer", default="",
                    help="filer host:port for fs.*/bucket.*/fsck commands")
    sh.add_argument("-volume", type=int, default=0)
    sh.add_argument("-collection", default="")
    sh.add_argument("-dry_run", action="store_true")
    sh.add_argument("-ec_large_block", type=int, default=1024 * 1024 * 1024)
    sh.add_argument("-ec_small_block", type=int, default=1024 * 1024)
    sh.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="command + args (empty for interactive REPL)")
    sh.set_defaults(fn=cmd_shell)

    bk = sub.add_parser("backup", help="incrementally pull a volume locally")
    bk.add_argument("-server", default="127.0.0.1:9333")
    bk.add_argument("-dir", default="./backup")
    bk.add_argument("-collection", default="")
    bk.add_argument("-volumeId", type=int, required=True)
    bk.set_defaults(fn=cmd_backup)

    fx = sub.add_parser("fix", help="rebuild .idx by scanning .dat")
    fx.add_argument("-dir", default="./data")
    fx.add_argument("-collection", default="")
    fx.add_argument("-volumeId", type=int, required=True)
    fx.set_defaults(fn=cmd_fix)

    ex = sub.add_parser("export", help="export volume to tar")
    ex.add_argument("-dir", default="./data")
    ex.add_argument("-collection", default="")
    ex.add_argument("-volumeId", type=int, required=True)
    ex.add_argument("-output", default="volume.tar")
    ex.set_defaults(fn=cmd_export)

    cp = sub.add_parser("compact", help="offline vacuum of one volume")
    cp.add_argument("-dir", default="./data")
    cp.add_argument("-collection", default="")
    cp.add_argument("-volumeId", type=int, required=True)
    cp.set_defaults(fn=cmd_compact)

    st = sub.add_parser("status", help="cluster status")
    st.add_argument("-server", default="127.0.0.1:9333")
    st.set_defaults(fn=cmd_status)

    b = sub.add_parser("benchmark", help="write/read benchmark")
    b.add_argument("-server", default="127.0.0.1:9333")
    b.add_argument("-n", type=int, default=1000)
    b.add_argument("-size", type=int, default=1024)
    b.add_argument("-concurrency", type=int, default=16)
    b.set_defaults(fn=cmd_benchmark)

    sc = sub.add_parser("scaffold", help="emit default TOML config templates")
    sc.add_argument("-config", default="security",
                    help="security|filer|master|notification|replication")
    sc.add_argument("-output", default="",
                    help="directory to write <config>.toml into "
                         "(default: stdout)")
    sc.set_defaults(fn=cmd_scaffold)

    ver = sub.add_parser("version", help="print version")
    ver.set_defaults(fn=cmd_version)

    return p


def main(argv=None) -> None:
    import os as _os
    if _os.environ.get("SEAWEEDFS_FORCE_CPU"):
        # env-var JAX_PLATFORMS is overridden by eager site hooks (axon);
        # jax.config wins — used by multi-process tests and CPU-only ops
        import jax
        jax.config.update("jax_platforms", "cpu")
    args = build_parser().parse_args(argv)
    from .utils import glog
    glog.setup(args.verbosity, args.vmodule, args.log_file)
    if args.cpuprofile:
        from .observe.profiler import setup_cpu_profile
        setup_cpu_profile(args.cpuprofile)
    args.fn(args)


if __name__ == "__main__":
    main()
