"""Per-volume access-heat tracking — the signal the lifecycle plane runs on.

Volume servers sample their existing read/write paths (both the aiohttp
handlers and the fastpath listener's inline shapes) into a HeatTracker:
one dict update per request, no locks on the hot path beyond a cheap
mutex, no I/O.  Every heartbeat drains only the volumes touched since the
last beat ("send only changed entries"), so an idle 1000-volume node adds
ZERO bytes to its heartbeat and a busy one adds O(changed volumes).

The master folds those deltas into per-node VolumeHeat records
(topology/topology.py) keyed by volume id: cumulative read/write counts,
the last access timestamp, and a decayed-EWMA read rate (reads/second,
half-life HALFLIFE seconds) that the policy engine compares against
WEED_LIFECYCLE_HOT_READ_RATE to decide when a warm (EC) volume has turned
hot again.  first_seen exists so a freshly restarted master — which has
no access history at all — never mistakes "I just booted" for "idle for
weeks": idleness is measured from max(last_access, first_seen).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Iterable, Optional

# decayed-EWMA half-life for the read-rate signal (seconds): after one
# half-life with no reads the remembered rate halves
HALFLIFE = 600.0


def decayed_rate(rate: float, since: float, now: float,
                 halflife: float = HALFLIFE) -> float:
    """The EWMA read rate `rate` recorded at `since`, decayed to `now`."""
    if rate <= 0.0:
        return 0.0
    dt = max(now - since, 0.0)
    return rate * 0.5 ** (dt / halflife)


class HeatTracker:
    """Volume-server side: O(1) sampling + delta drain for heartbeats."""

    def __init__(self, halflife: float = HALFLIFE):
        self.halflife = halflife
        self._lock = threading.Lock()
        # vid -> [reads_delta, writes_delta, last_access, rate, last_drain]
        self._stats: dict[int, list] = {}
        self._dirty: set[int] = set()

    def _entry(self, vid: int) -> list:
        st = self._stats.get(vid)
        if st is None:
            st = self._stats[vid] = [0, 0, 0.0, 0.0, time.time()]
        return st

    def record_read(self, vid: int) -> None:
        now = time.time()
        with self._lock:
            st = self._entry(vid)
            st[0] += 1
            st[2] = now
            self._dirty.add(vid)

    def record_write(self, vid: int) -> None:
        now = time.time()
        with self._lock:
            st = self._entry(vid)
            st[1] += 1
            st[2] = now
            self._dirty.add(vid)

    def drop(self, vid: int) -> None:
        with self._lock:
            self._stats.pop(vid, None)
            self._dirty.discard(vid)

    def requeue(self, entries: Iterable[dict]) -> None:
        """Put drained deltas back after a failed delivery (heartbeat
        POST timed out / leader changed) so the window's access records
        ride the next beat instead of vanishing. Counts and last_access
        merge exactly; the EWMA rate may count the window twice (it was
        already folded at drain time) — a slightly-hot bias is the safe
        direction for a signal that gates destructive idle transitions."""
        with self._lock:
            for e in entries:
                st = self._entry(int(e["id"]))
                st[0] += int(e.get("reads", 0))
                st[1] += int(e.get("writes", 0))
                st[2] = max(st[2], float(e.get("last_access", 0.0)))
                self._dirty.add(int(e["id"]))

    def deltas(self, known_vids: Optional[Iterable[int]] = None
               ) -> list[dict]:
        """Drain the dirty set into heartbeat entries (changed volumes
        only — the heartbeat stays O(changed), not O(volumes)).  Passing
        known_vids also prunes tracker state for volumes this server no
        longer holds, so deleted/moved volumes don't pin memory."""
        now = time.time()
        out: list[dict] = []
        with self._lock:
            if known_vids is not None:
                known = set(known_vids)
                for vid in [v for v in self._stats if v not in known]:
                    self._stats.pop(vid, None)
                    self._dirty.discard(vid)
            for vid in sorted(self._dirty):
                st = self._stats.get(vid)
                if st is None:
                    continue
                reads, writes, last_access, rate, last_drain = st
                dt = max(now - last_drain, 1e-3)
                # EWMA over drain intervals: decay the old rate to now,
                # blend in this interval's instantaneous reads/second
                decay = 0.5 ** (dt / self.halflife)
                rate = decay * rate + (1.0 - decay) * (reads / dt)
                st[0] = st[1] = 0
                st[3] = rate
                st[4] = now
                out.append({"id": vid, "reads": reads, "writes": writes,
                            "last_access": last_access,
                            "read_rate": round(rate, 6)})
            self._dirty.clear()
        return out


@dataclass
class VolumeHeat:
    """Master-side per-node heat record, merged from heartbeat deltas."""
    reads: int = 0
    writes: int = 0
    last_access: float = 0.0
    read_rate: float = 0.0
    first_seen: float = field(default_factory=time.time)
    updated: float = field(default_factory=time.time)

    def merge(self, entry: dict, now: Optional[float] = None) -> None:
        now = now if now is not None else time.time()
        self.reads += int(entry.get("reads", 0))
        self.writes += int(entry.get("writes", 0))
        self.last_access = max(self.last_access,
                               float(entry.get("last_access", 0.0)))
        # the reporter's EWMA is authoritative — it saw every access
        self.read_rate = float(entry.get("read_rate", 0.0))
        self.updated = now

    def rate_now(self, now: Optional[float] = None) -> float:
        return decayed_rate(self.read_rate, self.updated,
                            now if now is not None else time.time())

    def to_dict(self, now: Optional[float] = None) -> dict:
        now = now if now is not None else time.time()
        return {"reads": self.reads, "writes": self.writes,
                "last_access": self.last_access,
                "read_rate": round(self.rate_now(now), 6),
                "first_seen": self.first_seen}
