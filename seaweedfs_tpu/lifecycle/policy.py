"""Lifecycle policy: declarative rules evaluated over topology + heat.

Pure planning (no sockets, no clocks of its own — `now` is an argument)
so the rules are unit-testable exactly like the shell's EC planners.  The
daemon (lifecycle/daemon.py) executes whatever this module plans.

Rules, all tuned by WEED_LIFECYCLE_* env knobs (see LifecycleConfig):

* hot -> warm: a volume that is FULL (read_only, or size past
  WEED_LIFECYCLE_FULL_FRACTION of the cluster volume-size limit) and
  IDLE (no access for WEED_LIFECYCLE_WARM_AFTER, measured from
  max(last_access, first_seen)) is sealed, vacuumed, and EC-encoded into
  the 14-shard warm tier — the reference's manual `ec.encode` shell flow
  (PAPER.md §L6) made time-driven.  S3 Transition rules can also nudge
  specific volumes here regardless of idleness (warm_requested).
* warm -> hot: an EC volume whose decayed read rate exceeds
  WEED_LIFECYCLE_HOT_READ_RATE (reads/s; 0 disables) is decoded back to
  a normal volume (`ec.decode`), so archive data that turns popular
  stops paying reconstruct-read latency.
* expiry: TTL volumes (superblock TTL) whose last write is older than
  the TTL plus WEED_LIFECYCLE_TTL_GRACE, and volumes of collections
  listed in WEED_LIFECYCLE_COLLECTION_TTL ("logs=3600,tmp=600", values
  in seconds), are deleted on every holder at once — whole-volume
  expiry, the cheap bulk path the per-needle TTL check can't give.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional

from ..storage.types import TTL
from .heat import HALFLIFE  # noqa: F401  (re-exported knob surface)


def parse_duration(s: str, default: float = 0.0) -> float:
    """'90'/'90s'/'15m'/'6h'/'7d' -> seconds (0/'' -> default)."""
    s = (s or "").strip().lower()
    if not s:
        return default
    mult = {"s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}
    try:
        if s[-1] in mult:
            return float(s[:-1]) * mult[s[-1]]
        return float(s)
    except ValueError:
        return default


def _parse_collection_ttls(spec: str) -> dict[str, float]:
    """'logs=3600,tmp=10m' -> {'logs': 3600.0, 'tmp': 600.0}."""
    out: dict[str, float] = {}
    for part in (spec or "").split(","):
        name, _, val = part.strip().partition("=")
        if not name or not val:
            continue
        secs = parse_duration(val)
        if secs > 0:
            out[name] = secs
    return out


@dataclass
class LifecycleConfig:
    """All WEED_LIFECYCLE_* knobs in one place (README "Data lifecycle")."""
    warm_after: float = 0.0          # WEED_LIFECYCLE_WARM_AFTER (0=off)
    hot_read_rate: float = 0.0       # WEED_LIFECYCLE_HOT_READ_RATE (0=off)
    interval: float = 60.0           # WEED_LIFECYCLE_INTERVAL
    filer: str = ""                  # WEED_LIFECYCLE_FILER (S3/TTL rules)
    day_seconds: float = 86400.0     # WEED_LIFECYCLE_DAY_SECONDS
    full_fraction: float = 0.9       # WEED_LIFECYCLE_FULL_FRACTION
    ttl_grace: float = 60.0          # WEED_LIFECYCLE_TTL_GRACE
    collection_ttls: dict[str, float] = field(default_factory=dict)
    scan_limit: int = 10000          # WEED_LIFECYCLE_S3_SCAN_LIMIT
    heat_export_top: int = 64        # WEED_LIFECYCLE_HEAT_EXPORT_TOP
    force_enabled: Optional[bool] = None  # WEED_LIFECYCLE_ENABLED override
    # WEED_EC_FUSED (default on): warm transitions use the one-pass
    # fused warm-down (compact + gzip + encode + digest, ec/fused.py)
    # instead of the chained vacuum -> ec/generate steps
    ec_fused: bool = True

    @property
    def enabled(self) -> bool:
        """The daemon runs only when some rule can actually fire (or the
        operator forces it): a cluster with no lifecycle rules must
        behave exactly as before this subsystem existed."""
        if self.force_enabled is not None:
            return self.force_enabled
        return bool(self.warm_after > 0 or self.hot_read_rate > 0
                    or self.collection_ttls or self.filer)

    @classmethod
    def from_env(cls, env: Optional[dict] = None) -> "LifecycleConfig":
        env = env if env is not None else os.environ
        force = env.get("WEED_LIFECYCLE_ENABLED", "")
        return cls(
            warm_after=parse_duration(
                env.get("WEED_LIFECYCLE_WARM_AFTER", "")),
            hot_read_rate=float(
                env.get("WEED_LIFECYCLE_HOT_READ_RATE", "0") or 0),
            interval=max(parse_duration(
                env.get("WEED_LIFECYCLE_INTERVAL", "60"), 60.0), 0.05),
            filer=env.get("WEED_LIFECYCLE_FILER", ""),
            day_seconds=max(parse_duration(
                env.get("WEED_LIFECYCLE_DAY_SECONDS", "86400"),
                86400.0), 0.001),
            full_fraction=float(
                env.get("WEED_LIFECYCLE_FULL_FRACTION", "0.9") or 0.9),
            ttl_grace=parse_duration(
                env.get("WEED_LIFECYCLE_TTL_GRACE", "60"), 60.0),
            collection_ttls=_parse_collection_ttls(
                env.get("WEED_LIFECYCLE_COLLECTION_TTL", "")),
            scan_limit=int(
                env.get("WEED_LIFECYCLE_S3_SCAN_LIMIT", "10000") or 10000),
            heat_export_top=int(
                env.get("WEED_LIFECYCLE_HEAT_EXPORT_TOP", "64") or 64),
            force_enabled=(None if force == ""
                           else force not in ("0", "false", "no")),
            ec_fused=env.get("WEED_EC_FUSED", "1") not in ("0", "false",
                                                           "no"),
        )


@dataclass
class Transition:
    kind: str            # "warm" | "unec" | "expire"
    vid: int
    collection: str
    reason: str
    holders: list = field(default_factory=list)   # urls with the volume
    ec_holders: list = field(default_factory=list)  # urls with shards

    @property
    def key(self) -> tuple:
        return (self.kind, self.vid)


def _ttl_seconds(ttl_str: str) -> float:
    try:
        return TTL.parse(ttl_str).minutes() * 60.0
    except ValueError:
        return 0.0


def plan_transitions(topology, heat_view: dict, cfg: LifecycleConfig,
                     now: float,
                     warm_requested: Optional[dict] = None
                     ) -> list[Transition]:
    """Evaluate every rule against the cluster view; returns the
    transitions that are due this pass (the daemon applies in-flight /
    backoff gating on top).  `topology` is the master's Topology object;
    `heat_view` is Topology.heat_view(now); `warm_requested` maps vid ->
    reason for S3-Transition-nudged volumes."""
    warm_requested = warm_requested or {}
    out: list[Transition] = []

    # vid -> (VolumeInfo, [holder urls]) over normal volumes
    vols: dict[int, tuple] = {}
    for node in topology.nodes.values():
        for vid, vi in node.volumes.items():
            info = vols.get(vid)
            if info is None:
                vols[vid] = (vi, [node.url])
            else:
                info[1].append(node.url)
    # vid -> (collection, {shard ids}, [holder urls]) over EC volumes
    ecs: dict[int, tuple] = {}
    for node in topology.nodes.values():
        for vid, si in node.ec_shards.items():
            info = ecs.get(vid)
            if info is None:
                ecs[vid] = (si.collection, set(si.shard_ids), [node.url])
            else:
                info[1].update(si.shard_ids)
                info[2].append(node.url)

    vacuuming = {vid for layout in topology.layouts.values()
                 for vid in layout.vacuuming}

    for vid, (vi, holders) in sorted(vols.items()):
        h = heat_view.get(vid, {})
        last = max(h.get("last_access", 0.0), h.get("first_seen", now))
        ttl_secs = _ttl_seconds(vi.ttl)
        col_ttl = cfg.collection_ttls.get(vi.collection, 0.0)

        # --- expiry (whole-volume, all holders at once) ---
        expire_after = min((s for s in (ttl_secs, col_ttl) if s > 0),
                           default=0.0)
        if expire_after > 0:
            # anchor on the newest write/access the cluster has seen;
            # first_seen only as the fallback for a volume that has
            # never reported either (a brand-new empty TTL volume must
            # not expire out from under an in-flight assignment)
            written = max(getattr(vi, "last_modified", 0) or 0.0,
                          h.get("last_access", 0.0))
            if written <= 0:
                written = h.get("first_seen", now)
            if now >= written + expire_after + cfg.ttl_grace:
                out.append(Transition(
                    "expire", vid, vi.collection,
                    f"ttl {expire_after:.0f}s elapsed", holders=holders))
            continue  # an expiring volume never also goes warm

    # --- hot -> warm (idle sealed volumes, or S3-transition nudges) ---
        if vid in vacuuming:
            continue
        requested = vid in warm_requested
        idle = (cfg.warm_after > 0
                and now - last >= cfg.warm_after)
        if vid in ecs:
            # dual state: a shard set exists ALONGSIDE the original —
            # a transition crashed between shard mount and retirement.
            # Resume it (the daemon retires the original if the set is
            # complete, re-encodes if not) — but ONLY while the volume
            # is still idle (or explicitly requested): a volume that
            # was just un-EC'd back to hot also shows this dual state
            # through one stale-heartbeat window, and planning a resume
            # there would re-retire the freshly decoded copy. Idleness
            # distinguishes the two: a crashed warm transition's volume
            # stays idle (it qualified by being idle), an un-EC'd one
            # is hot by definition.
            if requested or idle:
                out.append(Transition(
                    "warm", vid, vi.collection,
                    "resume: shard set alongside original",
                    holders=holders))
            continue
        sealed = (vi.read_only
                  or vi.size >= cfg.full_fraction
                  * topology.volume_size_limit)
        if requested or (sealed and idle):
            reason = (warm_requested.get(vid) if requested
                      else f"idle {now - last:.0f}s >= "
                           f"{cfg.warm_after:.0f}s")
            out.append(Transition("warm", vid, vi.collection, reason,
                                  holders=holders))

    # --- expiry of warm (EC-only) volumes: a collection TTL added
    # AFTER data was tiered must still expire it (compliance rules
    # don't care which tier holds the bytes). EC volumes carry no
    # superblock/last_modified here, so the anchor is the newest
    # access the cluster has seen (first_seen as the conservative
    # fallback: at worst expiry waits one TTL from master boot).
    expiring_ec: set[int] = set()
    for vid, (collection, shard_ids, holders) in sorted(ecs.items()):
        if vid in vols:
            continue  # dual state is the warm-resume rule's business
        col_ttl = cfg.collection_ttls.get(collection, 0.0)
        if col_ttl <= 0:
            continue
        h = heat_view.get(vid, {})
        anchor = max(h.get("last_access", 0.0), h.get("first_seen", now))
        if now >= anchor + col_ttl + cfg.ttl_grace:
            expiring_ec.add(vid)
            out.append(Transition(
                "expire", vid, collection,
                f"collection ttl {col_ttl:.0f}s elapsed (warm tier)",
                ec_holders=holders))

    # --- warm -> hot (reconstruct-read rate above threshold) ---
    if cfg.hot_read_rate > 0:
        for vid, (collection, shard_ids, holders) in sorted(ecs.items()):
            if vid in vols:
                continue  # mid-transition: a normal copy still exists
            if vid in expiring_ec:
                continue  # expiring data never also decodes back
            h = heat_view.get(vid, {})
            rate = h.get("read_rate", 0.0)
            if rate >= cfg.hot_read_rate:
                out.append(Transition(
                    "unec", vid, collection,
                    f"read rate {rate:.2f}/s >= {cfg.hot_read_rate}/s",
                    ec_holders=holders))
    return out
