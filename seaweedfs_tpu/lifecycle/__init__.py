"""Lifecycle plane: heat-driven hot->warm tiering, TTL expiry, S3 rules.

The EC tier (sealed volumes Reed-Solomon-encoded RS(10,4) into 14
rack-spread shards) was fast, self-healing, and load-safe — but nothing
ever *decided* to use it: volumes only went warm when an operator typed
`ec.encode`.  This package is that decision-maker, in three layers:

1. **Heat tracking** (heat.py): volume servers sample their existing
   read/write paths (fastpath listener included) into per-volume access
   stats — counts, last access, decayed-EWMA read rate — and report
   only the CHANGED entries in each heartbeat; the master topology keeps
   the cluster heat view, exported via /metrics, `GET /vol/heat`, and
   the `volume.heat` shell command.

2. **Policy + daemon** (policy.py, daemon.py): a leader-only daemon on
   the master — sibling of the repair daemon, sharing its concurrency
   semaphore, backoff bookkeeping, and the overload plane's CLASS_BG
   priority so lifecycle work is shed first under load — evaluates
   declarative rules every WEED_LIFECYCLE_INTERVAL: full+idle volumes
   seal, vacuum, and EC-encode through the governed feed; hot EC
   volumes optionally decode back; TTL'd volumes/collections expire
   whole volumes at once.  Every transition emits `lifecycle.*` spans
   and `lifecycle_transitions{kind,outcome}` metrics, and is resumable:
   a crash mid-encode leaves either the original volume or the full
   shard set, never neither, and the daemon converges on retry.

3. **S3 surface** (s3_rules.py + s3/s3_server.py):
   Put/Get/DeleteBucketLifecycleConfiguration with Expiration and
   Transition(StorageClass=WARM) rules stored on the filer and enforced
   by the same daemon.

Every background loop here binds overload.CLASS_BG and sleeps on
``jittered(interval)`` — tests/test_async_guard.py fails the build on
any lifecycle loop that is unshedable or fires in fleet lockstep.
"""

from __future__ import annotations

import random

from .heat import HALFLIFE, HeatTracker, VolumeHeat, decayed_rate
from .policy import (LifecycleConfig, Transition, parse_duration,
                     plan_transitions)


def jittered(seconds: float, spread: float = 0.2) -> float:
    """An interval with +/-(spread/2) relative jitter: a fleet of masters
    (or a master and its volume servers) must not fire lifecycle scans in
    lockstep against the same volume servers."""
    lo = 1.0 - spread / 2.0
    return max(seconds, 0.01) * (lo + spread * random.random())


__all__ = [
    "HALFLIFE", "HeatTracker", "VolumeHeat", "decayed_rate",
    "LifecycleConfig", "Transition", "parse_duration",
    "plan_transitions", "jittered",
]
