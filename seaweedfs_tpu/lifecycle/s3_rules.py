"""S3 bucket lifecycle configuration — the supported XML subset.

PutBucketLifecycleConfiguration / GetBucketLifecycleConfiguration store a
parsed-rule JSON document in the bucket directory entry's extended
attributes (the same place object tags live), and the master's lifecycle
daemon enforces it through the filer's /__meta__ API.

Supported subset (everything else is rejected as MalformedXML rather
than silently dropped — a rule the daemon won't enforce must not look
accepted):

  <LifecycleConfiguration>
    <Rule>
      <ID>optional</ID>
      <Filter><Prefix>logs/</Prefix></Filter>   (or bare <Prefix>)
      <Status>Enabled|Disabled</Status>
      <Expiration><Days>N</Days></Expiration>
      <Transition>
        <Days>N</Days><StorageClass>WARM</StorageClass>
      </Transition>
    </Rule>
  </LifecycleConfiguration>

Transition's only storage class is WARM — this cluster's warm tier is
the RS(10,4) EC layer, so a Transition rule marks aged objects
x-amz-storage-class: WARM and nudges the volumes holding their chunks
into the hot->warm EC transition.
"""

from __future__ import annotations

import json
import xml.etree.ElementTree as ET

XMLNS = "http://s3.amazonaws.com/doc/2006-03-01/"

# the extended-attribute key on the bucket directory entry
BUCKET_ATTR = "seaweed-lifecycle"
# the extended-attribute key on object entries
STORAGE_CLASS_ATTR = "x-amz-storage-class"
WARM_CLASS = "WARM"

MAX_RULES = 100


class LifecycleXmlError(ValueError):
    pass


def _strip(tag: str) -> str:
    return tag.split("}", 1)[1] if tag.startswith("{") else tag


def _find(el, name):
    for child in el:
        if _strip(child.tag) == name:
            return child
    return None


def parse_lifecycle_xml(body: bytes) -> list[dict]:
    """XML -> [{id, status, prefix, expire_days, transition_days,
    transition_class}] — raises LifecycleXmlError on anything outside
    the supported subset."""
    try:
        root = ET.fromstring(body)
    except ET.ParseError as e:
        raise LifecycleXmlError(str(e))
    if _strip(root.tag) != "LifecycleConfiguration":
        raise LifecycleXmlError(
            f"expected LifecycleConfiguration, got {_strip(root.tag)}")
    rules: list[dict] = []
    for rule_el in root:
        if _strip(rule_el.tag) != "Rule":
            raise LifecycleXmlError(
                f"unexpected element {_strip(rule_el.tag)}")
        rule = {"id": "", "status": "Enabled", "prefix": "",
                "expire_days": None, "transition_days": None,
                "transition_class": ""}
        for el in rule_el:
            name = _strip(el.tag)
            if name == "ID":
                rule["id"] = el.text or ""
            elif name == "Status":
                if el.text not in ("Enabled", "Disabled"):
                    raise LifecycleXmlError(f"bad Status {el.text!r}")
                rule["status"] = el.text
            elif name == "Prefix":
                rule["prefix"] = el.text or ""
            elif name == "Filter":
                pfx = _find(el, "Prefix")
                rule["prefix"] = (pfx.text or "") if pfx is not None else ""
            elif name == "Expiration":
                days = _find(el, "Days")
                if days is None:
                    raise LifecycleXmlError(
                        "only Expiration/Days is supported")
                rule["expire_days"] = _days(days.text)
            elif name == "Transition":
                days = _find(el, "Days")
                cls = _find(el, "StorageClass")
                if days is None or cls is None:
                    raise LifecycleXmlError(
                        "Transition needs Days and StorageClass")
                if (cls.text or "").upper() != WARM_CLASS:
                    raise LifecycleXmlError(
                        f"unsupported StorageClass {cls.text!r} "
                        f"(only {WARM_CLASS})")
                rule["transition_days"] = _days(days.text)
                rule["transition_class"] = WARM_CLASS
            else:
                raise LifecycleXmlError(f"unsupported element {name}")
        if rule["expire_days"] is None and rule["transition_days"] is None:
            raise LifecycleXmlError(
                "rule needs an Expiration or a Transition")
        rules.append(rule)
    if not rules:
        raise LifecycleXmlError("no rules")
    if len(rules) > MAX_RULES:
        raise LifecycleXmlError(f"more than {MAX_RULES} rules")
    return rules


def _days(text) -> float:
    try:
        days = float(text)
    except (TypeError, ValueError):
        raise LifecycleXmlError(f"bad Days {text!r}")
    if days < 0:
        raise LifecycleXmlError("Days must be >= 0")
    return days


def rules_to_xml(rules: list[dict]) -> bytes:
    root = ET.Element("LifecycleConfiguration", xmlns=XMLNS)
    for rule in rules:
        r = ET.SubElement(root, "Rule")
        if rule.get("id"):
            ET.SubElement(r, "ID").text = rule["id"]
        f = ET.SubElement(r, "Filter")
        ET.SubElement(f, "Prefix").text = rule.get("prefix", "")
        ET.SubElement(r, "Status").text = rule.get("status", "Enabled")
        if rule.get("expire_days") is not None:
            e = ET.SubElement(r, "Expiration")
            ET.SubElement(e, "Days").text = _fmt_days(rule["expire_days"])
        if rule.get("transition_days") is not None:
            t = ET.SubElement(r, "Transition")
            ET.SubElement(t, "Days").text = _fmt_days(
                rule["transition_days"])
            ET.SubElement(t, "StorageClass").text = WARM_CLASS
    return (b'<?xml version="1.0" encoding="UTF-8"?>\n'
            + ET.tostring(root))


def _fmt_days(days: float) -> str:
    return str(int(days)) if float(days).is_integer() else str(days)


def rules_to_json(rules: list[dict]) -> str:
    return json.dumps(rules, sort_keys=True)


def rules_from_json(raw: str) -> list[dict]:
    try:
        rules = json.loads(raw)
    except (TypeError, ValueError):
        return []
    return rules if isinstance(rules, list) else []
