"""Leader-only lifecycle daemon: executes what policy.py plans.

Runs on the master as a sibling of the PR 4 repair daemon and shares its
discipline end to end:

* leader-only — a follower's stale topology must never seal or delete a
  volume, and two masters must never both drive one transition;
* the SAME concurrency semaphore as the repair planner
  (master._repair_sem), so lifecycle encodes and deficit rebuilds
  compete for one bounded budget instead of stampeding volume servers;
* the SAME per-key exponential-backoff bookkeeping
  (master._repair_backoff), so a transition that keeps failing retries
  at 2^n * interval, capped;
* overload CLASS_BG priority bound for the daemon loop and re-stamped in
  every transition task, so every admin call it fans out carries
  X-Seaweed-Priority: bg and is shed FIRST under load (PR 6).

Transitions are crash-safe by ordering, not by journal: the original
volume is deleted only after every one of the 14 shards is verified
mounted on its target (a /status read-back, not a trusted response), so
a crash at ANY point leaves either the original volume or a complete
shard set — never neither — and the next pass converges (shards already
live -> just retire the original; shards incomplete -> re-encode).
Named fault points (`lifecycle.warm`, `lifecycle.encode`,
`lifecycle.unec`, `lifecycle.expire`) let the chaos suite kill a
transition at the worst moment and prove exactly that.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from collections import deque
from dataclasses import asdict
from typing import Optional

import aiohttp

from .. import faults, observe, overload
from ..storage.file_id import FileId
from . import s3_rules
from .policy import LifecycleConfig, Transition, plan_transitions
from . import jittered

log = logging.getLogger("lifecycle")


class _EncodeBatcher:
    """Queue-aware encode batching: warm transitions that reach their
    encode step while others are in flight coalesce into ONE
    multi-volume ``ec/generate`` POST per source server, so the volume
    server streams the whole window through a single governed [k, B]
    executable back-to-back (store.ec_generate_many ->
    pipeline.stream_encode_many) instead of paying a program load per
    volume. Window size bounds via WEED_EC_ENCODE_WINDOW (default 8);
    a short linger lets near-simultaneous transitions land in one
    window without delaying a lone volume meaningfully."""

    def __init__(self, daemon: "LifecycleDaemon", linger: float = 0.5):
        self.daemon = daemon
        self.linger = linger
        try:
            self.max_window = max(
                1, int(os.environ.get("WEED_EC_ENCODE_WINDOW", "8")))
        except ValueError:
            self.max_window = 8
        # (source url, fused?) -> [(vid, future)] awaiting the next
        # window — fused warm-downs window SEPARATELY from plain
        # encodes: they hit a different endpoint (ec/fused) and a mixed
        # window would force half the batch through the wrong pass
        self._waiting: dict[tuple, list] = {}

    async def encode(self, source: str, vid: int,
                     fused: bool = False) -> None:
        fut = asyncio.get_event_loop().create_future()
        key = (source, fused)
        batch = self._waiting.setdefault(key, [])
        batch.append((vid, fut))
        if len(batch) >= self.max_window:
            self._waiting.pop(key, None)
            await self._post(key, batch)
        elif len(batch) == 1:
            task = asyncio.create_task(self._flush_after(key, batch))
            self.daemon._tasks.add(task)
            task.add_done_callback(self.daemon._tasks.discard)
        await fut

    async def _flush_after(self, key: tuple, batch: list) -> None:
        await asyncio.sleep(self.linger)
        # flush only OUR batch: if a full window already flushed it (and
        # a newer batch is forming under the same source), this stale
        # linger must not fire the newer batch early
        if self._waiting.get(key) is batch:
            self._waiting.pop(key, None)
            await self._post(key, batch)

    async def _post(self, key: tuple, batch: list) -> None:
        source, fused = key
        vids = [vid for vid, _ in batch]
        body = ({"volume_id": vids[0]} if len(vids) == 1
                else {"volume_ids": vids})
        try:
            await self.daemon.master._admin_post(
                source, "ec/fused" if fused else "ec/generate", body,
                timeout=900.0 * len(vids))
        except Exception as e:
            for _, fut in batch:
                if not fut.done():
                    fut.set_exception(e)
        else:
            for _, fut in batch:
                if not fut.done():
                    fut.set_result(None)


class LifecycleDaemon:
    def __init__(self, master, cfg: Optional[LifecycleConfig] = None):
        self.master = master
        self.cfg = cfg or LifecycleConfig.from_env()
        # key -> monotonic start time of the in-flight transition
        self._inflight: dict[tuple, float] = {}
        self._tasks: set = set()
        self.recent: deque = deque(maxlen=64)
        self.last_pass = 0.0
        self.passes = 0
        # vid -> reason, fed by S3 Transition rules: these volumes go
        # warm on the next pass regardless of idleness
        self.warm_requested: dict[int, str] = {}
        # coalesces concurrent warm transitions' encode steps into
        # multi-volume windows per source (one governed executable)
        self._encode_batcher = _EncodeBatcher(self)

    # --- loop ---

    async def run_loop(self) -> None:
        # lifecycle work is background by definition: every admin call
        # the daemon (and its transition tasks) fans out carries
        # X-Seaweed-Priority: bg and sheds before user traffic
        overload.set_priority(overload.CLASS_BG)
        while True:
            await asyncio.sleep(jittered(self.cfg.interval))
            try:
                await self.pass_once()
            except asyncio.CancelledError:
                raise
            except Exception as e:
                log.warning("lifecycle pass failed: %s", e)

    def stop(self) -> None:
        for task in list(self._tasks):
            task.cancel()

    # --- one evaluation pass ---

    async def pass_once(self) -> dict:
        master = self.master
        if not master.raft.is_leader or not await master.raft.ensure_ready():
            return {"skipped": "not leader"}
        now = time.time()
        self.last_pass = now
        self.passes += 1
        s3 = {}
        if self.cfg.filer:
            try:
                s3 = await self._s3_pass(now)
            except Exception as e:
                log.warning("lifecycle s3 pass failed: %s", e)
                s3 = {"error": str(e)}
        heat = master.topology.heat_view(now)
        plan = plan_transitions(master.topology, heat, self.cfg, now,
                                self.warm_requested)
        launched = []
        for tr in plan:
            if not self._due(tr.key):
                continue
            self._launch(tr)
            launched.append({"kind": tr.kind, "volume": tr.vid,
                             "reason": tr.reason})
        self.export_gauges(heat)
        return {"planned": len(plan), "launched": launched, "s3": s3}

    def _due(self, key: tuple) -> bool:
        if key in self._inflight:
            return False
        back = self.master._repair_backoff.get(key)
        if back is not None and time.monotonic() < back[1]:
            return False
        return True

    def _launch(self, tr: Transition) -> None:
        self._inflight[tr.key] = time.monotonic()
        task = asyncio.create_task(self._run_transition(tr))
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    async def _run_transition(self, tr: Transition) -> None:
        # explicit stamp: transitions can also be launched from the
        # /lifecycle/run admin path, outside the bg-tagged loop context
        overload.set_priority(overload.CLASS_BG)
        key = tr.key
        fn = {"warm": self._warm, "unec": self._unec,
              "expire": self._expire}[tr.kind]
        try:
            async with self.master._repair_sem:
                # same numbered worker pool as the repair daemon
                # (WEED_EC_ENCODE_WORKERS): a storm of warm transitions
                # and a rebuild storm drain through one visible budget
                worker = self.master._checkout_worker()
                log.info("encode worker %d: lifecycle %s of volume %s "
                         "(trace %s)", worker, tr.kind, tr.vid,
                         observe.ensure_ctx("master").trace_id)
                try:
                    with observe.span(f"lifecycle.{tr.kind}",
                                      tags={"vid": tr.vid,
                                            "reason": tr.reason,
                                            "worker": worker}):
                        await fn(tr)
                finally:
                    self.master._checkin_worker(worker)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            failures = self.master._repair_backoff.get(key, (0, 0.0))[0] + 1
            delay = min(self.cfg.interval * (2 ** failures), 300.0)
            self.master._repair_backoff[key] = (failures,
                                                time.monotonic() + delay)
            self._record(tr.kind, tr.vid, "failed", error=str(e))
            log.warning("lifecycle %s of volume %d failed (attempt %d, "
                        "next in %.1fs): %s", tr.kind, tr.vid, failures,
                        delay, e)
        else:
            self.master._repair_backoff.pop(key, None)
            if tr.kind == "warm":
                self.warm_requested.pop(tr.vid, None)
            self._record(tr.kind, tr.vid, "ok", reason=tr.reason)
            log.info("lifecycle %s of volume %d done (%s)",
                     tr.kind, tr.vid, tr.reason)
        finally:
            self._inflight.pop(key, None)

    def _record(self, kind: str, vid, outcome: str, reason: str = "",
                error: str = "") -> None:
        self.master.metrics.count("lifecycle_transitions",
                                  labels={"kind": kind,
                                          "outcome": outcome})
        entry = {"kind": kind, "volume": vid, "outcome": outcome,
                 "at": time.time()}
        if reason:
            entry["reason"] = reason
        if error:
            entry["error"] = error
        self.recent.appendleft(entry)

    # --- plumbing ---

    def _check_leader(self) -> None:
        if not self.master.raft.is_leader:
            raise RuntimeError("lost leadership mid-transition")

    async def _get_json(self, url: str, path: str,
                        timeout: float = 30.0) -> dict:
        async with self.master._maint_http().get(
                f"http://{url}{path}",
                timeout=aiohttp.ClientTimeout(total=timeout)) as r:
            out = await r.json()
            if r.status != 200:
                raise RuntimeError(f"{url}{path}: "
                                   f"{out.get('error', r.status)}")
            return out

    # --- hot -> warm: seal, vacuum, ec.encode through the governed feed ---

    async def _warm(self, tr: Transition) -> None:
        master = self.master
        vid, collection = tr.vid, tr.collection
        if await faults.fire_async("lifecycle.warm"):
            raise RuntimeError("injected drop at lifecycle.warm")
        total = master.ec_total_shards_for(collection)
        # resumable finish: a prior attempt (or crash) already produced
        # the full shard set — only the original is left to retire.
        # The topology view can be STALE (an un-EC that just deleted
        # every shard file still lists them until heartbeats land), so
        # nothing is destroyed on its word alone: re-verify by reading
        # each holder's /status back, and back off if they disagree.
        shards = master.topology.lookup_ec_shards(vid)
        if len(shards) >= total:
            shard_holders = {n.url for nodes in shards.values()
                             for n in nodes}
            mounted = await self._mounted_shards(vid, shard_holders)
            if len(mounted) < total:
                raise RuntimeError(
                    f"volume {vid}: topology lists a full shard set but "
                    f"only {sorted(mounted)} verified mounted — stale "
                    f"view, retrying after the next heartbeats")
            await self._finish_warm(vid, tr.holders)
            return
        holders = tr.holders
        if not holders:
            raise RuntimeError(f"volume {vid} has no holders")
        # 1. seal every replica (the volume stops taking writes NOW;
        #    heartbeats move it out of the writable set)
        for url in holders:
            self._check_leader()
            await master._admin_post(url, "volume/readonly",
                                     {"volume_id": vid,
                                      "read_only": True})
        source = holders[0]
        if self.cfg.ec_fused:
            # 2+3 fused (WEED_EC_FUSED, default on): the one-pass
            # warm-down compacts, gzips, encodes and digests in a
            # single governed pass on the source (ec/fused.py via
            # store.ec_fused_generate) — no separate vacuum round-trip,
            # and the shard set holds the compacted volume either way.
            # Same verify-then-retire discipline below: the source
            # volume survives untouched until 14/14 mounted shards are
            # read back.
            self._check_leader()
            await self._encode_batcher.encode(source, vid, fused=True)
        else:
            # 2. vacuum when compaction would actually shrink the .dat —
            #    encoding tombstoned bytes into 14 shards wastes the tier
            try:
                garbage = (await self._get_json(
                    source, f"/admin/vacuum/check?volume_id={vid}")
                )["garbage_level"]
            except Exception:
                garbage = 0.0
            if garbage > 0.01:
                await master._admin_post(source, "vacuum",
                                         {"volume_id": vid},
                                         timeout=600.0)
            # 3. encode on the source through the governed EC feed —
            #    via the encode batcher, so a burst of warm transitions
            #    sharing a source streams as ONE multi-volume window
            #    through a single governed executable
            #    (store.ec_generate_many)
            self._check_leader()
            await self._encode_batcher.encode(source, vid)
        # 4. spread with the same balanced plan the ec.encode shell uses
        from ..shell.ec_commands import collect_ec_nodes, plan_shard_spread
        nodes = collect_ec_nodes(master.topology.to_dict())
        plan = plan_shard_spread(nodes, total, source)
        for target, sids in plan.items():
            self._check_leader()
            if target != source:
                await master._admin_post(
                    target, "ec/copy",
                    {"volume_id": vid, "collection": collection,
                     "shard_ids": sids, "source": source,
                     "copy_ecx_file": True}, timeout=600.0)
            await master._admin_post(
                target, "ec/mount",
                {"volume_id": vid, "collection": collection,
                 "shard_ids": sids})
        # verify 14/14 by reading each target's /status back — mount
        # responses alone don't distinguish "already mounted" from
        # "shard file missing"; nothing is destroyed on trust
        mounted = await self._mounted_shards(vid, plan)
        if len(mounted) < total:
            raise RuntimeError(
                f"volume {vid}: only shards {sorted(mounted)} mounted "
                f"({len(mounted)}/{total}); keeping the original")
        # 5. retire the original everywhere + surplus shard files at
        #    the source (generate left all 14 there; it mounted only
        #    its assigned ones)
        await self._finish_warm(vid, holders)
        surplus = [s for s in range(total) if s not in plan.get(source, [])]
        if surplus:
            await master._admin_post(
                source, "ec/delete_shards",
                {"volume_id": vid, "collection": collection,
                 "shard_ids": surplus})

    async def _mounted_shards(self, vid: int, targets) -> set:
        """Shard ids ACTUALLY mounted for `vid`, by reading each
        target's /status back — the only evidence the daemon trusts
        before destroying anything."""
        mounted: set[int] = set()
        for target in targets:
            st = await self._get_json(target, "/status")
            for s in st.get("ec_shards", []):
                if s.get("id") == vid:
                    mounted.update(s.get("shard_ids", []))
        return mounted

    async def _finish_warm(self, vid: int, holders: list) -> None:
        """The last step of a warm transition — shared by the fresh path
        and the crash-resume path so BOTH cross the same chaos hook: the
        worst crash point is 'full shard set live, original not yet
        retired'; both copies exist there and a retry converges."""
        if await faults.fire_async("lifecycle.encode"):
            raise RuntimeError("injected drop at lifecycle.encode")
        for url in holders:
            self._check_leader()
            await self.master._admin_post(url, "volume/delete",
                                          {"volume_id": vid})

    # --- warm -> hot: un-EC a reconstruct-hot volume (ec.decode flow) ---

    async def _unec(self, tr: Transition) -> None:
        master = self.master
        vid, collection = tr.vid, tr.collection
        if await faults.fire_async("lifecycle.unec"):
            raise RuntimeError("injected drop at lifecycle.unec")
        shards = master.topology.lookup_ec_shards(vid)
        if not shards:
            raise RuntimeError(f"no shards for volume {vid}")
        total = master.ec_total_shards_for(collection)
        holder_count: dict[str, int] = {}
        for nodes in shards.values():
            for n in nodes:
                holder_count[n.url] = holder_count.get(n.url, 0) + 1
        target = max(holder_count, key=holder_count.get)
        need = [sid for sid, nodes in sorted(shards.items())
                if target not in {n.url for n in nodes}]
        for sid in need:
            self._check_leader()
            await master._admin_post(
                target, "ec/copy",
                {"volume_id": vid, "collection": collection,
                 "shard_ids": [sid], "source": shards[sid][0].url},
                timeout=600.0)
        self._check_leader()
        await master._admin_post(target, "ec/to_volume",
                                 {"volume_id": vid,
                                  "collection": collection},
                                 timeout=900.0)
        # the decoded volume is live on the target: drop shard files
        # everywhere (the target's copies were consumed by the decode)
        urls = {n.url for nodes in shards.values() for n in nodes}
        urls.add(target)
        for url in sorted(urls):
            await master._admin_post(
                url, "ec/delete_shards",
                {"volume_id": vid, "collection": collection,
                 "shard_ids": list(range(total))})

    # --- TTL expiry: whole volumes at once, every holder ---

    async def _expire(self, tr: Transition) -> None:
        master = self.master
        if await faults.fire_async("lifecycle.expire"):
            raise RuntimeError("injected drop at lifecycle.expire")
        for url in tr.holders:
            self._check_leader()
            await master._admin_post(url, "volume/delete",
                                     {"volume_id": tr.vid})
        # an expired collection that was EC-encoded loses its shards too
        shards = master.topology.lookup_ec_shards(tr.vid)
        urls = {n.url for nodes in shards.values() for n in nodes}
        for url in sorted(urls):
            await master._admin_post(
                url, "ec/delete_shards",
                {"volume_id": tr.vid, "collection": tr.collection,
                 "shard_ids": list(range(
                     master.ec_total_shards_for(tr.collection)))})

    # --- S3 bucket rules: Expiration + Transition(WARM), via the filer ---

    async def _filer_get(self, path: str, params: dict) -> tuple[int, dict]:
        async with self.master._maint_http().get(
                f"http://{self.cfg.filer}{path}", params=params,
                timeout=aiohttp.ClientTimeout(total=60)) as r:
            return r.status, await r.json()

    async def _filer_post(self, path: str, body: dict) -> tuple[int, dict]:
        async with self.master._maint_http().post(
                f"http://{self.cfg.filer}{path}", json=body,
                timeout=aiohttp.ClientTimeout(total=60)) as r:
            return r.status, await r.json()

    async def _s3_pass(self, now: float) -> dict:
        stats = {"expired": 0, "transitioned": 0, "scanned": 0}
        # paginate the bucket listing itself — a rule on bucket #1001
        # must be enforced exactly like one on bucket #1
        start = ""
        while True:
            status, body = await self._filer_get(
                "/__meta__/list", {"dir": "/buckets", "start": start,
                                   "limit": "512"})
            if status != 200:
                return stats
            entries = body.get("entries", [])
            for bucket_entry in entries:
                name = bucket_entry["path"].rsplit("/", 1)[-1]
                if name.startswith("."):
                    continue
                raw = (bucket_entry.get("extended") or {}).get(
                    s3_rules.BUCKET_ATTR)
                if not raw:
                    continue
                rules = [r for r in s3_rules.rules_from_json(raw)
                         if r.get("status") == "Enabled"]
                if not rules:
                    continue
                with observe.span("lifecycle.s3", tags={"bucket": name}):
                    await self._apply_bucket_rules(
                        name, bucket_entry["path"], rules, now, stats)
            if len(entries) < 512:
                return stats
            start = entries[-1]["path"].rsplit("/", 1)[-1]

    async def _apply_bucket_rules(self, bucket: str, base: str,
                                  rules: list, now: float,
                                  stats: dict) -> None:

        async def walk(dir_path: str, key_prefix: str) -> None:
            start = ""
            while True:
                if stats["scanned"] >= self.cfg.scan_limit:
                    # bounded pass: what's left ages into the next one
                    log.info("lifecycle s3 scan limit %d hit in %s",
                             self.cfg.scan_limit, bucket)
                    return
                status, body = await self._filer_get(
                    "/__meta__/list", {"dir": dir_path, "start": start,
                                       "limit": "512"})
                entries = body.get("entries", []) if status == 200 else []
                for e in entries:
                    name = e["path"].rsplit("/", 1)[-1]
                    if bool(e.get("attr", {}).get("mode", 0) & 0o40000):
                        await walk(e["path"], key_prefix + name + "/")
                        continue
                    stats["scanned"] += 1
                    await self._apply_object_rules(
                        bucket, key_prefix + name, e, rules, now, stats)
                if len(entries) < 512:
                    return
                start = entries[-1]["path"].rsplit("/", 1)[-1]

        await walk(base, "")

    async def _apply_object_rules(self, bucket: str, key: str, entry: dict,
                                  rules: list, now: float,
                                  stats: dict) -> None:
        mtime = float(entry.get("attr", {}).get("mtime", 0) or 0)
        age = now - mtime if mtime else 0.0
        for rule in rules:
            prefix = rule.get("prefix") or ""
            if prefix and not key.startswith(prefix):
                continue
            exp = rule.get("expire_days")
            if exp is not None and age >= exp * self.cfg.day_seconds:
                await self._filer_post("/__meta__/delete",
                                       {"path": entry["path"]})
                self._record("s3_expire", f"{bucket}/{key}", "ok")
                stats["expired"] += 1
                return  # the entry is gone; no further rules apply
            tdays = rule.get("transition_days")
            ext = entry.get("extended") or {}
            if (tdays is not None
                    and age >= tdays * self.cfg.day_seconds
                    and ext.get(s3_rules.STORAGE_CLASS_ATTR)
                    != s3_rules.WARM_CLASS):
                ext[s3_rules.STORAGE_CLASS_ATTR] = s3_rules.WARM_CLASS
                entry["extended"] = ext
                await self._filer_post("/__meta__/update_entry",
                                       {"entry": entry})
                # nudge the volumes holding this object's chunks into
                # the hot->warm transition on the next pass (the warm
                # tier is volume-grained: the whole volume moves)
                for c in entry.get("chunks", []):
                    try:
                        vid = FileId.parse(c["fid"]).volume_id
                    except (KeyError, ValueError):
                        continue
                    self.warm_requested.setdefault(
                        vid, f"s3 transition {bucket}/{prefix or '*'}")
                self._record("s3_transition", f"{bucket}/{key}", "ok")
                stats["transitioned"] += 1

    # --- observability ---

    def export_gauges(self, heat_view: Optional[dict] = None) -> None:
        m = self.master.metrics
        m.gauge("lifecycle_inflight", len(self._inflight))
        m.gauge("lifecycle_warm_requested", len(self.warm_requested))
        if heat_view is None:
            heat_view = self.master.topology.heat_view()
        top = sorted(heat_view.items(),
                     key=lambda kv: kv[1].get("read_rate", 0.0),
                     reverse=True)[:self.cfg.heat_export_top]
        for vid, h in top:
            m.gauge("volume_heat_read_rate", h.get("read_rate", 0.0),
                    labels={"volume": str(vid)})
            m.gauge("volume_heat_reads", h.get("reads", 0),
                    labels={"volume": str(vid)})

    def status(self) -> dict:
        now = time.monotonic()
        return {
            "enabled": self.cfg.enabled,
            "is_leader": self.master.raft.is_leader,
            "last_pass": self.last_pass,
            "passes": self.passes,
            "pending": [{"kind": k, "volume": v,
                         "for_s": round(now - t0, 1)}
                        for (k, v), t0 in sorted(self._inflight.items(),
                                                 key=lambda kv: str(kv[0]))],
            "recent": list(self.recent),
            "warm_requested": {str(v): r
                               for v, r in self.warm_requested.items()},
            "config": {k: v for k, v in asdict(self.cfg).items()
                       if k != "force_enabled"},
        }

    def heat_status(self) -> dict:
        master = self.master
        now = time.time()
        heat = master.topology.heat_view(now)
        vols: dict[int, dict] = {}
        for node in master.topology.nodes.values():
            for vid, vi in node.volumes.items():
                rec = vols.setdefault(vid, {
                    "volume": vid, "collection": vi.collection,
                    "state": "hot", "ttl": vi.ttl, "size": vi.size,
                    "read_only": vi.read_only, "holders": []})
                rec["holders"].append(node.url)
            for vid, si in node.ec_shards.items():
                rec = vols.setdefault(vid, {
                    "volume": vid, "collection": si.collection,
                    "state": "warm", "ttl": "", "size": 0,
                    "read_only": True, "holders": []})
                if rec["state"] == "hot":
                    rec["state"] = "transitioning"
                if node.url not in rec["holders"]:
                    rec["holders"].append(node.url)
        for vid, rec in vols.items():
            h = heat.get(vid, {})
            rec.update({
                "reads": h.get("reads", 0),
                "writes": h.get("writes", 0),
                "read_rate": h.get("read_rate", 0.0),
                "idle_s": round(now - max(h.get("last_access", 0.0),
                                          h.get("first_seen", now)), 1),
            })
        return {"now": now,
                "volumes": [vols[v] for v in sorted(vols)]}
