"""Minimal in-repo Azure Blob service for CI.

The environment has no cloud egress, so the Azure sink
(replication/sink.py AzureSink — counterpart of
weed/replication/sink/azuresink/azure_sink.go) is proven against this
fake: a threaded HTTP server implementing Put Blob, Put Block, Put
Block List, Delete Blob and Get Blob with REAL SharedKey signature
verification (the same azure_shared_key_signature the sink uses, so a
canonicalization bug on either side fails CI). Same pattern as
replication/fake_gcs.py.
"""

from __future__ import annotations

import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .sink import azure_shared_key_signature


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, *a) -> None:
        pass

    @property
    def srv(self) -> "FakeAzureServer":
        return self.server.owner  # type: ignore

    def _reject(self, code: int, msg: str) -> None:
        body = msg.encode()
        self.send_response(code)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _auth_ok(self, verb: str, path: str, query: dict,
                 body_len: int) -> bool:
        auth = self.headers.get("Authorization", "")
        want_prefix = f"SharedKey {self.srv.account}:"
        if not auth.startswith(want_prefix):
            return False
        given = auth[len(want_prefix):]
        expect = azure_shared_key_signature(
            self.srv.account, self.srv.key, verb, path, query,
            dict(self.headers.items()), body_len)
        import hmac
        return hmac.compare_digest(given, expect)

    def _parse(self):
        parsed = urllib.parse.urlparse(self.path)
        path = urllib.parse.unquote(parsed.path)
        query = dict(urllib.parse.parse_qsl(parsed.query,
                                            keep_blank_values=True))
        parts = path.lstrip("/").split("/", 1)
        container = parts[0] if parts else ""
        blob = parts[1] if len(parts) > 1 else ""
        return path, query, container, blob

    def do_PUT(self) -> None:
        path, query, container, blob = self._parse()
        length = int(self.headers.get("Content-Length", "0") or 0)
        body = self.rfile.read(length) if length else b""
        if not self._auth_ok("PUT", path, query, length):
            return self._reject(403, "AuthenticationFailed")
        if not container or not blob:
            return self._reject(400, "InvalidUri")
        with self.srv.lock:
            cont = self.srv.containers.setdefault(container, {})
            comp = query.get("comp", "")
            if comp == "block":
                bid = query.get("blockid", "")
                if not bid:
                    return self._reject(400, "MissingBlockId")
                self.srv.blocks.setdefault((container, blob), {})[bid] = \
                    body
            elif comp == "blocklist":
                staged = self.srv.blocks.pop((container, blob), {})
                ids = []
                import re
                for m in re.finditer(
                        rb"<(?:Latest|Committed|Uncommitted)>([^<]+)</",
                        body):
                    ids.append(m.group(1).decode())
                try:
                    cont[blob] = b"".join(staged[i] for i in ids)
                except KeyError:
                    return self._reject(400, "InvalidBlockList")
            else:
                if self.headers.get("x-ms-blob-type") != "BlockBlob":
                    return self._reject(400, "UnsupportedBlobType")
                cont[blob] = body
        self.send_response(201)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_DELETE(self) -> None:
        path, query, container, blob = self._parse()
        if not self._auth_ok("DELETE", path, query, 0):
            return self._reject(403, "AuthenticationFailed")
        with self.srv.lock:
            cont = self.srv.containers.get(container, {})
            if blob not in cont:
                return self._reject(404, "BlobNotFound")
            del cont[blob]
        self.send_response(202)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_GET(self) -> None:
        # unauthenticated readback for test assertions
        _path, _query, container, blob = self._parse()
        with self.srv.lock:
            data: Optional[bytes] = self.srv.containers.get(
                container, {}).get(blob)
        if data is None:
            return self._reject(404, "BlobNotFound")
        self.send_response(200)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)


class FakeAzureServer:
    def __init__(self, account: str = "devaccount",
                 key_b64: str = "ZmFrZS1henVyZS1rZXktZm9yLWNp",
                 host: str = "127.0.0.1", port: int = 0):
        self.account = account
        self.key = key_b64
        self.containers: dict[str, dict[str, bytes]] = {}
        self.blocks: dict[tuple, dict[str, bytes]] = {}
        self.lock = threading.Lock()
        self._http = ThreadingHTTPServer((host, port), _Handler)
        self._http.owner = self  # type: ignore
        self.host, self.port = self._http.server_address
        self._thread = threading.Thread(target=self._http.serve_forever,
                                        daemon=True)
        self._thread.start()

    @property
    def endpoint(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        self._http.shutdown()
        self._http.server_close()
