"""Queue-fed replication inputs (weed/replication/sub/notifications.go).

`weed filer.replicate` in the reference consumes filer events from a
message queue (Kafka/SQS/pubsub) that the source filer's notification
layer feeds. Same shape here, with the backends this environment can
host:

- FileQueueInput   : tails the notification FileQueue spool directory
                     (notification/queues.py writes it) with a persisted
                     (file, offset) position — the durable local queue.
- BrokerQueueInput : consumes from the in-repo messaging broker — the
                     Kafka-class backend (the notification side publishes
                     with BrokerQueue below).

Both expose the reference's NotificationInput contract: receive() blocks
up to a timeout and returns the next MetaEvent (or None), and ack()
persists the consume position.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Iterator, Optional

from ..filer.filer import MetaEvent
from ..utils import durable

log = logging.getLogger("replication.sub")


class NotificationInput:
    name = "base"

    def receive(self, timeout: float = 1.0) -> Optional[MetaEvent]:
        raise NotImplementedError

    def ack(self) -> None:
        """Persist the consume position of the last received event."""

    def close(self) -> None:
        pass


class FileQueueInput(NotificationInput):
    """Tail the FileQueue spool: dated ndjson files consumed in order."""

    name = "file"

    def __init__(self, directory: str, position_path: str = ""):
        self.directory = directory
        self.position_path = position_path or os.path.join(
            directory, ".consumer_position")
        self._file = ""
        self._offset = 0
        self._load_position()

    def _load_position(self) -> None:
        try:
            with open(self.position_path, encoding="utf-8") as f:
                d = json.load(f)
            self._file, self._offset = d.get("file", ""), d.get("offset", 0)
        except (OSError, ValueError):
            pass

    def ack(self) -> None:
        # durable: a position that rolls back after power loss re-applies
        # events (safe but wasteful); a TORN position file used to read
        # as {} and restart from the epoch
        durable.write_json_atomic(
            self.position_path,
            {"file": self._file, "offset": self._offset})

    def _spool_files(self) -> list[str]:
        try:
            return sorted(n for n in os.listdir(self.directory)
                          if n.startswith("events-")
                          and n.endswith(".ndjson"))
        except OSError:
            return []

    def receive(self, timeout: float = 1.0) -> Optional[MetaEvent]:
        deadline = time.monotonic() + timeout
        while True:
            ev = self._try_read()
            if ev is not None:
                return ev
            if time.monotonic() >= deadline:
                return None
            time.sleep(min(0.1, timeout))

    def _try_read(self) -> Optional[MetaEvent]:
        files = self._spool_files()
        if not files:
            return None
        if self._file not in files:
            # position file ahead of retention or first run: start at the
            # earliest spool file after the recorded one
            later = [n for n in files if n > self._file]
            self._file = later[0] if later else files[0]
            self._offset = 0
        while True:
            path = os.path.join(self.directory, self._file)
            try:
                with open(path, encoding="utf-8") as f:
                    f.seek(self._offset)
                    line = f.readline()
            except OSError:
                return None
            if line.endswith("\n"):
                self._offset += len(line.encode("utf-8"))
                line = line.strip()
                if not line:
                    continue
                try:
                    return MetaEvent.from_dict(json.loads(line))
                except Exception:
                    continue
            # tail of the current file: move on if a newer file exists
            later = [n for n in self._spool_files() if n > self._file]
            if not later:
                return None
            self._file, self._offset = later[0], 0


class BrokerQueueInput(NotificationInput):
    """Consume filer events from a messaging-broker topic (Kafka-class)."""

    name = "broker"

    def __init__(self, brokers: list[str], namespace: str = "notifications",
                 topic: str = "filer", partition: int = 0,
                 position_path: str = ""):
        from ..messaging.client import Subscriber
        self.position_path = position_path
        self._since = 0
        if position_path and os.path.exists(position_path):
            try:
                with open(position_path, encoding="utf-8") as f:
                    self._since = json.load(f).get("since", 0)
            except (OSError, ValueError):
                pass
        self._sub = Subscriber(brokers, namespace, topic,
                               partition=partition)
        self._pending: list = []

    def receive(self, timeout: float = 1.0) -> Optional[MetaEvent]:
        while True:
            if not self._pending:
                for entry in self._sub.stream(since=self._since,
                                              timeout=timeout):
                    self._pending.append(entry)
                    break  # one at a time; stream() reopens per receive
            if not self._pending:
                return None
            entry = self._pending.pop(0)
            self._since = entry.ts_ns
            try:
                return MetaEvent.from_dict(
                    json.loads(entry.value.decode()))
            except Exception:
                # dropped-one is not caught-up: advance past the corrupt
                # message and keep consuming
                log.warning("broker input: dropping corrupt message at "
                            "ts %d", entry.ts_ns)

    def ack(self) -> None:
        if self.position_path:
            durable.write_json_atomic(self.position_path,
                                      {"since": self._since})


class KafkaQueueInput(NotificationInput):
    """Consume filer events from a Kafka topic over the real wire
    protocol (weed/replication/sub/notification_kafka.go:22-117 — the
    reference's sarama consumer with a progress file persisting the
    resume offset)."""

    name = "kafka"

    def __init__(self, bootstrap: str, topic: str = "seaweedfs_filer",
                 partition: int = 0, position_path: str = ""):
        from ..messaging.kafka_wire import KafkaClient
        self._client = KafkaClient.from_addr(bootstrap)
        self.topic = topic
        self.partition = partition
        self.position_path = position_path
        self._offset = 0
        if position_path and os.path.exists(position_path):
            try:
                with open(position_path, encoding="utf-8") as f:
                    self._offset = json.load(f).get("offset", 0)
            except (OSError, ValueError):
                pass
        self._pending: list = []

    def receive(self, timeout: float = 1.0) -> Optional[MetaEvent]:
        # a corrupt message must read as "dropped one, keep going", not
        # as "caught up": skip it and serve the next message — looping
        # back to fetch when the drop emptied the batch (a corrupt TAIL
        # must not look like an empty queue), so iter_queue's
        # None-means-idle contract stays true
        while True:
            if not self._pending:
                try:
                    self._pending = self._client.fetch(
                        self.topic, self.partition, self._offset,
                        max_wait_ms=int(timeout * 1000))
                except Exception:
                    return None
                if not self._pending:
                    return None  # genuinely caught up
            while self._pending:
                offset, _key, value = self._pending.pop(0)
                self._offset = offset + 1
                try:
                    return MetaEvent.from_dict(
                        json.loads((value or b"").decode()))
                except Exception:
                    log.warning("kafka input: dropping corrupt message "
                                "at %s/%d offset %d", self.topic,
                                self.partition, offset)

    def ack(self) -> None:
        if self.position_path:
            durable.write_json_atomic(self.position_path,
                                      {"offset": self._offset})

    def close(self) -> None:
        self._client.close()


def iter_queue(inp: NotificationInput, idle_timeout: float = 1.0,
               stop_check=None) -> Iterator[MetaEvent]:
    """Drain an input until it idles past idle_timeout (or stop_check)."""
    while True:
        if stop_check is not None and stop_check():
            return
        ev = inp.receive(timeout=idle_timeout)
        if ev is None:
            return
        yield ev
        inp.ack()


def load_notification_input(cfg) -> Optional[NotificationInput]:
    """Build the input from replication.toml's [source.*] section
    (the reference reads the notification config for the same purpose)."""
    if cfg.get_bool("source.file.enabled", False):
        return FileQueueInput(
            cfg.get_string("source.file.directory", "./filer_events"),
            cfg.get_string("source.file.position_path", ""))
    if cfg.get_bool("source.broker.enabled", False):
        brokers = [b for b in cfg.get_string(
            "source.broker.brokers", "").split(",") if b]
        return BrokerQueueInput(
            brokers,
            namespace=cfg.get_string("source.broker.namespace",
                                     "notifications"),
            topic=cfg.get_string("source.broker.topic", "filer"),
            partition=cfg.get_int("source.broker.partition", 0),
            position_path=cfg.get_string("source.broker.position_path", ""))
    if cfg.get_bool("source.kafka.enabled", False):
        return KafkaQueueInput(
            cfg.get_string("source.kafka.hosts",
                           "127.0.0.1:9092").split(",")[0],
            topic=cfg.get_string("source.kafka.topic", "seaweedfs_filer"),
            partition=cfg.get_int("source.kafka.partition", 0),
            position_path=cfg.get_string("source.kafka.position_path", ""))
    return None
