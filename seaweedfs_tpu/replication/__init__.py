from .sink import ReplicationSink, LocalSink, FilerSink  # noqa: F401
from .replicator import Replicator  # noqa: F401
