"""In-repo fake GCS: the JSON/media REST subset GcsSink speaks — media
upload, object delete, plus media download for test verification. Same
technique as filer/fake_redis.py / filer/fake_etcd.py: a threaded HTTP
server so CI proves the sink over real sockets without cloud access.
Optionally enforces a bearer token to prove the auth header plumbing.
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, unquote, urlparse

_UPLOAD = re.compile(r"^/upload/storage/v1/b/([^/]+)/o$")
_OBJECT = re.compile(r"^/storage/v1/b/([^/]+)/o/(.+)$")


def _make_handler(state: dict, lock: threading.Lock, token: str):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _reply(self, status: int, body: bytes = b"{}",
                   ctype: str = "application/json") -> None:
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _authed(self) -> bool:
            if not token:
                return True
            return self.headers.get("Authorization") == f"Bearer {token}"

        def do_POST(self):
            if not self._authed():
                self._reply(401)
                return
            u = urlparse(self.path)
            m = _UPLOAD.match(u.path)
            if not m:
                self._reply(404)
                return
            q = parse_qs(u.query)
            name = unquote(q.get("name", [""])[0])
            ln = int(self.headers.get("Content-Length", 0))
            data = self.rfile.read(ln)
            with lock:
                state.setdefault(m.group(1), {})[name] = data
            self._reply(200, json.dumps(
                {"bucket": m.group(1), "name": name,
                 "size": str(len(data))}).encode())

        def do_DELETE(self):
            if not self._authed():
                self._reply(401)
                return
            m = _OBJECT.match(urlparse(self.path).path)
            if not m:
                self._reply(404)
                return
            name = unquote(m.group(2))
            with lock:
                objs = state.get(m.group(1), {})
                if name not in objs:
                    self._reply(404, b'{"error": {"code": 404}}')
                    return
                del objs[name]
            self._reply(204, b"")

        def do_GET(self):
            if not self._authed():
                self._reply(401)
                return
            u = urlparse(self.path)
            m = _OBJECT.match(u.path)
            if not m:
                self._reply(404)
                return
            name = unquote(m.group(2))
            with lock:
                data = state.get(m.group(1), {}).get(name)
            if data is None:
                self._reply(404, b'{"error": {"code": 404}}')
                return
            if "alt=media" in (u.query or ""):
                self._reply(200, data, "application/octet-stream")
            else:
                self._reply(200, json.dumps(
                    {"name": name, "size": str(len(data))}).encode())

    return Handler


class FakeGcsServer:
    def __init__(self, host: str = "127.0.0.1", token: str = ""):
        self.buckets: dict[str, dict[str, bytes]] = {}
        self._lock = threading.Lock()
        self._srv = ThreadingHTTPServer(
            (host, 0), _make_handler(self.buckets, self._lock, token))
        self.host = host
        self.port = self._srv.server_address[1]
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()

    @property
    def endpoint(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
