"""Replication sinks: targets that filer metadata events are applied to.

Mirrors weed/replication/sink/replication_sink.go:10-18 — interface
{CreateEntry, UpdateEntry, DeleteEntry} — with two shippable
implementations: ``LocalSink`` (materialize files into a local directory,
the analog of the reference's azure/gcs/b2/s3 object sinks, which need
cloud credentials) and ``FilerSink`` (another seaweedfs_tpu filer over
HTTP, the analog of sink/filersink).
"""

from __future__ import annotations

import json
import os
import urllib.error
import urllib.parse
import urllib.request
from typing import Callable, Optional

from ..filer.entry import Entry
from ..utils import retry


class ReplicationSink:
    """signatures: filer ids that already processed the mutation — passed
    through so a filer-class sink can stamp them for loop prevention."""

    def create_entry(self, entry: Entry,
                     fetch_data: Callable[[], bytes],
                     signatures: tuple[int, ...] = ()) -> None:
        raise NotImplementedError

    def update_entry(self, old: Optional[Entry], new: Entry,
                     fetch_data: Callable[[], bytes],
                     signatures: tuple[int, ...] = ()) -> None:
        if old is not None and old.full_path != new.full_path:
            self.delete_entry(old, signatures)
        self.create_entry(new, fetch_data, signatures)

    def delete_entry(self, entry: Entry,
                     signatures: tuple[int, ...] = ()) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass

    def identity(self) -> str:
        """Stable string identifying the sink *target* — used to key
        per-job replication resume offsets."""
        return type(self).__name__


class LocalSink(ReplicationSink):
    """Materialize the replicated tree under a local directory."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def identity(self) -> str:
        return f"LocalSink:{os.path.abspath(self.directory)}"

    def _path(self, entry_path: str) -> str:
        return os.path.join(self.directory, entry_path.lstrip("/"))

    def create_entry(self, entry: Entry,
                     fetch_data: Callable[[], bytes],
                     signatures: tuple[int, ...] = ()) -> None:
        p = self._path(entry.full_path)
        if entry.is_directory:
            os.makedirs(p, exist_ok=True)
            return
        os.makedirs(os.path.dirname(p) or ".", exist_ok=True)
        with open(p, "wb") as f:
            f.write(fetch_data())

    def delete_entry(self, entry: Entry,
                     signatures: tuple[int, ...] = ()) -> None:
        p = self._path(entry.full_path)
        try:
            if entry.is_directory:
                import shutil
                shutil.rmtree(p, ignore_errors=True)
            else:
                os.remove(p)
        except FileNotFoundError:
            pass


class FilerSink(ReplicationSink):
    """Apply events to another filer via its HTTP file API
    (weed/replication/sink/filersink)."""

    def __init__(self, filer_url: str, directory: str = "/"):
        self.filer = filer_url.rstrip("/")
        self.directory = directory.rstrip("/")

    def identity(self) -> str:
        return f"FilerSink:{self.filer}{self.directory}"

    def _url(self, entry_path: str, **params) -> str:
        qs = urllib.parse.urlencode(
            {k: v for k, v in params.items() if v})
        return (f"http://{self.filer}{self.directory}"
                + urllib.parse.quote(entry_path) + (f"?{qs}" if qs else ""))

    @staticmethod
    def _sigs(signatures: tuple[int, ...]) -> str:
        return ",".join(str(s) for s in signatures)

    def create_entry(self, entry: Entry,
                     fetch_data: Callable[[], bytes],
                     signatures: tuple[int, ...] = ()) -> None:
        if entry.is_directory:
            req = urllib.request.Request(
                self._url(entry.full_path, op="mkdir",
                          signatures=self._sigs(signatures)),
                method="POST", headers=retry.inject_deadline({}))
            try:
                urllib.request.urlopen(
                    req, timeout=retry.cap_timeout(60)).close()
            except urllib.error.HTTPError:
                pass
            return
        req = urllib.request.Request(
            self._url(entry.full_path, signatures=self._sigs(signatures)),
            data=fetch_data(), method="PUT",
            headers=retry.inject_deadline(
                {"Content-Type": "application/octet-stream"}))
        urllib.request.urlopen(req, timeout=retry.cap_timeout(300)).close()

    def delete_entry(self, entry: Entry,
                     signatures: tuple[int, ...] = ()) -> None:
        req = urllib.request.Request(
            self._url(entry.full_path, recursive="true",
                      signatures=self._sigs(signatures)),
            method="DELETE", headers=retry.inject_deadline({}))
        try:
            urllib.request.urlopen(
                req, timeout=retry.cap_timeout(60)).close()
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise


class S3Sink(ReplicationSink):
    """Replicate entries into an S3-compatible bucket
    (weed/replication/sink/s3sink) via the SigV4 object-store client —
    works against AWS-compatible endpoints and this project's own S3
    gateway."""

    def __init__(self, endpoint: str, bucket: str, directory: str = "/",
                 access_key: str = "", secret_key: str = "",
                 region: str = "us-east-1"):
        from ..storage.backend import S3ObjectStore
        self.store = S3ObjectStore(endpoint, bucket, access_key,
                                   secret_key, region)
        self.prefix = directory.strip("/")

    def identity(self) -> str:
        return (f"S3Sink:{self.store.endpoint}/{self.store.bucket}/"
                f"{self.prefix}")

    def _key(self, entry_path: str) -> str:
        key = entry_path.lstrip("/")
        return f"{self.prefix}/{key}" if self.prefix else key

    def create_entry(self, entry: Entry,
                     fetch_data: Callable[[], bytes],
                     signatures: tuple[int, ...] = ()) -> None:
        if entry.is_directory:
            return  # object stores have no directories
        import tempfile
        with tempfile.NamedTemporaryFile() as tmp:
            tmp.write(fetch_data())
            tmp.flush()
            self.store.put(self._key(entry.full_path), tmp.name)

    def delete_entry(self, entry: Entry,
                     signatures: tuple[int, ...] = ()) -> None:
        if entry.is_directory:
            return
        try:
            self.store.delete(self._key(entry.full_path))
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise


class GcsSink(ReplicationSink):
    """Replicate entries into a Google Cloud Storage bucket
    (weed/replication/sink/gcssink/gcs_sink.go:15-120) over GCS's public
    JSON/media REST API — media upload
    (POST /upload/storage/v1/b/<bucket>/o?uploadType=media&name=<key>)
    and object delete (DELETE /storage/v1/b/<bucket>/o/<key>) — so no
    cloud SDK is needed. Auth is a bearer token (service-account OAuth
    token or GCE metadata token supplied by the operator); CI proves the
    sink against the in-repo fake (replication/fake_gcs.py) speaking the
    same surface."""

    def __init__(self, bucket: str, directory: str = "/",
                 endpoint: str = "https://storage.googleapis.com",
                 token: str = ""):
        self.bucket = bucket
        self.prefix = directory.strip("/")
        self.endpoint = endpoint.rstrip("/")
        self.token = token

    def identity(self) -> str:
        return f"GcsSink:{self.endpoint}/{self.bucket}/{self.prefix}"

    def _key(self, entry_path: str) -> str:
        key = entry_path.lstrip("/")
        return f"{self.prefix}/{key}" if self.prefix else key

    def _headers(self) -> dict:
        return ({"Authorization": f"Bearer {self.token}"}
                if self.token else {})

    def create_entry(self, entry: Entry,
                     fetch_data: Callable[[], bytes],
                     signatures: tuple[int, ...] = ()) -> None:
        if entry.is_directory:
            return  # gcs_sink.go:92: directories are implicit
        from urllib.parse import quote
        key = quote(self._key(entry.full_path), safe="")
        req = urllib.request.Request(
            f"{self.endpoint}/upload/storage/v1/b/{self.bucket}/o"
            f"?uploadType=media&name={key}",
            data=fetch_data(), method="POST",
            headers={"Content-Type": "application/octet-stream",
                     **self._headers()})
        # external endpoint: honor the ambient budget by bounding the
        # socket instead of leaking the cluster header
        with urllib.request.urlopen(
                req, timeout=retry.cap_timeout(60)) as r:
            r.read()

    def delete_entry(self, entry: Entry,
                     signatures: tuple[int, ...] = ()) -> None:
        from urllib.parse import quote
        key = self._key(entry.full_path)
        if entry.is_directory:
            key += "/"  # gcs_sink.go:76-78
        req = urllib.request.Request(
            f"{self.endpoint}/storage/v1/b/{self.bucket}/o/"
            f"{quote(key, safe='')}",
            method="DELETE", headers=self._headers())
        try:
            with urllib.request.urlopen(
                    req, timeout=retry.cap_timeout(60)) as r:
                r.read()
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise


def azure_shared_key_signature(account: str, key_b64: str, verb: str,
                               path: str, query: dict, headers: dict,
                               body_len: int) -> str:
    """Azure Storage SharedKey signature (2015-02-21+ rules: empty
    Content-Length slot when the body is empty). `headers` must already
    contain the x-ms-* headers to be signed; `path` is
    /{container}/{blob}. Shared by AzureSink and fake_azure so client
    and verifier cannot drift."""
    import base64
    import hashlib
    import hmac as hmac_mod

    h = {k.lower(): str(v) for k, v in headers.items()}
    canonical_headers = "".join(
        f"{k}:{h[k]}\n" for k in sorted(h) if k.startswith("x-ms-"))
    canonical_resource = f"/{account}{path}"
    for k in sorted(query):
        canonical_resource += f"\n{k.lower()}:{query[k]}"
    sts = "\n".join([
        verb,
        h.get("content-encoding", ""),
        h.get("content-language", ""),
        str(body_len) if body_len else "",
        h.get("content-md5", ""),
        h.get("content-type", ""),
        "",  # Date: empty — x-ms-date is signed instead
        h.get("if-modified-since", ""),
        h.get("if-match", ""),
        h.get("if-none-match", ""),
        h.get("if-unmodified-since", ""),
        h.get("range", ""),
    ]) + "\n" + canonical_headers + canonical_resource
    mac = hmac_mod.new(base64.b64decode(key_b64), sts.encode("utf-8"),
                       hashlib.sha256)
    return base64.b64encode(mac.digest()).decode()


class AzureSink(ReplicationSink):
    """Replicate entries into an Azure Blob container
    (weed/replication/sink/azuresink/azure_sink.go:1-133) over the Blob
    REST API with SharedKey auth — Put Blob for small bodies, Put Block
    + Put Block List beyond `block_size`, Delete Blob for removals. No
    SDK: the API surface is plain HTTPS, and CI proves it against the
    in-repo fake (replication/fake_azure.py) speaking the same
    protocol + signature scheme."""

    API_VERSION = "2020-10-02"

    def __init__(self, account: str, account_key_b64: str, container: str,
                 directory: str = "/", endpoint: str = "",
                 block_size: int = 8 * 1024 * 1024):
        self.account = account
        self.key = account_key_b64
        self.container = container
        self.prefix = directory.strip("/")
        self.endpoint = (endpoint.rstrip("/") if endpoint
                         else f"https://{account}.blob.core.windows.net")
        self.block_size = block_size

    def identity(self) -> str:
        return (f"AzureSink:{self.endpoint}/{self.container}/"
                f"{self.prefix}")

    def _key_for(self, entry_path: str) -> str:
        key = entry_path.lstrip("/")
        return f"{self.prefix}/{key}" if self.prefix else key

    def _request(self, verb: str, blob: str, query: dict,
                 body: bytes, extra_headers: dict) -> None:
        import email.utils

        path = f"/{self.container}/{blob}"
        headers = {
            "x-ms-date": email.utils.formatdate(usegmt=True),
            "x-ms-version": self.API_VERSION,
            **extra_headers,
        }
        if body and not any(k.lower() == "content-type" for k in headers):
            # urllib injects a default Content-Type AFTER signing; pin it
            # explicitly so the signature covers what is actually sent
            headers["Content-Type"] = "application/octet-stream"
        headers["Authorization"] = (
            f"SharedKey {self.account}:"
            + azure_shared_key_signature(
                self.account, self.key, verb, path, query, headers,
                len(body)))
        qs = urllib.parse.urlencode(query)
        url = (self.endpoint + urllib.parse.quote(path)
               + (f"?{qs}" if qs else ""))
        req = urllib.request.Request(url, data=body or None, method=verb,
                                     headers=headers)
        # external endpoint: the budget bounds the socket; adding the
        # cluster header here would also break the SharedKey signature
        with urllib.request.urlopen(
                req, timeout=retry.cap_timeout(60)) as r:
            r.read()

    def create_entry(self, entry: Entry,
                     fetch_data: Callable[[], bytes],
                     signatures: tuple[int, ...] = ()) -> None:
        if entry.is_directory:
            return  # azure_sink.go:92: blob stores have no directories
        import base64
        data = fetch_data()
        blob = self._key_for(entry.full_path)
        if len(data) <= self.block_size:
            self._request("PUT", blob, {}, data,
                          {"x-ms-blob-type": "BlockBlob",
                           "Content-Type": "application/octet-stream"})
            return
        # staged upload: Put Block per chunk, then commit the list
        ids = []
        for i in range(0, len(data), self.block_size):
            bid = base64.b64encode(f"{i // self.block_size:08d}"
                                   .encode()).decode()
            self._request("PUT", blob,
                          {"comp": "block", "blockid": bid},
                          data[i:i + self.block_size], {})
            ids.append(bid)
        manifest = ("<?xml version=\"1.0\" encoding=\"utf-8\"?>"
                    "<BlockList>"
                    + "".join(f"<Latest>{i}</Latest>" for i in ids)
                    + "</BlockList>").encode()
        self._request("PUT", blob, {"comp": "blocklist"}, manifest,
                      {"Content-Type": "application/octet-stream"})

    def delete_entry(self, entry: Entry,
                     signatures: tuple[int, ...] = ()) -> None:
        if entry.is_directory:
            return
        try:
            self._request("DELETE", self._key_for(entry.full_path), {},
                          b"", {})
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise


def _cloud_stub(name: str) -> ReplicationSink:
    raise RuntimeError(
        f"replication sink {name!r} needs its cloud SDK, which this image "
        "does not ship; the s3 sink covers any S3-compatible endpoint "
        "(including backblaze b2's S3-compatible gateway)")


def load_sink(config) -> Optional[ReplicationSink]:
    """First enabled [sink.<name>] in replication.toml wins
    (weed/replication/replicator.go NewReplicator)."""
    section = config.section("sink")
    for name in section.keys():
        sub = section.section(name)
        if not sub.get_bool("enabled"):
            continue
        if name == "local":
            return LocalSink(sub.get_string("directory", "./replicated"))
        if name == "filer":
            return FilerSink(sub.get_string("grpcAddress", "localhost:8888"),
                             sub.get_string("directory", "/"))
        if name == "s3":
            return S3Sink(sub.get_string("endpoint", ""),
                          sub.get_string("bucket", ""),
                          sub.get_string("directory", "/"),
                          sub.get_string("aws_access_key_id", ""),
                          sub.get_string("aws_secret_access_key", ""),
                          sub.get_string("region", "us-east-1"))
        if name == "gcs":
            return GcsSink(
                sub.get_string("bucket", ""),
                sub.get_string("directory", "/"),
                sub.get_string("endpoint",
                               "https://storage.googleapis.com"),
                sub.get_string("token", ""))
        if name == "azure":
            return AzureSink(
                sub.get_string("account", ""),
                sub.get_string("account_key", ""),
                sub.get_string("container", ""),
                sub.get_string("directory", "/"),
                sub.get_string("endpoint", ""))
        if name == "backblaze":
            # B2's S3-compatible gateway: the s3 sink with B2's endpoint
            # and key pair is the supported route (b2_sink.go's role)
            return S3Sink(
                sub.get_string("endpoint",
                               "https://s3.us-west-000.backblazeb2.com"),
                sub.get_string("bucket", ""),
                sub.get_string("directory", "/"),
                sub.get_string("b2_account_id", ""),
                sub.get_string("b2_master_application_key", ""),
                sub.get_string("region", "us-west-000"))
    return None
