"""Replicator: consume filer metadata events and apply them to a sink.

Mirrors weed/replication/replicator.go — the engine behind both
`filer.replicate` (events from a queue, here the FileQueue spool or a live
subscribe stream) and `filer.sync` (direct peer subscription with
signature-based loop prevention, weed/command/filer_sync.go:81-330).
"""

from __future__ import annotations

import json
import time
import urllib.parse
import urllib.request
from typing import Iterator, Optional

from ..filer.entry import Entry
from ..filer.filer import MetaEvent
from ..utils import glog
from .sink import ReplicationSink


class Replicator:
    def __init__(self, source_filer: str, sink: ReplicationSink,
                 source_path_prefix: str = "/"):
        self.source = source_filer.rstrip("/")
        self.sink = sink
        self.prefix = source_path_prefix

    def _fetch_entry_data(self, entry: Entry) -> bytes:
        """Read the file body from the source filer (repl_util chunk fetch
        helpers in the reference; we read through the filer's HTTP API so
        chunk/manifest resolution stays server-side)."""
        url = f"http://{self.source}" + urllib.parse.quote(entry.full_path)
        with urllib.request.urlopen(url, timeout=300) as r:
            return r.read()

    def apply(self, event: MetaEvent) -> None:
        old, new = event.old_entry, event.new_entry
        if new is not None and not new.full_path.startswith(self.prefix):
            new = None
        if old is not None and not old.full_path.startswith(self.prefix):
            old = None
        if old is None and new is None:
            return
        sigs = event.signatures
        if new is not None and old is not None:
            self.sink.update_entry(old, new,
                                   lambda: self._fetch_entry_data(new), sigs)
        elif new is not None:
            self.sink.create_entry(new,
                                   lambda: self._fetch_entry_data(new), sigs)
        else:
            self.sink.delete_entry(old, sigs)

    # --- event sources ---
    def subscribe_events(self, since: int = 0,
                         reconnect: bool = True,
                         exclude_sig: int = 0) -> Iterator[MetaEvent]:
        """Live ndjson stream from the source filer's /__meta__/subscribe."""
        while True:
            params = {"since": str(since)}
            if exclude_sig:
                params["exclude_sig"] = str(exclude_sig)
            url = (f"http://{self.source}/__meta__/subscribe?"
                   + urllib.parse.urlencode(params))
            try:
                with urllib.request.urlopen(url, timeout=None) as r:
                    for line in r:
                        line = line.strip()
                        if not line:
                            continue
                        e = MetaEvent.from_dict(json.loads(line))
                        since = e.tsns
                        yield e
            except Exception as ex:
                if not reconnect:
                    return
                glog.warning("subscribe to %s lost: %s (retrying)",
                             self.source, ex)
                time.sleep(1.0)

    def run(self, since: int = 0, max_events: Optional[int] = None,
            stop_check=None, exclude_sig: int = 0) -> int:
        """Consume the live stream and apply each event. Returns the count
        applied (bounded runs are for tests)."""
        applied = 0
        for e in self.subscribe_events(since, reconnect=max_events is None,
                                       exclude_sig=exclude_sig):
            try:
                self.apply(e)
                applied += 1
            except Exception as ex:
                glog.error("replicate event at %d failed: %s", e.tsns, ex)
            if max_events is not None and applied >= max_events:
                break
            if stop_check is not None and stop_check():
                break
        return applied


def consume_spool_file(path: str) -> Iterator[MetaEvent]:
    """Read a FileQueue spool file (the queue-consumer side of
    weed/replication/sub/ for the local 'file' queue)."""
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                yield MetaEvent.from_dict(json.loads(line))
            except Exception:
                continue
