"""Replicator: consume filer metadata events and apply them to a sink.

Mirrors weed/replication/replicator.go — the engine behind both
`filer.replicate` (events from a queue, here the FileQueue spool or a live
subscribe stream) and `filer.sync` (direct peer subscription with
signature-based loop prevention, weed/command/filer_sync.go:81-330).
"""

from __future__ import annotations

import json
import os
import time
import urllib.parse
from typing import Iterator, Optional

from .. import faults
from ..cache.http_pool import shared_pool
from ..filer.entry import Entry
from ..filer.filer import MetaEvent
from ..utils import durable, glog
from ..utils import metrics as metrics_mod
from ..utils.retry import RetryPolicy
from .sink import ReplicationSink


class Replicator:
    # transient sink failures are retried from the last good offset; after
    # this many consecutive failures of the SAME event it is treated as
    # poisoned (e.g. create of a path already deleted at the source) and
    # skipped with a loud error, so head-of-line livelock is bounded
    MAX_EVENT_RETRIES = 3

    def __init__(self, source_filer: str, sink: ReplicationSink,
                 source_path_prefix: str = "/",
                 offset_path: str = ""):
        self.source = source_filer.rstrip("/")
        self.sink = sink
        self.prefix = source_path_prefix
        # persisted resume offset so restarts don't replay the whole
        # meta log (reference persists per-source sync offsets,
        # weed/command/filer_sync.go setOffset/getOffset)
        self.offset_path = offset_path

    def load_offset(self) -> int:
        if self.offset_path and os.path.exists(self.offset_path):
            try:
                with open(self.offset_path, encoding="utf-8") as f:
                    return int(json.load(f)["since"])
            except Exception:
                return 0
        return 0

    def save_offset(self, tsns: int) -> None:
        if not self.offset_path:
            return
        durable.write_json_atomic(self.offset_path, {"since": tsns})
        self._last_save = time.monotonic()

    def _maybe_save_offset(self, tsns: int) -> None:
        """Throttled persist: at most ~1/s on the hot path (the reference
        persists offsets periodically too, filer_sync.go setOffset)."""
        if not self.offset_path:
            return
        now = time.monotonic()
        if now - getattr(self, "_last_save", 0.0) >= 1.0:
            self.save_offset(tsns)

    def _fetch_entry_data(self, entry: Entry) -> bytes:
        """Read the file body from the source filer (repl_util chunk fetch
        helpers in the reference; we read through the filer's HTTP API so
        chunk/manifest resolution stays server-side). Chunkless entries
        (empty files, metadata-only events off a queue) have no body to
        fetch.  Rides the shared pool, so the fetch gets keep-alive,
        breaker protection, deadline budgets, and trace/priority
        propagation like every other intra-cluster client."""
        if not entry.chunks:
            return b""
        url = f"http://{self.source}" + urllib.parse.quote(entry.full_path)
        r = shared_pool().request("GET", url, timeout=300)
        if r.status != 200:
            raise RuntimeError(f"source fetch {entry.full_path}: "
                               f"HTTP {r.status}")
        return r.data

    def apply(self, event: MetaEvent) -> None:
        if faults.fire("geo.apply"):
            # injected drop: the event vanished mid-apply — surface as
            # a failure so the offset/poison machinery sees it, never a
            # silent skip
            raise faults.FaultError("injected drop at geo.apply")
        old, new = event.old_entry, event.new_entry
        if new is not None and not new.full_path.startswith(self.prefix):
            new = None
        if old is not None and not old.full_path.startswith(self.prefix):
            old = None
        if old is None and new is None:
            return
        sigs = event.signatures
        if new is not None and old is not None:
            self.sink.update_entry(old, new,
                                   lambda: self._fetch_entry_data(new), sigs)
        elif new is not None:
            self.sink.create_entry(new,
                                   lambda: self._fetch_entry_data(new), sigs)
        else:
            self.sink.delete_entry(old, sigs)

    # reconnect backoff for a lost subscribe stream: jittered
    # exponential up to ~15s, reset by any successfully-delivered
    # event — a dead source filer is probed politely instead of at a
    # flat 1 Hz forever, and a fleet of replicators never redials in
    # lockstep
    RECONNECT_POLICY = RetryPolicy(max_attempts=1, base_delay=0.5,
                                   max_delay=15.0, jitter=0.5)

    # --- event sources ---
    def subscribe_events(self, since: int = 0,
                         reconnect: bool = True,
                         exclude_sig: int = 0) -> Iterator[MetaEvent]:
        """Live ndjson stream from the source filer's /__meta__/subscribe.

        Rides the shared pool's streaming face (cache/http_pool.stream):
        breaker-gated, trace/priority/deadline-propagating, and BOUNDED
        — the dial and each idle read have socket timeouts, so a wedged
        filer surfaces as a reconnect instead of a socket parked
        forever (this used to be the only unbounded intra-cluster
        socket in the tree)."""
        failures = 0
        while True:
            params = {"since": str(since)}
            if exclude_sig:
                params["exclude_sig"] = str(exclude_sig)
            url = (f"http://{self.source}/__meta__/subscribe?"
                   + urllib.parse.urlencode(params))
            try:
                if faults.fire("geo.stream"):
                    raise ConnectionResetError(
                        "injected drop at geo.stream")
                with shared_pool().stream("GET", url) as r:
                    if r.status != 200:
                        # urlopen raised HTTPError here; the pooled
                        # stream hands back the status — an error body
                        # must never be iterated as ndjson
                        raise RuntimeError(f"subscribe: HTTP {r.status}")
                    for line in r:
                        line = line.strip()
                        if not line:
                            continue
                        e = MetaEvent.from_dict(json.loads(line))
                        since = e.tsns
                        failures = 0
                        yield e
            except Exception as ex:
                if not reconnect:
                    return
                delay = self.RECONNECT_POLICY.backoff(min(failures, 5))
                failures += 1
                glog.warning("subscribe to %s lost: %s (retrying in "
                             "%.1fs)", self.source, ex, delay)
                time.sleep(delay)

    def run(self, since: int = 0, max_events: Optional[int] = None,
            stop_check=None, exclude_sig: int = 0) -> int:
        """Consume the live stream and apply each event. Returns the count
        applied (bounded runs are for tests). Resumes from the persisted
        offset when one exists and no explicit `since` is given.

        The offset only advances past events that applied successfully;
        on a sink failure the subscription is torn down and re-established
        from the last good offset after a backoff, so a transiently
        unreachable sink never loses events (the reference likewise only
        advances after the event fn succeeds, filer_sync.go
        processEventFnWithOffset)."""
        applied = 0
        if since == 0:
            since = self.load_offset()
        reconnect = max_events is None
        fail_tsns, fail_count = 0, 0
        while True:
            resubscribe = False
            for e in self.subscribe_events(since, reconnect=reconnect,
                                           exclude_sig=exclude_sig):
                if stop_check is not None and stop_check():
                    break
                try:
                    self.apply(e)
                except Exception as ex:
                    fail_count = fail_count + 1 if e.tsns == fail_tsns else 1
                    fail_tsns = e.tsns
                    if fail_count >= self.MAX_EVENT_RETRIES:
                        # poison event: a transient sink outage would have
                        # recovered by now — skip it (loudly) rather than
                        # livelock every event behind it
                        glog.error(
                            "replicate event at %d failed %d times: %s — "
                            "SKIPPING (entry may be missing at sink)",
                            e.tsns, fail_count, ex)
                        since = e.tsns
                        self._maybe_save_offset(e.tsns)
                        continue
                    glog.error("replicate event at %d failed: %s "
                               "(retry %d/%d from last good offset)",
                               e.tsns, ex, fail_count,
                               self.MAX_EVENT_RETRIES)
                    resubscribe = True
                    break
                applied += 1
                since = e.tsns
                self._maybe_save_offset(e.tsns)
                if max_events is not None and applied >= max_events:
                    break
            self.save_offset(since)
            if not resubscribe or not reconnect:
                break
            if stop_check is not None and stop_check():
                break
            time.sleep(1.0)
        return applied


def run_from_queue(replicator: "Replicator", inp,
                   idle_timeout: float = 1.0, stop_check=None) -> int:
    """Apply queued filer events to the replicator's sink until the queue
    idles — the queue-fed `filer.replicate` mode (the reference consumes
    Kafka/SQS via weed/replication/sub; here the file spool or the
    messaging broker via replication.sub)."""
    from .sub import iter_queue
    applied = 0
    for ev in iter_queue(inp, idle_timeout=idle_timeout,
                         stop_check=stop_check):
        # apply() prefix-filters on full_path exactly like live mode
        replicator.apply(ev)
        applied += 1
    return applied


def consume_spool_file(path: str) -> Iterator[MetaEvent]:
    """Read a FileQueue spool file (the queue-consumer side of
    weed/replication/sub/ for the local 'file' queue).  A corrupt line
    is SKIPPED LOUDLY — glog.error + a replication_corrupt_events
    count — never swallowed: a torn spool write that silently dropped
    mutations would surface as replica divergence weeks later (same
    fix shape as the PR 2 kafka-input change)."""
    with open(path, encoding="utf-8") as f:
        for lineno, line in enumerate(f, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                yield MetaEvent.from_dict(json.loads(line))
            except Exception as e:
                metrics_mod.shared("replication").count(
                    "replication_corrupt_events")
                glog.error("spool %s line %d: corrupt event (%s) — "
                           "SKIPPING one mutation", path, lineno, e)
