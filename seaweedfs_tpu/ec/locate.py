"""Interval addressing: map a needle's (.dat offset, size) onto shard files.

Byte-exact port of the reference addressing scheme
(weed/storage/erasure_coding/ec_locate.go:15-87): a volume is striped
row-major, first in rows of k large blocks, then rows of k small blocks for
the tail. Every needle decomposes into intervals, each living inside one
block of one shard. This pure math is the contract the TPU kernels and the
on-disk shard layout share — block index maps to (shard id, offset).
"""

from __future__ import annotations

from dataclasses import dataclass

from .geometry import Geometry


@dataclass(frozen=True)
class Interval:
    block_index: int
    inner_block_offset: int
    size: int
    is_large_block: bool
    large_block_rows_count: int

    def to_shard_id_and_offset(self, g: Geometry) -> tuple[int, int]:
        offset = self.inner_block_offset
        row_index = self.block_index // g.data_shards
        if self.is_large_block:
            offset += row_index * g.large_block_size
        else:
            offset += (self.large_block_rows_count * g.large_block_size
                       + row_index * g.small_block_size)
        return self.block_index % g.data_shards, offset


def locate_data(g: Geometry, dat_size: int, offset: int,
                size: int) -> list[Interval]:
    block_index, is_large, inner = _locate_offset(g, dat_size, offset)
    # The encoder guarantees < ratio small rows per volume (a tail that
    # would need a full large_block of small rows is written as a padded
    # large row instead — striping.write_ec_files), so the plain floor is
    # exact even for dat_size padded up to whole small blocks. The
    # reference instead adds one small row here (ec_locate.go:19-20),
    # which misaddresses layouts whose small region is exactly
    # large_block-sized — an inconsistency this build removes.
    n_large_rows = dat_size // g.large_row_size

    intervals: list[Interval] = []
    while size > 0:
        block_len = g.large_block_size if is_large else g.small_block_size
        remaining = block_len - inner
        take = min(size, remaining)
        intervals.append(Interval(block_index, inner, take, is_large,
                                  n_large_rows))
        size -= take
        if size <= 0:
            break
        block_index += 1
        if is_large and block_index == n_large_rows * g.data_shards:
            is_large = False
            block_index = 0
        inner = 0
    return intervals


def _locate_offset(g: Geometry, dat_size: int,
                   offset: int) -> tuple[int, bool, int]:
    n_large_rows = dat_size // g.large_row_size
    if offset < n_large_rows * g.large_row_size:
        return (offset // g.large_block_size, True,
                offset % g.large_block_size)
    offset -= n_large_rows * g.large_row_size
    return offset // g.small_block_size, False, offset % g.small_block_size
