"""Pluggable ErasureCoder interface — the seam where TPU meets storage.

The reference binds directly to klauspost/reedsolomon
(weed/storage/erasure_coding/ec_encoder.go:8); this build routes all RS math
through one interface with interchangeable backends:

- NumpyCoder   — pure-python/numpy reference (always available, slow)
- JaxCoder     — jit'd XLA (CPU or TPU; bitplane-MXU, nibble-LUT, or
                 packed-word xorsched formulation — rs_jax.FORMULATIONS)
- PallasCoder  — hand-tiled TPU kernel (rs_pallas.py)
- CppCoder     — native C++ table coder (native/, klauspost-equivalent CPU path)

All backends produce bit-identical shards (enforced by tests), so the choice
is purely a placement/performance decision. WEED_EC_FORMULATION pins the
JaxCoder/PallasCoder kernel formulation; unset, the JaxCoder defaults to
bitplane and lets the feed governor's formulation axis retune it between
runs from measured kernel spans (retune_formulation).
"""

from __future__ import annotations

import os
from typing import Callable, Optional, Sequence

import numpy as np

from ..ops import gf256, rs_jax

# fn(survivors [k, n] uint8) -> rebuilt rows [len(missing), n] uint8
ApplyFn = Callable[[np.ndarray], np.ndarray]
# (present_k, missing) -> ApplyFn
ApplyBuilder = Callable[[tuple, tuple], ApplyFn]


class ErasureCoder:
    """Encode/reconstruct fixed-width stripes of k data + m parity shards."""

    def __init__(self, data_shards: int, parity_shards: int):
        self.k = data_shards
        self.m = parity_shards

    def encode(self, data: np.ndarray) -> np.ndarray:
        """data [k, n] uint8 -> parity [m, n] uint8."""
        raise NotImplementedError

    def _rec_apply(self, present: tuple, missing: tuple) -> ApplyFn:
        """Backend hook: build the survivors->missing transform."""
        raise NotImplementedError

    # --- async pipeline hooks (ec/pipeline.py) ---
    # CPU backends compute synchronously — the streaming pipeline still
    # overlaps their compute with disk read/write via its worker threads.
    # JAX backends override these to return in-flight device computations.

    def encode_async(self, data: np.ndarray):
        """Dispatch an encode; returns a handle for materialize()."""
        return self.encode(data)

    def rec_apply_async(self, present: tuple, missing: tuple) -> ApplyFn:
        """Like _rec_apply but the returned fn may defer computation."""
        return self._rec_apply(present, missing)

    def _run_rec(self, present: tuple, missing: tuple,
                 survivors: np.ndarray):
        """Apply the reconstruction transform (hook so backends can add
        retry/fallback around the kernel call)."""
        return self._rec_apply(present, missing)(survivors)

    def materialize(self, handle) -> np.ndarray:
        """Block until a handle from encode_async/rec_apply_async is real."""
        return np.asarray(handle)

    def encode_digest_async(self, data: np.ndarray, acc=None):
        """Dispatch encode + on-device parity digest; handle materializes to
        [m] uint32 — per parity row, the wrapping byte sum mod 2^32,
        folded into `acc` when given (so a streaming caller chains ONE
        executable per batch instead of alternating digest and add
        programs — remote backends pipeline repeated launches of the same
        executable far better).

        Device backends fuse the reduction into the encode jit so only 4*m
        bytes ever cross device->host: the link-independent sink the
        streaming pipeline's bench mode needs (pipeline.stream_encode is
        otherwise bound by the D2H link, which parity must cross to reach
        shard files). Digests combine across batches by wrapping addition,
        and zero-padding contributes nothing (parity of zeros is zeros).
        """
        parity = self.encode(data)
        digest = np.sum(parity, axis=1, dtype=np.uint32)
        if acc is not None:
            digest = (np.asarray(acc, dtype=np.uint32) + digest)
        return digest

    # --- staged-window hooks (latency-aware sink schedule) ---
    # Tunneled dev links charge a fixed latency per operation AND degrade
    # the transfer path while kernels execute; the window schedule in
    # pipeline.stream_encode_device_sink therefore separates "move bytes"
    # (stage_async) from "run kernels" (one *_window_async dispatch per
    # staged window) so H2D rides the healthy link and per-launch latency
    # is paid once per window, not once per batch.

    def stage_async(self, data: np.ndarray):
        """Move one batch toward the device WITHOUT running any kernel.
        CPU backends return the array unchanged."""
        return np.asarray(data, dtype=np.uint8)

    def encode_digest_window_async(self, staged: Sequence, acc=None):
        """Digest a whole staged window; device backends dispatch ONE
        multi-input executable. All staged batches must share a shape."""
        for b in staged:
            acc = self.encode_digest_async(b, acc)
        return acc

    def rec_digest_window_async(self, present: tuple, missing: tuple,
                                staged: Sequence, acc=None):
        """Like encode_digest_window_async but digesting RECONSTRUCTED
        shards: staged batches are [k, n] survivor stripes; the digest is
        the [len(missing)] uint32 wrapping byte sum of the rebuilt rows."""
        apply_fn = self._rec_apply(present, missing)
        for b in staged:
            rebuilt = np.asarray(apply_fn(np.asarray(b, dtype=np.uint8)))
            d = np.sum(rebuilt, axis=1, dtype=np.uint32)
            acc = d if acc is None else np.asarray(acc, np.uint32) + d
        return acc

    def warm_encode_digest_window(self, n_batches: int,
                                  shape: tuple) -> None:
        """Ahead-of-time compile the window executable WITHOUT executing
        anything on device. On tunneled dev chips the transfer path
        degrades ~100x once any encode kernel has run, so a warm-up
        execution would poison the very measurement (or production pass)
        it prepares for; AOT compilation is free of that side effect.
        CPU backends have nothing to compile."""

    def warm_rec_digest_window(self, present: tuple, missing: tuple,
                               n_batches: int, shape: tuple) -> None:
        """AOT-compile the reconstruction window executable (see
        warm_encode_digest_window)."""

    def reconstruct(self, shards: Sequence[Optional[np.ndarray]],
                    data_only: bool = False,
                    targets: Optional[Sequence[int]] = None
                    ) -> list[Optional[np.ndarray]]:
        """Fill missing (None) entries from any k survivors.

        targets: rebuild only these shard ids (all must be absent); default
        rebuilds every absent shard (all of them, or data shards only with
        data_only=True) — matching the reference coder's
        Reconstruct/ReconstructData split.
        """
        total = self.k + self.m
        assert len(shards) == total
        present = tuple(i for i, s in enumerate(shards) if s is not None)
        if targets is not None:
            missing = tuple(targets)
            assert all(shards[i] is None for i in missing), missing
        else:
            missing = tuple(i for i, s in enumerate(shards) if s is None
                            and (not data_only or i < self.k))
        if not missing:
            return list(shards)
        if len(present) < self.k:
            raise ValueError("too few shards to reconstruct")
        survivors = np.stack([np.asarray(shards[i], dtype=np.uint8)
                              for i in present[:self.k]])
        rebuilt = np.asarray(
            self._run_rec(present[:self.k], missing, survivors))
        out = list(shards)
        for row, tgt in enumerate(missing):
            out[tgt] = rebuilt[row]
        return out

    def verify(self, shards: Sequence[np.ndarray]) -> bool:
        data = np.stack(shards[:self.k])
        parity = np.stack(shards[self.k:])
        return bool(np.array_equal(self.encode(data), parity))


class NumpyCoder(ErasureCoder):
    def encode(self, data: np.ndarray) -> np.ndarray:
        return gf256.encode_parity(np.asarray(data, dtype=np.uint8), self.m)

    def _rec_apply(self, present, missing):
        rec = gf256.reconstruction_matrix(self.k, self.m, present, missing)
        mul = gf256.mul_table()

        def apply_fn(survivors: np.ndarray) -> np.ndarray:
            out = np.zeros((len(missing), survivors.shape[1]), dtype=np.uint8)
            for r in range(rec.shape[0]):
                for c in range(rec.shape[1]):
                    out[r] ^= mul[rec[r, c]][survivors[c]]
            return out

        return apply_fn


def _fused_digest(encode_fn):
    """jit((data, acc) -> acc + per-row uint32 byte sum): parity stays on
    device and the running digest accumulates inside the SAME executable,
    so a streaming caller repeats one program per batch."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def fn(data, acc):
        parity = encode_fn(data)
        return acc + jnp.sum(parity.astype(jnp.uint32), axis=1,
                             dtype=jnp.uint32)

    return fn


def _rec_window_cap() -> int:
    """Max batches per RECONSTRUCTION window executable
    (WEED_EC_REC_WINDOW_BATCHES, default 8). The rec-window compile+load
    measured 140-540+s through the tunneled dev link and wedged the whole
    bench phase (BENCH_r05 rebuild_p50_s: null); capping the window bounds
    the program size, and with the shared dynamic-matrix executable a cap
    >= the encode window's batch count means rebuild compiles NOTHING new.
    """
    try:
        cap = int(os.environ.get("WEED_EC_REC_WINDOW_BATCHES", "8"))
    except ValueError:
        return 8
    return cap if cap > 0 else 8


def _chunks(seq: Sequence, cap: int):
    for i in range(0, len(seq), cap):
        yield seq[i:i + cap]


def _fused_digest_multi(apply_fn):
    """jit((acc, *batches) -> acc + sum of per-batch row digests): ONE
    executable covers a whole staged window, so a remote/tunneled backend
    pays its per-launch latency once per window instead of once per batch
    (~0.3-0.4s/launch measured on the axon tunnel — at 10+ batches that
    latency, not bandwidth, was the round-3 headline's 1000x gap)."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def fn(acc, *batches):
        for b in batches:
            rows = apply_fn(b)
            acc = acc + jnp.sum(rows.astype(jnp.uint32), axis=1,
                                dtype=jnp.uint32)
        return acc

    return fn


def _fused_digest_multi_dyn():
    """One executable, ANY coefficient matrix: fn(acc, w, *batches)
    applies the expanded binary matrix w (rs_jax.gf_apply_bitplane_dyn)
    to every batch and folds the per-row uint32 byte sums into acc.

    Compiled once per (n_batches, batch shape) — the encode window and
    every reconstruction window share the program (the zero-padded rec
    matrix rides in as data), so a rebuild in a process (or persistent
    compilation cache) that has encoded never compiles anything."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def fn(acc, w, *batches):
        for b in batches:
            rows = rs_jax.gf_apply_bitplane_dyn(w, b)
            acc = acc + jnp.sum(rows.astype(jnp.uint32), axis=1,
                                dtype=jnp.uint32)
        return acc

    return fn


def _fused_digest_multi_dyn_packed():
    """_fused_digest_multi_dyn over uint32-packed bit-plane batches
    (method="xorsched"): fn(acc, w, *planes) applies the expanded binary
    matrix as word masks (rs_jax.gf_apply_planes_dyn) — batches arrive
    already bit-plane-resident from stage_async, so the per-batch program
    contains NO expand transpose, and the only byte repack is the m
    output rows feeding the digest sum.

    Same one-executable-per-shape contract as the byte-domain dyn
    program: the matrix is runtime data, so the encode window and every
    zero-padded rec matrix share one compiled program per
    (n_batches, packed shape) and rebuild windows never recompile."""
    import jax
    import jax.numpy as jnp
    from ..ops import xor_schedule

    @jax.jit
    def fn(acc, w, *planes):
        for p in planes:
            out = rs_jax.gf_apply_planes_dyn(w, p)
            rows = xor_schedule.unpack_planes(out, int(p.shape[1]) * 32)
            acc = acc + jnp.sum(rows.astype(jnp.uint32), axis=1,
                                dtype=jnp.uint32)
        return acc

    return fn


def _aot_compile_window_dyn_packed(m_rows: int, k: int, n_batches: int,
                                   shape: tuple):
    """AOT-compile the packed dynamic-matrix window executable from the
    BYTE batch shape callers plan with (the packed staged shape is
    derived here). compiled(acc, w, *planes)."""
    import jax
    import jax.numpy as jnp
    from ..ops import xor_schedule
    jfn = _fused_digest_multi_dyn_packed()
    sds = jax.ShapeDtypeStruct(
        (int(shape[0]) * 8, xor_schedule.packed_width(int(shape[1]))),
        jnp.uint32)
    w_sds = jax.ShapeDtypeStruct((m_rows * 8, k * 8), jnp.int8)
    acc_sds = jax.ShapeDtypeStruct((m_rows,), jnp.uint32)
    return jfn.lower(acc_sds, w_sds, *([sds] * n_batches)).compile()


def _aot_compile_window_dyn(m_rows: int, k: int, n_batches: int,
                            shape: tuple):
    """AOT-compile the dynamic-matrix window executable (abstract shapes
    only — no bytes move, nothing executes). compiled(acc, w, *batches)."""
    import jax
    import jax.numpy as jnp
    jfn = _fused_digest_multi_dyn()
    sds = jax.ShapeDtypeStruct(tuple(shape), jnp.uint8)
    w_sds = jax.ShapeDtypeStruct((m_rows * 8, k * 8), jnp.int8)
    acc_sds = jax.ShapeDtypeStruct((m_rows,), jnp.uint32)
    return jfn.lower(acc_sds, w_sds, *([sds] * n_batches)).compile()


def _jax_stage(data: np.ndarray):
    import jax
    return jax.device_put(np.asarray(data, dtype=np.uint8))


def _aot_compile_window(apply_fn, m_rows: int, n_batches: int,
                        shape: tuple):
    """Lower + compile the multi-batch digest executable from abstract
    shapes only — no bytes move, no kernel runs. The returned compiled
    object is called exactly like the jit fn: compiled(acc, *batches)."""
    import jax
    import jax.numpy as jnp
    jfn = _fused_digest_multi(apply_fn)
    sds = jax.ShapeDtypeStruct(tuple(shape), jnp.uint8)
    acc_sds = jax.ShapeDtypeStruct((m_rows,), jnp.uint32)
    return jfn.lower(acc_sds, *([sds] * n_batches)).compile()


class JaxCoder(ErasureCoder):
    # subclasses may accept extra kernel backends (MeshCoder: "pallas")
    _VALID_METHODS = frozenset(rs_jax.FORMULATIONS)

    def __init__(self, data_shards: int, parity_shards: int,
                 method: str | None = None):
        super().__init__(data_shards, parity_shards)
        env = rs_jax.formulation_env()
        # an explicit method or the env var pins the formulation; only an
        # unpinned coder lets the governor's formulation axis retune it
        self._method_pinned = method is not None or env is not None
        self.method = method or env or "bitplane"
        if self.method not in self._VALID_METHODS:
            raise ValueError(f"unknown formulation {self.method!r}; "
                             f"have {sorted(self._VALID_METHODS)}")

    def retune_formulation(self, method: str) -> str:
        """Governor hook (pipeline._steer_formulation): switch the kernel
        formulation BETWEEN runs. Pinned coders (explicit method or
        WEED_EC_FORMULATION) ignore the request; returns the method
        actually in use so finish_run attributes kernel spans to what
        ran. The cached fused digest fn is method-bound and dropped on a
        switch; window caches key by method (or are method-generic)."""
        if (not self._method_pinned and method != self.method
                and method in rs_jax.FORMULATIONS):
            self.method = method
            self._digest_fn = None
        return self.method

    def encode(self, data: np.ndarray) -> np.ndarray:
        out = rs_jax.encode_parity(np.asarray(data, dtype=np.uint8), self.m,
                                   method=self.method)
        return np.asarray(out)

    def _rec_apply(self, present, missing):
        return rs_jax._reconstruct_fn(self.k, self.m, present, missing,
                                      self.method)

    def encode_async(self, data: np.ndarray):
        import jax
        return rs_jax.encode_parity(
            jax.device_put(np.asarray(data, dtype=np.uint8)), self.m,
            method=self.method)

    def rec_apply_async(self, present, missing):
        import jax
        fn = self._rec_apply(present, missing)
        return lambda survivors: fn(
            jax.device_put(np.asarray(survivors, dtype=np.uint8)))

    def encode_digest_async(self, data: np.ndarray, acc=None):
        import jax
        import jax.numpy as jnp
        fn = getattr(self, "_digest_fn", None)
        if fn is None:
            # via the _encode_fn hook so subclasses' kernel choice
            # (MeshCoder's pallas/lut methods) holds on this path too
            fn = self._digest_fn = _fused_digest(self._encode_fn())
        if acc is None:
            acc = jnp.zeros(self.m, dtype=jnp.uint32)
        return fn(jax.device_put(np.asarray(data, dtype=np.uint8)), acc)

    def stage_async(self, data: np.ndarray):
        """H2D staging; under method="xorsched" the batch is ALSO
        transposed to uint32-packed bit-plane rows here — once per batch
        on the stager pool, fused with the H2D put — so every window
        kernel (encode, digests, rebuild) consumes the resident layout
        and the expand/repack cost amortizes from per-kernel to
        per-window. The packed form is the same total bytes as the
        input (no 8x lane expansion)."""
        if self.method != "xorsched":
            return _jax_stage(data)
        from .. import faults, observe
        if faults.fire("ec.stage.pack"):
            # a dropped pack has no silent fallback: the window kernels
            # need the resident layout, so failing the stage is the
            # honest degradation (the sink's error path surfaces it)
            raise faults.FaultError("dropped at ec.stage.pack")
        import jax
        with observe.span("ec.stage.pack"):
            arr = jax.device_put(np.asarray(data, dtype=np.uint8))
            return self._pack_fn()(arr)

    def _pack_fn(self):
        fn = getattr(self, "_pack_jit", None)
        if fn is None:
            import jax
            from ..ops import xor_schedule
            fn = self._pack_jit = jax.jit(xor_schedule.pack_planes)
        return fn

    def _encode_fn(self):
        return lambda d: rs_jax.encode_parity(d, self.m, method=self.method)

    def _wcache(self) -> dict:
        cache = getattr(self, "_window_cache", None)
        if cache is None:
            cache = self._window_cache = {}
        return cache

    # --- dynamic-matrix window path (bitplane method) ---
    # The window executable takes the expanded binary matrix as DATA, so
    # encode and every reconstruction share one program per
    # (n_batches, shape): warming the encode window warms every rebuild.

    def _dyn_w(self, key, build):
        cache = getattr(self, "_dyn_mats", None)
        if cache is None:
            cache = self._dyn_mats = {}
        w = cache.get(key)
        if w is None:
            import jax.numpy as jnp
            w = cache[key] = jnp.asarray(rs_jax.bitplane_matrix(build()))
        return w

    def _dyn_w_enc(self):
        return self._dyn_w(
            "enc", lambda: gf256.parity_matrix(self.k, self.m))

    def _dyn_w_rec(self, present: tuple, missing: tuple):
        def build() -> np.ndarray:
            rec = gf256.reconstruction_matrix(self.k, self.m, present,
                                              missing)
            if rec.shape[0] < self.m:
                # zero rows reconstruct zeros (digest 0): padding to the
                # parity matrix's shape is what lets the rec window reuse
                # the encode executable; callers slice the pad rows off
                rec = np.vstack([
                    rec, np.zeros((self.m - rec.shape[0], self.k),
                                  dtype=rec.dtype)])
            return rec
        return self._dyn_w(("rec", present, missing), build)

    def _dyn_window_fn(self, n_batches: int, shape: tuple):
        cache = self._wcache()
        key = ("dynw", n_batches, tuple(shape))
        fn = cache.get(key)
        if fn is None:
            fn = cache[key] = _fused_digest_multi_dyn()
        return fn

    def _packed_shape(self, shape: tuple) -> tuple:
        from ..ops import xor_schedule
        return (shape[0] * 8, xor_schedule.packed_width(shape[1]))

    def _dyn_window_fn_packed(self, n_batches: int, shape: tuple):
        # shape is the PACKED per-batch shape (staged batches are already
        # bit-plane words under xorsched); keyed separately from "dynw"
        # so byte- and packed-domain programs never collide
        cache = self._wcache()
        key = ("dynwp", n_batches, tuple(shape))
        fn = cache.get(key)
        if fn is None:
            fn = cache[key] = _fused_digest_multi_dyn_packed()
        return fn

    def _dyn_window_builder(self):
        """The matrix-as-data window builder for this formulation, or
        None when the formulation has no dyn path (lut): bitplane windows
        consume byte batches, xorsched windows consume the bit-plane-
        resident batches stage_async produces. Either way encode and
        every rebuild share ONE executable per (n_batches, shape)."""
        if self.method == "bitplane":
            return self._dyn_window_fn
        if self.method == "xorsched":
            return self._dyn_window_fn_packed
        return None

    def encode_digest_window_async(self, staged, acc=None):
        import jax.numpy as jnp
        if acc is None:
            acc = jnp.zeros(self.m, dtype=jnp.uint32)
        dyn = self._dyn_window_builder()
        if dyn is not None:
            fn = dyn(len(staged), staged[0].shape)
            return fn(acc, self._dyn_w_enc(), *staged)
        cache = self._wcache()
        key = ("enc", self.method, len(staged), tuple(staged[0].shape))
        fn = cache.get(key)
        if fn is None:
            fn = cache[key] = _fused_digest_multi(self._encode_fn())
        return fn(acc, *staged)

    def rec_digest_window_async(self, present, missing, staged, acc=None):
        import jax.numpy as jnp
        present, missing = tuple(present), tuple(missing)
        cap = _rec_window_cap()
        dyn = self._dyn_window_builder()
        if dyn is not None:
            n_missing = len(missing)
            if acc is None:
                full = jnp.zeros(self.m, dtype=jnp.uint32)
            elif n_missing == self.m:
                full = jnp.asarray(acc, dtype=jnp.uint32)
            else:
                full = jnp.pad(jnp.asarray(acc, dtype=jnp.uint32),
                               (0, self.m - n_missing))
            w = self._dyn_w_rec(present, missing)
            for chunk in _chunks(list(staged), cap):
                fn = dyn(len(chunk), chunk[0].shape)
                full = fn(full, w, *chunk)
            return full if n_missing == self.m else full[:n_missing]
        if acc is None:
            acc = jnp.zeros(len(missing), dtype=jnp.uint32)
        cache = self._wcache()
        for chunk in _chunks(list(staged), cap):
            key = ("rec", self.method, present, missing, len(chunk),
                   tuple(chunk[0].shape))
            fn = cache.get(key)
            if fn is None:
                fn = cache[key] = _fused_digest_multi(
                    self._rec_apply(present, missing))
            acc = fn(acc, *chunk)
        return acc

    def warm_encode_digest_window(self, n_batches, shape):
        if self.method == "bitplane":
            key = ("dynw", n_batches, tuple(shape))
            self._wcache()[key] = _aot_compile_window_dyn(
                self.m, self.k, n_batches, shape)
            return
        if self.method == "xorsched":
            # warm takes the BYTE batch shape (what the pipeline knows);
            # the packed shape it compiles for is what stage_async emits
            key = ("dynwp", n_batches, self._packed_shape(tuple(shape)))
            self._wcache()[key] = _aot_compile_window_dyn_packed(
                self.m, self.k, n_batches, shape)
            return
        key = ("enc", self.method, n_batches, tuple(shape))
        self._wcache()[key] = _aot_compile_window(
            self._encode_fn(), self.m, n_batches, shape)

    def warm_rec_digest_window(self, present, missing, n_batches, shape):
        cap = _rec_window_cap()
        sizes = {min(cap, n_batches)}
        if n_batches > cap and n_batches % cap:
            sizes.add(n_batches % cap)
        if self.method == "bitplane":
            for n in sizes:
                key = ("dynw", n, tuple(shape))
                if key not in self._wcache():
                    self._wcache()[key] = _aot_compile_window_dyn(
                        self.m, self.k, n, shape)
            return
        if self.method == "xorsched":
            for n in sizes:
                key = ("dynwp", n, self._packed_shape(tuple(shape)))
                if key not in self._wcache():
                    self._wcache()[key] = _aot_compile_window_dyn_packed(
                        self.m, self.k, n, shape)
            return
        present, missing = tuple(present), tuple(missing)
        for n in sizes:
            key = ("rec", self.method, present, missing, n, tuple(shape))
            self._wcache()[key] = _aot_compile_window(
                self._rec_apply(present, missing), len(missing), n, shape)


class PallasCoder(ErasureCoder):
    """Fused TPU kernel path (rs_pallas.py); interpret-mode on CPU."""

    def __init__(self, data_shards: int, parity_shards: int,
                 tile: int | None = None,
                 formulation: str | None = None):
        super().__init__(data_shards, parity_shards)
        from ..ops import rs_pallas
        self._mod = rs_pallas
        self._tile = tile or rs_pallas.DEFAULT_TILE
        # env pin: xorsched swaps the kernel body (schedule-driven, no
        # matrix operand); lut has no Pallas twin so any other value
        # keeps the bitplane kernel
        env = rs_jax.formulation_env()
        self.formulation = formulation or (
            "xorsched" if env == "xorsched" else "bitplane")
        self._encode = rs_pallas.gf_apply_pallas(
            gf256.parity_matrix(data_shards, parity_shards),
            tile=self._tile, formulation=self.formulation)
        self._rec_cache: dict = {}
        self._digest_cache: dict = {}

    def _shrink_tile(self) -> None:
        """Fallback for chips whose VMEM can't hold the default tile:
        quarter the tile and rebuild the kernels. VMEM overflows are
        compile-time errors and jit compiles synchronously on first
        dispatch, so they surface inside the retry loops below; genuine
        runtime errors re-raise once the floor tile is reached."""
        if self._tile <= 16384:
            raise
        import logging
        logging.getLogger("ec.coder").warning(
            "pallas kernel failed at tile %d; retrying at %d "
            "(expected only for VMEM-constrained chips)",
            self._tile, self._tile // 4)
        self._tile //= 4
        self._encode = self._mod.gf_apply_pallas(
            gf256.parity_matrix(self.k, self.m), tile=self._tile,
            formulation=self.formulation)
        self._rec_cache.clear()

    def _run_encode(self, data):
        while True:
            try:
                return self._encode(data)
            except Exception:
                self._shrink_tile()

    def encode(self, data: np.ndarray) -> np.ndarray:
        return np.asarray(
            self._run_encode(np.asarray(data, dtype=np.uint8)))

    def _run_rec(self, present, missing, survivors):
        while True:
            try:
                return self._rec_apply(present, missing)(survivors)
            except Exception:
                self._shrink_tile()

    def _rec_apply(self, present, missing):
        key = (present, missing)
        fn = self._rec_cache.get(key)
        if fn is None:
            rec = gf256.reconstruction_matrix(self.k, self.m, present,
                                              missing)
            fn = self._mod.gf_apply_pallas(rec, tile=self._tile,
                                           formulation=self.formulation)
            self._rec_cache[key] = fn
        return fn

    def encode_async(self, data: np.ndarray):
        import jax
        return self._run_encode(
            jax.device_put(np.asarray(data, dtype=np.uint8)))

    def rec_apply_async(self, present, missing):
        import jax

        def run(survivors):
            d = jax.device_put(np.asarray(survivors, dtype=np.uint8))
            while True:
                try:
                    return self._rec_apply(present, missing)(d)
                except Exception:
                    self._shrink_tile()

        return run

    def encode_digest_async(self, data: np.ndarray, acc=None):
        import jax
        import jax.numpy as jnp
        d = jax.device_put(np.asarray(data, dtype=np.uint8))
        if acc is None:
            acc = jnp.zeros(self.m, dtype=jnp.uint32)
        while True:
            try:
                fn = self._digest_cache.get(self._tile)
                if fn is None:
                    fn = _fused_digest(self._encode)
                    self._digest_cache[self._tile] = fn
                return fn(d, acc)
            except Exception:
                self._shrink_tile()

    stage_async = staticmethod(_jax_stage)

    def encode_digest_window_async(self, staged, acc=None):
        import jax.numpy as jnp
        if acc is None:
            acc = jnp.zeros(self.m, dtype=jnp.uint32)
        while True:
            try:
                key = ("enc", self._tile, len(staged),
                       tuple(staged[0].shape))
                fn = self._digest_cache.get(key)
                if fn is None:
                    fn = self._digest_cache[key] = _fused_digest_multi(
                        self._encode)
                return fn(acc, *staged)
            except Exception:
                self._shrink_tile()

    def rec_digest_window_async(self, present, missing, staged, acc=None):
        import jax.numpy as jnp
        if acc is None:
            acc = jnp.zeros(len(missing), dtype=jnp.uint32)
        # capped like the Jax path: a bounded rec program per chunk
        # instead of one giant window executable (see _rec_window_cap)
        for chunk in _chunks(list(staged), _rec_window_cap()):
            while True:
                try:
                    key = ("rec", self._tile, present, missing,
                           len(chunk), tuple(chunk[0].shape))
                    fn = self._digest_cache.get(key)
                    if fn is None:
                        fn = self._digest_cache[key] = _fused_digest_multi(
                            self._rec_apply(present, missing))
                    acc = fn(acc, *chunk)
                    break
                except Exception:
                    self._shrink_tile()
        return acc

    def warm_encode_digest_window(self, n_batches, shape):
        key = ("enc", self._tile, n_batches, tuple(shape))
        self._digest_cache[key] = _aot_compile_window(
            self._encode, self.m, n_batches, shape)

    def warm_rec_digest_window(self, present, missing, n_batches, shape):
        key = ("rec", self._tile, present, missing, n_batches,
               tuple(shape))
        self._digest_cache[key] = _aot_compile_window(
            self._rec_apply(present, missing), len(missing), n_batches,
            shape)


class CppCoder(ErasureCoder):
    """Native C++ table kernel (native/rs_core.cpp) — the CPU production
    path, equivalent in role to the reference's klauspost/reedsolomon."""

    def __init__(self, data_shards: int, parity_shards: int):
        super().__init__(data_shards, parity_shards)
        from ..ops import native
        if not native.available():
            raise RuntimeError("native core unavailable")
        self._native = native
        self._pm = gf256.parity_matrix(data_shards, parity_shards)

    def encode(self, data: np.ndarray) -> np.ndarray:
        return self._native.gf_matrix_apply(self._pm, data)

    def _rec_apply(self, present, missing):
        rec = gf256.reconstruction_matrix(self.k, self.m, present, missing)
        return lambda survivors: self._native.gf_matrix_apply(rec, survivors)


_REGISTRY = {}


def register_coder(name: str, factory) -> None:
    _REGISTRY[name] = factory


def _mesh_factory(data_shards: int, parity_shards: int) -> ErasureCoder:
    """Mesh-or-single factory (parallel/mesh_coder.py): a MeshCoder over
    WEED_EC_MESH_DEVICES (default: every local device), degenerating to
    the plain JaxCoder on a 1-chip host. Imported lazily — the parallel
    package must not load for processes that never pick this backend."""
    from ..parallel import mesh_coder as mesh_mod
    return mesh_mod.coder(data_shards, parity_shards)


register_coder("numpy", NumpyCoder)
register_coder("jax", JaxCoder)
register_coder("jax_lut", lambda k, m: JaxCoder(k, m, method="lut"))
register_coder("jax_xorsched",
               lambda k, m: JaxCoder(k, m, method="xorsched"))
register_coder("pallas", PallasCoder)
register_coder("cpp", CppCoder)
register_coder("mesh", _mesh_factory)


def get_coder(name: str, data_shards: int, parity_shards: int) -> ErasureCoder:
    if name == "auto":
        import jax
        # pallas only wins on real TPU; its CPU interpret mode is ~2x slower
        # than the XLA bitplane path
        order = (("pallas", "jax", "numpy")
                 if jax.default_backend() == "tpu"
                 else ("cpp", "jax", "numpy"))
        for candidate in order:
            if candidate in _REGISTRY:
                try:
                    return _REGISTRY[candidate](data_shards, parity_shards)
                except Exception:
                    continue
        raise KeyError("no erasure coder backend available")
    if name not in _REGISTRY:
        raise KeyError(f"unknown coder {name!r}; have {sorted(_REGISTRY)}")
    return _REGISTRY[name](data_shards, parity_shards)
