"""Pluggable ErasureCoder interface — the seam where TPU meets storage.

The reference binds directly to klauspost/reedsolomon
(weed/storage/erasure_coding/ec_encoder.go:8); this build routes all RS math
through one interface with interchangeable backends:

- NumpyCoder   — pure-python/numpy reference (always available, slow)
- JaxCoder     — jit'd XLA (CPU or TPU; bitplane-MXU or nibble-LUT method)
- PallasCoder  — hand-tiled TPU kernel (rs_pallas.py)
- CppCoder     — native C++ table coder (native/, klauspost-equivalent CPU path)

All backends produce bit-identical shards (enforced by tests), so the choice
is purely a placement/performance decision.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..ops import gf256, rs_jax


class ErasureCoder:
    """Encode/reconstruct fixed-width stripes of k data + m parity shards."""

    def __init__(self, data_shards: int, parity_shards: int):
        self.k = data_shards
        self.m = parity_shards

    def encode(self, data: np.ndarray) -> np.ndarray:
        """data [k, n] uint8 -> parity [m, n] uint8."""
        raise NotImplementedError

    def reconstruct(self, shards: Sequence[Optional[np.ndarray]],
                    data_only: bool = False) -> list[Optional[np.ndarray]]:
        """Fill None entries from any k survivors; returns full shard list."""
        raise NotImplementedError

    def verify(self, shards: Sequence[np.ndarray]) -> bool:
        data = np.stack(shards[:self.k])
        parity = np.stack(shards[self.k:])
        return bool(np.array_equal(self.encode(data), parity))


class NumpyCoder(ErasureCoder):
    def encode(self, data: np.ndarray) -> np.ndarray:
        return gf256.encode_parity(np.asarray(data, dtype=np.uint8), self.m)

    def reconstruct(self, shards, data_only=False):
        arrs = [None if s is None else np.asarray(s, dtype=np.uint8)
                for s in shards]
        return gf256.reconstruct(arrs, self.k, self.m, data_only=data_only)


class JaxCoder(ErasureCoder):
    def __init__(self, data_shards: int, parity_shards: int,
                 method: str = "bitplane"):
        super().__init__(data_shards, parity_shards)
        self.method = method

    def encode(self, data: np.ndarray) -> np.ndarray:
        out = rs_jax.encode_parity(np.asarray(data, dtype=np.uint8), self.m,
                                   method=self.method)
        return np.asarray(out)

    def reconstruct(self, shards, data_only=False):
        arrs = [None if s is None else np.asarray(s, dtype=np.uint8)
                for s in shards]
        out = rs_jax.reconstruct(arrs, self.k, self.m, method=self.method,
                                 data_only=data_only)
        return [None if s is None else np.asarray(s) for s in out]


_REGISTRY = {}


def register_coder(name: str, factory) -> None:
    _REGISTRY[name] = factory


register_coder("numpy", NumpyCoder)
register_coder("jax", JaxCoder)
register_coder("jax_lut", lambda k, m: JaxCoder(k, m, method="lut"))


def get_coder(name: str, data_shards: int, parity_shards: int) -> ErasureCoder:
    if name == "auto":
        for candidate in ("pallas", "jax", "numpy"):
            if candidate in _REGISTRY:
                try:
                    return _REGISTRY[candidate](data_shards, parity_shards)
                except Exception:
                    continue
    if name not in _REGISTRY:
        raise KeyError(f"unknown coder {name!r}; have {sorted(_REGISTRY)}")
    return _REGISTRY[name](data_shards, parity_shards)
