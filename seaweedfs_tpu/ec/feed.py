"""Zero-copy host feed for the EC pipeline (ec/pipeline.py).

BENCH_r05 pinned the encode pipeline at 0.72 GB/s with
``healthy_link_binding_stage: "disk_read (1-core host feed)"`` while the
window executable ran at 30-40 GB/s: the chip is starved by a host feed
that assembles every [k, B] batch on one thread. This module deletes
that work, in two tiers:

- ``MmapFeed`` maps the source file once and exposes it as a numpy view
  over the page cache. A batch whose k rows sit at one uniform stride is
  yielded as an ``as_strided`` view: ZERO host copies (``device_put`` or
  the CPU coder gathers straight from the page cache). Aggregated batches
  (small-block rows) are assembled with one vectorized 2-D copy per
  contiguous k-row file run into a reusable staging buffer — one memcpy,
  no syscalls, no bytes objects.
- ``PreadvFeed`` is the fallback when mmap is unavailable (or forced via
  ``WEED_EC_MMAP=0``): ``os.preadv`` scatters each contiguous k-row file
  run straight into the staging-buffer rows — one syscall per run and no
  intermediate bytes objects.
- ``ShardFeed`` is the same idea for the rebuild path's k survivor shard
  files (one source file per row instead of one strided file).

**Reader pool (round 10).** ``WEED_EC_READERS`` > 1 assembles batches on
a bounded pool of reader threads instead of serially in the pipeline's
one reader thread: each batch's segment fills (or the page prefaults of
a zero-copy view) split into per-row-range jobs that run concurrently,
while batches are still yielded strictly in order. preads, page faults
and the vectorized copies all release the GIL, so N readers keep N disk
reads in flight — the host feed stops being a 1-core property. Reader
count defaults from the governor's operating point (ec/governor.py);
``readers=1`` is the exact serial path of rounds 3-9, byte-identical.

**O_DIRECT (round 10).** ``WEED_EC_ODIRECT=1`` reads stripe/survivor
rows with ``O_DIRECT`` into page-aligned staging buffers, so a 30 GB
volume scan stops churning the page cache out from under the serving
path. Unaligned spans (odd tails, narrow batches) silently fall back to
a buffered fd, and filesystems that refuse O_DIRECT (EINVAL at open or
first read) degrade to the plain buffered path — the feed never fails
on alignment, it just loses the cache-bypass property for that span.

Staging buffers come from a bounded ``BufferPool`` so the pipeline
double-buffers: batch N+1 assembles while batch N's device_put + kernel
are in flight, and memory stays at pool_size * k * batch bytes no matter
how long the volume is. The pipeline recycles a buffer once its batch is
fully consumed (parity materialized AND every shard row written). Feeds
with ``pooled=False`` hand out fresh buffers and recycling is a no-op —
the device-sink bench paths use that mode because a whole window of
batches stays referenced until its single dispatch.

Fault points: ``ec.feed.read`` fires on every stripe/survivor read
operation (a drop fails the read — a feed must never silently feed
zeros), ``ec.feed.stall`` fires when the feed waits on a staging buffer
(delay = an injected slow consumer; drop aborts the wait).
"""

from __future__ import annotations

import errno
import mmap
import os
import queue
import threading
from collections import deque
from typing import Callable, Iterator, Optional, Sequence

import numpy as np

from .. import faults

# Segment = (k file offsets, width); produced by striping.stripe_segments
Segment = "tuple[list[int], int]"

# O_DIRECT alignment: 4096 covers 512e and 4Kn sectors and the page size
_ALIGN = 4096


def use_mmap_default() -> bool:
    """WEED_EC_MMAP=0 forces the preadv fallback (e.g. filesystems where
    mmap faults are slower than reads, or for A/B measurement)."""
    return os.environ.get("WEED_EC_MMAP", "1") not in ("0", "false", "no")


def use_odirect_default() -> bool:
    """WEED_EC_ODIRECT=1 opts bulk volume scans out of the page cache."""
    return os.environ.get("WEED_EC_ODIRECT", "0") in ("1", "true", "yes")


def env_thread_count(name: str, cap: int) -> int:
    """Shared env->thread-count rule for the feed-tier pools: a positive
    value is clamped to `cap`; unset/0/garbage means auto (one per core,
    at most 4 — a 1-core container keeps the proven serial path)."""
    try:
        n = int(os.environ.get(name, "0"))
    except ValueError:
        n = 0
    if n > 0:
        return min(n, cap)
    return max(1, min(4, os.cpu_count() or 1))


def reader_count_default() -> int:
    """WEED_EC_READERS: reader-pool width (1 = serial assembly)."""
    return env_thread_count("WEED_EC_READERS", 64)


def _aligned_empty(shape: tuple) -> np.ndarray:
    """A [k, w] uint8 buffer whose data pointer is page-aligned, so
    O_DIRECT reads can land in it directly."""
    n = int(shape[0]) * int(shape[1])
    raw = np.empty(n + _ALIGN, dtype=np.uint8)
    off = (-raw.ctypes.data) % _ALIGN
    return raw[off:off + n].reshape(shape)


class BufferPool:
    """Bounded free-list of [k, width] uint8 staging buffers.

    ``pooled=False`` turns the pool into an allocator: acquire returns a
    fresh buffer, release is a no-op (for consumers that hold many
    batches at once, e.g. a whole staged window). ``aligned=True``
    allocates page-aligned buffers (O_DIRECT destinations).
    """

    def __init__(self, k: int, width: int, count: int, pooled: bool = True,
                 aligned: bool = False):
        self.shape = (k, width)
        self.pooled = pooled
        self.aligned = aligned
        self._closed = threading.Event()
        self._q: queue.Queue = queue.Queue()
        if pooled:
            for _ in range(max(count, 2)):
                self._q.put(self._alloc())

    def _alloc(self) -> np.ndarray:
        if self.aligned:
            return _aligned_empty(self.shape)
        return np.empty(self.shape, dtype=np.uint8)

    def acquire(self) -> np.ndarray:
        if not self.pooled:
            return self._alloc()
        # poll with a timeout so a consumer that stops recycling (error
        # paths) can never wedge the reader thread: close() unblocks us
        stalled = False
        while True:
            if self._closed.is_set():
                raise RuntimeError("feed closed while awaiting a buffer")
            try:
                return self._q.get(timeout=0.1)
            except queue.Empty:
                if not stalled:
                    stalled = True
                    if faults.fire("ec.feed.stall"):
                        raise RuntimeError(
                            "injected abort at ec.feed.stall")
                continue

    def try_acquire(self) -> Optional[np.ndarray]:
        """Non-blocking acquire (reader-pool lookahead must never block
        behind buffers the consumer hasn't recycled yet)."""
        if not self.pooled:
            return self._alloc()
        if self._closed.is_set():
            raise RuntimeError("feed closed while awaiting a buffer")
        try:
            return self._q.get_nowait()
        except queue.Empty:
            return None

    def release(self, buf: np.ndarray) -> None:
        if self.pooled:
            self._q.put(buf)

    def close(self) -> None:
        self._closed.set()


class _Pending:
    """One in-flight batch on the reader pool: its outstanding job count,
    completion event and any job errors."""

    __slots__ = ("out", "buf", "errors", "event", "_left", "_lock")

    def __init__(self, out: np.ndarray, buf: Optional[np.ndarray],
                 jobs: int):
        self.out = out
        self.buf = buf
        self.errors: list[BaseException] = []
        self.event = threading.Event()
        self._left = jobs
        self._lock = threading.Lock()
        if jobs == 0:
            self.event.set()

    def job_done(self, err: Optional[BaseException] = None) -> None:
        with self._lock:
            if err is not None:
                self.errors.append(err)
            self._left -= 1
            done = self._left <= 0
        if done:
            self.event.set()


class _ReaderPool:
    """N daemon threads running (fn, pending) fill jobs for one feed.

    close() makes every worker exit after its current job and fails any
    job that never ran, so a mid-read close can neither wedge a worker
    nor leave a _Pending waiter blocked forever."""

    def __init__(self, n: int):
        self._q: queue.Queue = queue.Queue()
        self._closed = False
        self._threads: list[threading.Thread] = []
        for i in range(n):
            th = threading.Thread(target=self._worker, daemon=True,
                                  name=f"ec-feed-reader-{i}")
            th.start()
            self._threads.append(th)

    def submit(self, fn: Callable[[], None], pending: _Pending) -> None:
        if self._closed:
            pending.job_done(RuntimeError("feed closed"))
            return
        self._q.put((fn, pending))

    def _worker(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            fn, pending = item
            try:
                fn()
            except BaseException as e:
                pending.job_done(e)
            else:
                pending.job_done()

    def close(self) -> None:
        self._closed = True
        for _ in self._threads:
            self._q.put(None)
        for th in self._threads:
            th.join()
        # fail whatever never ran (jobs queued behind the sentinels)
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                return
            if item is not None:
                item[1].job_done(RuntimeError("feed closed"))


def ordered_pool_map(pool: "_ReaderPool", fns: "Iterator[Callable]",
                     lookahead: int):
    """Run zero-arg callables on a reader pool, yielding their results
    strictly in submission order while up to ``lookahead`` later calls
    execute concurrently — the same ordered-window discipline as
    ``_FeedBase._ordered_parallel``, for work that isn't a stripe batch
    (the fused warm-down's compaction-filter chunks ride this). The
    first job error is re-raised at its in-order yield position; the
    ``finally`` waits the in-flight tail out (``_ReaderPool.close``
    fails unrun jobs, so the wait always terminates)."""
    window: deque = deque()
    it = iter(fns)
    exhausted = False
    try:
        while True:
            while not exhausted and len(window) <= max(int(lookahead), 0):
                fn = next(it, None)
                if fn is None:
                    exhausted = True
                    break
                slot: list = [None]
                pend = _Pending(None, None, 1)

                def job(fn=fn, slot=slot):
                    slot[0] = fn()

                pool.submit(job, pend)
                window.append((slot, pend))
            if not window:
                return
            slot, pend = window.popleft()
            pend.event.wait()
            if pend.errors:
                raise pend.errors[0]
            yield slot[0]
    finally:
        while window:
            _, pend = window.popleft()
            pend.event.wait()


_PLANS_DONE = object()


class _FeedBase:
    """Common assembly bookkeeping: lent-buffer tracking + recycling +
    the ordered reader-pool window."""

    def __init__(self, k: int, width: int, pool_buffers: int, pooled: bool,
                 readers: Optional[int] = None, aligned: bool = False):
        self.k = k
        self.width = width
        self.readers = (reader_count_default() if readers is None
                        else max(1, int(readers)))
        self.pool = BufferPool(k, width, pool_buffers, pooled,
                               aligned=aligned)
        self._rpool: Optional[_ReaderPool] = None
        self._lent: dict[int, np.ndarray] = {}
        self._lent_lock = threading.Lock()

    def _lend(self, buf: np.ndarray, out: np.ndarray) -> np.ndarray:
        """Register `out` (a view of pool buffer `buf`) as lent."""
        if self.pool.pooled:
            with self._lent_lock:
                self._lent[id(out)] = buf
        return out

    def recycle(self, batch: np.ndarray) -> None:
        """Return a batch's staging buffer to the pool. No-op for
        zero-copy views and unpooled buffers — always safe to call."""
        with self._lent_lock:
            buf = self._lent.pop(id(batch), None)
        if buf is not None:
            self.pool.release(buf)

    def _read_hook(self) -> None:
        """Chaos hook on every stripe/survivor read operation. A drop
        must FAIL the read — a feed that silently skips a read would
        feed zeros into the parity math."""
        if faults.fire("ec.feed.read"):
            raise IOError("injected drop at ec.feed.read")

    def _reader_pool(self) -> _ReaderPool:
        if self._rpool is None:
            self._rpool = _ReaderPool(self.readers)
        return self._rpool

    def _zero_copy(self, offsets: Sequence[int],
                   w: int) -> Optional[np.ndarray]:
        return None  # only the mmap feed can avoid the staging copy

    def _fill_segment(self, buf: np.ndarray, col: int,
                      offsets: Sequence[int], w: int) -> None:
        raise NotImplementedError

    def _fill_rows(self, buf: np.ndarray, col: int, offsets: Sequence[int],
                   w: int, lo: int, hi: int) -> None:
        """Fill rows lo..hi of one segment — the reader-pool work unit.
        Default: per-row fills via _fill_one."""
        for i in range(lo, hi):
            self._fill_one(buf, i, col, offsets[i], w)

    def _fill_one(self, buf: np.ndarray, row: int, col: int, off: int,
                  w: int) -> None:
        raise NotImplementedError

    def _prefault_jobs(self, view: np.ndarray, offsets: Sequence[int],
                       w: int) -> list:
        """Jobs that fault a zero-copy view's pages in on the reader
        pool (parallel disk read ahead of the consumer's gather).
        Non-mmap feeds have no views and return []."""
        return []

    # --- batch aggregation ---

    def batches(self, segments: Iterator[Segment],
                pad_final: bool = False) -> Iterator[np.ndarray]:
        """Aggregate stripe segments into [k, width] batches — the same
        column-concatenation the pipeline always used (consecutive
        segments append to the same shard files), so batch width never
        changes the on-disk layout. pad_final yields the last batch at
        full width, zero-padded (window executables need one shape).

        readers > 1 assembles on the reader pool (ordered yield);
        readers == 1 is the serial path, byte-identical output."""
        if self.readers <= 1:
            yield from self._batches_serial(segments, pad_final)
        else:
            yield from self._ordered_parallel(
                self._stripe_plans(segments, pad_final))

    def _batches_serial(self, segments: Iterator[Segment],
                        pad_final: bool) -> Iterator[np.ndarray]:
        buf: Optional[np.ndarray] = None
        col = 0
        for offsets, w in segments:
            if col == 0 and w == self.width:
                zc = self._zero_copy(offsets, w)
                if zc is not None:
                    yield zc
                    continue
            if buf is None:
                buf = self.pool.acquire()
            if col + w > self.width:
                yield self._lend(buf, buf[:, :col])
                buf = self.pool.acquire()
                col = 0
            self._read_hook()
            self._fill_segment(buf, col, offsets, w)
            col += w
        if buf is not None and col:
            if col < self.width and pad_final:
                buf[:, col:] = 0
                yield self._lend(buf, buf)
            else:
                yield self._lend(buf, buf[:, :col] if col < self.width
                                 else buf)

    def _stripe_plans(self, segments: Iterator[Segment],
                      pad_final: bool) -> Iterator[tuple]:
        """("view", view, offsets, w) | ("fill", fills, used_cols, pad):
        the same aggregation as the serial path, decisions only — no
        bytes move until the plan is submitted to the reader pool."""
        fills: list[tuple[int, Sequence[int], int]] = []
        col = 0
        for offsets, w in segments:
            if col == 0 and w == self.width:
                zc = self._zero_copy(offsets, w)
                if zc is not None:
                    yield ("view", zc, offsets, w)
                    continue
            if col + w > self.width:
                yield ("fill", fills, col, False)
                fills = []
                col = 0
            fills.append((col, offsets, w))
            col += w
        if fills:
            yield ("fill", fills, col, pad_final)

    def _submit_plan(self, plan: tuple,
                     block: bool) -> Optional[_Pending]:
        """Turn one plan into reader-pool jobs. block=False returns None
        instead of waiting for a staging buffer (ordered lookahead must
        not deadlock against buffers the consumer still holds)."""
        rpool = self._reader_pool()
        if plan[0] == "view":
            _, view, offsets, w = plan
            jobs = self._prefault_jobs(view, offsets, w)
            pend = _Pending(view, None, len(jobs))
            for fn in jobs:
                rpool.submit(fn, pend)
            return pend
        _, fills, used, pad = plan
        buf = self.pool.acquire() if block else self.pool.try_acquire()
        if buf is None:
            return None
        if used < self.width:
            out = buf if pad else buf[:, :used]
        else:
            out = buf
        self._lend(buf, out)
        # split fills into jobs: many small fills parallelize as-is; a
        # single wide fill (large-block stripe) splits across its k rows
        jobs: list[Callable[[], None]] = []
        per_fill = max(1, self.readers // max(len(fills), 1))
        for (c, offsets, w) in fills:
            k = len(offsets)
            step = max(1, -(-k // per_fill))
            for lo in range(0, k, step):
                hi = min(lo + step, k)

                def job(c=c, offsets=offsets, w=w, lo=lo, hi=hi):
                    self._read_hook()
                    self._fill_rows(buf, c, offsets, w, lo, hi)

                jobs.append(job)
        if pad and used < self.width:
            def pad_job(used=used):
                buf[:, used:] = 0

            jobs.append(pad_job)
        pend = _Pending(out, buf, len(jobs))
        for fn in jobs:
            rpool.submit(fn, pend)
        return pend

    def _await_pending(self, pend: _Pending) -> np.ndarray:
        while not pend.event.wait(0.05):
            if self.pool._closed.is_set():
                raise RuntimeError("feed closed while assembling a batch")
        if pend.errors:
            self.recycle(pend.out)
            raise pend.errors[0]
        return pend.out

    def _ordered_parallel(self, plans: Iterator[tuple]
                          ) -> Iterator[np.ndarray]:
        """Yield plan results strictly in order while up to readers+1
        later plans assemble concurrently on the reader pool."""
        window: deque[_Pending] = deque()
        it = iter(plans)
        next_plan: object = None
        exhausted = False
        lookahead = self.readers + 1
        try:
            while True:
                while not exhausted and len(window) <= lookahead:
                    if next_plan is None:
                        next_plan = next(it, _PLANS_DONE)
                        if next_plan is _PLANS_DONE:
                            exhausted = True
                            break
                    pend = self._submit_plan(next_plan,
                                             block=not window)
                    if pend is None:
                        break  # no free buffer: yield one first
                    next_plan = None
                    window.append(pend)
                if not window:
                    return
                yield self._await_pending(window.popleft())
        finally:
            # error/early-close path: wait the in-flight jobs out (or
            # until close() fails them) and recycle their buffers so
            # pooled staging keeps circulating
            while window:
                pend = window.popleft()
                while not pend.event.wait(0.05):
                    if self.pool._closed.is_set():
                        break
                self.recycle(pend.out)

    def close(self) -> None:
        self.pool.close()
        if self._rpool is not None:
            self._rpool.close()
            self._rpool = None


class _DirectReader:
    """Shared O_DIRECT read discipline for the pread-based feeds: direct
    pread when (offset, length, destination address) are all aligned,
    buffered fd otherwise; EINVAL from a filesystem that lied about
    supporting O_DIRECT permanently downgrades to buffered."""

    def __init__(self, path: str, odirect: bool):
        self.fd = os.open(path, os.O_RDONLY)
        self.fd_direct = -1
        self.use_direct = False
        if odirect and hasattr(os, "O_DIRECT"):
            try:
                self.fd_direct = os.open(path, os.O_RDONLY | os.O_DIRECT)
                self.use_direct = True
            except OSError:
                self.fd_direct = -1  # fs refuses O_DIRECT: buffered only

    def read_row(self, dest: np.ndarray, offset: int) -> int:
        """pread `dest` bytes at `offset`, zero-filling past EOF;
        O_DIRECT when the span allows it."""
        if (self.use_direct and offset % _ALIGN == 0
                and dest.nbytes % _ALIGN == 0
                and dest.ctypes.data % _ALIGN == 0):
            try:
                return _readinto(self.fd_direct, dest, offset)
            except OSError as e:
                if e.errno != errno.EINVAL:
                    raise
                # downgrade is FLAG-ONLY: reader-pool threads share this
                # object, and closing fd_direct here would race their
                # in-flight preadvs (EBADF at best, a reused fd number at
                # worst). The fd stays open until close().
                self.use_direct = False
        return _readinto(self.fd, dest, offset)

    @property
    def direct(self) -> bool:
        return self.use_direct

    def close(self) -> None:
        if self.fd >= 0:
            os.close(self.fd)
            self.fd = -1
        if self.fd_direct >= 0:
            os.close(self.fd_direct)
            self.fd_direct = -1


class MmapFeed(_FeedBase):
    """Page-cache-mapped stripe feed over one .dat file."""

    def __init__(self, path: str, k: int, width: int,
                 pool_buffers: int = 4, pooled: bool = True,
                 readers: Optional[int] = None):
        super().__init__(k, width, pool_buffers, pooled, readers=readers)
        self.size = os.path.getsize(path)
        self._fd = os.open(path, os.O_RDONLY)
        self._mm: Optional[mmap.mmap] = None
        self._view: Optional[np.ndarray] = None
        if self.size:
            try:
                self._mm = mmap.mmap(self._fd, self.size, mmap.MAP_SHARED,
                                     mmap.PROT_READ)
            except (OSError, ValueError):
                os.close(self._fd)  # open_feed falls back to PreadvFeed
                self._fd = -1
                raise
            # advise sequential so readahead keeps the page cache ahead of
            # the feed (harmless no-op where unsupported)
            try:
                self._mm.madvise(mmap.MADV_SEQUENTIAL)
            except (AttributeError, OSError):
                pass
            self._view = np.frombuffer(self._mm, dtype=np.uint8)

    def _zero_copy(self, offsets: Sequence[int], w: int
                   ) -> Optional[np.ndarray]:
        """[k, w] as_strided view when the segment's rows are uniformly
        strided and fully inside the file — no bytes move at all."""
        if self._view is None or offsets[-1] + w > self.size:
            return None
        if self.k == 1:
            return self._view[offsets[0]:offsets[0] + w].reshape(1, w)
        stride = offsets[1] - offsets[0]
        if any(offsets[i + 1] - offsets[i] != stride
               for i in range(self.k - 1)):
            return None
        return np.lib.stride_tricks.as_strided(
            self._view[offsets[0]:], shape=(self.k, w),
            strides=(stride, 1))

    def _prefault_jobs(self, view: np.ndarray, offsets: Sequence[int],
                       w: int) -> list:
        """Touch one byte per page of each row's span: the reader pool
        faults the pages in concurrently (the actual disk reads), so
        the consumer's gather — device_put or the staging copy — never
        stalls single-threaded on major faults."""
        if self._view is None:
            return []
        src = self._view
        jobs = []
        k = len(offsets)
        step = max(1, -(-k // self.readers))
        page = mmap.PAGESIZE or _ALIGN
        for lo in range(0, k, step):
            rows = list(offsets[lo:lo + step])

            def job(rows=rows):
                self._read_hook()
                for off in rows:
                    stop = min(off + w, src.shape[0])
                    if off < stop:
                        # reading every page-th byte faults the pages
                        int(np.sum(src[off:stop:page], dtype=np.uint64))

            jobs.append(job)
        return jobs

    def _fill_segment(self, buf: np.ndarray, col: int,
                      offsets: Sequence[int], w: int) -> None:
        view, size = self._view, self.size
        if (view is not None and len(offsets) > 1
                and all(offsets[i + 1] - offsets[i] == w
                        for i in range(len(offsets) - 1))
                and offsets[0] + len(offsets) * w <= size):
            # contiguous k-row run (small-block rows): ONE vectorized copy
            start = offsets[0]
            src = view[start:start + len(offsets) * w]
            np.copyto(buf[:, col:col + w], src.reshape(len(offsets), w))
            return
        for i, off in enumerate(offsets):
            self._fill_one(buf, i, col, off, w)

    def _fill_one(self, buf: np.ndarray, row: int, col: int, off: int,
                  w: int) -> None:
        view, size = self._view, self.size
        n = min(w, size - off) if off < size else 0
        if n > 0:
            np.copyto(buf[row, col:col + n], view[off:off + n])
        if n < w:
            buf[row, col + n:col + w] = 0

    def close(self) -> None:
        super().close()
        self._view = None
        if self._mm is not None:
            try:
                self._mm.close()
            except BufferError:
                # live views (queued batches on an error path) still
                # reference the map; the GC closes it when they die
                pass
            self._mm = None
        if self._fd >= 0:
            os.close(self._fd)
            self._fd = -1


def _readinto(fd: int, dest: np.ndarray, offset: int) -> int:
    """preadv straight into a (contiguous) numpy row; loops on short
    reads, zero-fills past EOF. Returns bytes actually read."""
    done = 0
    n = dest.shape[0]
    while done < n:
        got = os.preadv(fd, [dest[done:]], offset + done)
        if got <= 0:
            dest[done:] = 0
            break
        done += got
    return done


class PreadvFeed(_FeedBase):
    """preadv-into-staging fallback (no mmap): still zero intermediate
    bytes objects, one syscall per contiguous k-row run (serial) or one
    pread per row range (reader pool / O_DIRECT)."""

    def __init__(self, path: str, k: int, width: int,
                 pool_buffers: int = 4, pooled: bool = True,
                 readers: Optional[int] = None,
                 odirect: Optional[bool] = None):
        if odirect is None:
            odirect = use_odirect_default()
        super().__init__(k, width, pool_buffers, pooled, readers=readers,
                         aligned=odirect)
        self.size = os.path.getsize(path)
        self._rd = _DirectReader(path, odirect)

    @property
    def _fd(self) -> int:  # back-compat for tests poking the raw fd
        return self._rd.fd

    def _fill_segment(self, buf: np.ndarray, col: int,
                      offsets: Sequence[int], w: int) -> None:
        k = len(offsets)
        if (not self._rd.direct and k > 1
                and all(offsets[i + 1] - offsets[i] == w
                        for i in range(k - 1))
                and offsets[0] + k * w <= self.size):
            # contiguous k-row run: one preadv scatters the whole run
            # across the k staging rows
            rows = [buf[i, col:col + w] for i in range(k)]
            done = 0
            total = k * w
            while done < total:
                row, sub = divmod(done, w)
                iov = [rows[row][sub:]] + rows[row + 1:]
                got = os.preadv(self._rd.fd, iov, offsets[0] + done)
                if got <= 0:
                    break
                done += got
            if done < total:  # unexpected EOF: zero the remainder
                row, sub = divmod(done, w)
                rows[row][sub:] = 0
                for r in rows[row + 1:]:
                    r[:] = 0
            return
        for i, off in enumerate(offsets):
            self._fill_one(buf, i, col, off, w)

    def _fill_one(self, buf: np.ndarray, row: int, col: int, off: int,
                  w: int) -> None:
        if off >= self.size:
            buf[row, col:col + w] = 0
        else:
            self._rd.read_row(buf[row, col:col + w], off)

    def close(self) -> None:
        super().close()
        self._rd.close()


class ShardFeed(_FeedBase):
    """[k, n] batches whose row i comes from survivor shard file i — the
    rebuild-path twin of the stripe feeds. A short survivor file raises
    IOError (a truncated shard must fail the rebuild, not feed zeros).
    Runs on the same reader pool: each batch's k row reads split across
    the pool threads, so a rebuild storm drains at disk speed."""

    def __init__(self, paths: Sequence[str], width: int,
                 pool_buffers: int = 4, pooled: bool = True,
                 use_mmap: Optional[bool] = None,
                 readers: Optional[int] = None,
                 odirect: Optional[bool] = None):
        if odirect is None:
            odirect = use_odirect_default()
        if use_mmap is None:
            use_mmap = use_mmap_default() and not odirect
        super().__init__(len(paths), width, pool_buffers, pooled,
                         readers=readers, aligned=odirect)
        self.shard_size = os.path.getsize(paths[0])
        # all-or-nothing open: a failure on survivor 7 of 10 (EMFILE, a
        # shard deleted mid-plan) must close the readers already opened —
        # __init__ raising means close() can never be called on us
        self._rds: list[_DirectReader] = []
        try:
            for p in paths:
                self._rds.append(_DirectReader(p, odirect))
            self._sizes = [os.path.getsize(p) for p in paths]
        except BaseException:
            for rd in self._rds:
                rd.close()
            raise
        self._paths = list(paths)
        self._mms: list[Optional[mmap.mmap]] = [None] * self.k
        self._views: list[Optional[np.ndarray]] = [None] * self.k
        if use_mmap:
            for i, rd in enumerate(self._rds):
                if not self._sizes[i]:
                    continue
                try:
                    mm = mmap.mmap(rd.fd, self._sizes[i], mmap.MAP_SHARED,
                                   mmap.PROT_READ)
                except (OSError, ValueError):
                    continue  # this file reads via preadv instead
                try:
                    mm.madvise(mmap.MADV_SEQUENTIAL)
                except (AttributeError, OSError):
                    pass
                self._mms[i] = mm
                self._views[i] = np.frombuffer(mm, dtype=np.uint8)

    def _fill_row(self, buf: np.ndarray, i: int, offset: int,
                  n: int) -> None:
        if offset + n > self._sizes[i]:
            raise IOError(
                f"shard file {self._paths[i]} short read "
                f"{max(self._sizes[i] - offset, 0)} != {n}")
        view = self._views[i]
        if view is not None:
            np.copyto(buf[i, :n], view[offset:offset + n])
        else:
            got = self._rds[i].read_row(buf[i, :n], offset)
            if got != n:
                raise IOError(
                    f"shard file {self._paths[i]} short read "
                    f"{got} != {n}")

    def _shard_plans(self, batch_size: int,
                     pad_final: bool) -> Iterator[tuple]:
        """Base-shaped ("fill", ...) plans: one segment whose k rows all
        read from the same shard offset (row i = survivor file i), so
        _FeedBase._submit_plan's acquire/lend/split/pad machinery is
        reused verbatim — only _fill_one differs."""
        offset = 0
        while offset < self.shard_size:
            n = min(batch_size, self.shard_size - offset)
            yield ("fill", [(0, [offset] * self.k, n)], n, pad_final)
            offset += n

    def _fill_one(self, buf: np.ndarray, row: int, col: int, off: int,
                  w: int) -> None:
        self._fill_row(buf, row, off, w)

    def batches(self, batch_size: int,
                pad_final: bool = False) -> Iterator[np.ndarray]:
        if self.readers > 1:
            yield from self._ordered_parallel(
                self._shard_plans(batch_size, pad_final))
            return
        offset = 0
        while offset < self.shard_size:
            n = min(batch_size, self.shard_size - offset)
            buf = self.pool.acquire()
            self._read_hook()
            for i in range(self.k):
                self._fill_row(buf, i, offset, n)
            if n < batch_size:
                if pad_final:
                    buf[:, n:] = 0
                    yield self._lend(buf, buf)
                else:
                    yield self._lend(buf, buf[:, :n])
            else:
                yield self._lend(buf, buf)
            offset += n

    def close(self) -> None:
        super().close()
        for i, mm in enumerate(self._mms):
            self._views[i] = None
            if mm is not None:
                try:
                    mm.close()
                except BufferError:
                    pass
                self._mms[i] = None
        for rd in self._rds:
            rd.close()


def open_feed(path: str, k: int, width: int, pool_buffers: int = 4,
              pooled: bool = True,
              use_mmap: Optional[bool] = None,
              readers: Optional[int] = None,
              odirect: Optional[bool] = None) -> "_FeedBase":
    """The stripe feed for <base>.dat: mmap when possible, preadv
    otherwise. width must equal the pipeline batch size. O_DIRECT
    (``WEED_EC_ODIRECT=1`` or odirect=True) forces the pread path —
    page-cache bypass and mmap are mutually exclusive by construction."""
    if odirect is None:
        odirect = use_odirect_default()
    if odirect:
        return PreadvFeed(path, k, width, pool_buffers, pooled,
                          readers=readers, odirect=True)
    if use_mmap is None:
        use_mmap = use_mmap_default()
    if use_mmap:
        try:
            return MmapFeed(path, k, width, pool_buffers, pooled,
                            readers=readers)
        except (OSError, ValueError):
            pass  # e.g. filesystems that refuse MAP_SHARED; fall through
    return PreadvFeed(path, k, width, pool_buffers, pooled,
                      readers=readers, odirect=False)
