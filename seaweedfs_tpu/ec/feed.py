"""Zero-copy host feed for the EC pipeline (ec/pipeline.py).

BENCH_r05 pinned the encode pipeline at 0.72 GB/s with
``healthy_link_binding_stage: "disk_read (1-core host feed)"`` while the
window executable ran at 30-40 GB/s: the chip is starved by a host feed
that assembles every [k, B] batch through os.pread -> bytes object ->
np.frombuffer -> copy-into-aggregate — two full memcpys plus a heap
allocation per byte fed, all on one core. This module deletes that work:

- ``MmapFeed`` maps the source file once and exposes it as a numpy view
  over the page cache. A batch whose k rows sit at one uniform stride is
  yielded as an ``as_strided`` view: ZERO host copies (``device_put`` or
  the CPU coder gathers straight from the page cache). Aggregated batches
  (small-block rows) are assembled with one vectorized 2-D copy per
  contiguous k-row file run into a reusable staging buffer — one memcpy,
  no syscalls, no bytes objects.
- ``PreadvFeed`` is the fallback when mmap is unavailable (or forced via
  ``WEED_EC_MMAP=0``): ``os.preadv`` scatters each contiguous k-row file
  run straight into the staging-buffer rows — one syscall per run and no
  intermediate bytes objects (the classic pread path allocates and copies
  one bytes per row per batch).
- ``ShardFeed`` is the same idea for the rebuild path's k survivor shard
  files (one source file per row instead of one strided file).

Staging buffers come from a bounded ``BufferPool`` so the pipeline
double-buffers: batch N+1 assembles while batch N's device_put + kernel
are in flight, and memory stays at pool_size * k * batch bytes no matter
how long the volume is. The pipeline recycles a buffer once its batch is
fully consumed (parity materialized AND every shard row written). Feeds
with ``pooled=False`` hand out fresh buffers and recycling is a no-op —
the device-sink bench paths use that mode because a whole window of
batches stays referenced until its single dispatch.

Assembly runs single-threaded in the pipeline's reader thread (the old
path fanned k preads over a thread pool). That trades copy parallelism
for half — often all — of the copies; on the one-core hosts where the
feed binds, fewer copies is strictly faster, and on multi-core hosts the
reader thread still overlaps assembly with dispatch/compute.
"""

from __future__ import annotations

import mmap
import os
import queue
import threading
from typing import Iterator, Optional, Sequence

import numpy as np

# Segment = (k file offsets, width); produced by striping.stripe_segments
Segment = "tuple[list[int], int]"


def use_mmap_default() -> bool:
    """WEED_EC_MMAP=0 forces the preadv fallback (e.g. filesystems where
    mmap faults are slower than reads, or for A/B measurement)."""
    return os.environ.get("WEED_EC_MMAP", "1") not in ("0", "false", "no")


class BufferPool:
    """Bounded free-list of [k, width] uint8 staging buffers.

    ``pooled=False`` turns the pool into an allocator: acquire returns a
    fresh buffer, release is a no-op (for consumers that hold many
    batches at once, e.g. a whole staged window).
    """

    def __init__(self, k: int, width: int, count: int, pooled: bool = True):
        self.shape = (k, width)
        self.pooled = pooled
        self._closed = threading.Event()
        self._q: queue.Queue = queue.Queue()
        if pooled:
            for _ in range(max(count, 2)):
                self._q.put(np.empty(self.shape, dtype=np.uint8))

    def acquire(self) -> np.ndarray:
        if not self.pooled:
            return np.empty(self.shape, dtype=np.uint8)
        # poll with a timeout so a consumer that stops recycling (error
        # paths) can never wedge the reader thread: close() unblocks us
        while True:
            if self._closed.is_set():
                raise RuntimeError("feed closed while awaiting a buffer")
            try:
                return self._q.get(timeout=0.1)
            except queue.Empty:
                continue

    def release(self, buf: np.ndarray) -> None:
        if self.pooled:
            self._q.put(buf)

    def close(self) -> None:
        self._closed.set()


class _FeedBase:
    """Common assembly bookkeeping: lent-buffer tracking + recycling."""

    def __init__(self, k: int, width: int, pool_buffers: int, pooled: bool):
        self.k = k
        self.width = width
        self.pool = BufferPool(k, width, pool_buffers, pooled)
        self._lent: dict[int, np.ndarray] = {}
        self._lent_lock = threading.Lock()

    def _lend(self, buf: np.ndarray, out: np.ndarray) -> np.ndarray:
        """Register `out` (a view of pool buffer `buf`) as lent."""
        if self.pool.pooled:
            with self._lent_lock:
                self._lent[id(out)] = buf
        return out

    def recycle(self, batch: np.ndarray) -> None:
        """Return a batch's staging buffer to the pool. No-op for
        zero-copy views and unpooled buffers — always safe to call."""
        with self._lent_lock:
            buf = self._lent.pop(id(batch), None)
        if buf is not None:
            self.pool.release(buf)

    def _zero_copy(self, offsets: Sequence[int],
                   w: int) -> Optional[np.ndarray]:
        return None  # only the mmap feed can avoid the staging copy

    def _fill_segment(self, buf: np.ndarray, col: int,
                      offsets: Sequence[int], w: int) -> None:
        raise NotImplementedError

    def batches(self, segments: Iterator[Segment],
                pad_final: bool = False) -> Iterator[np.ndarray]:
        """Aggregate stripe segments into [k, width] batches — the same
        column-concatenation the pipeline always used (consecutive
        segments append to the same shard files), so batch width never
        changes the on-disk layout. pad_final yields the last batch at
        full width, zero-padded (window executables need one shape)."""
        buf: Optional[np.ndarray] = None
        col = 0
        for offsets, w in segments:
            if col == 0 and w == self.width:
                zc = self._zero_copy(offsets, w)
                if zc is not None:
                    yield zc
                    continue
            if buf is None:
                buf = self.pool.acquire()
            if col + w > self.width:
                yield self._lend(buf, buf[:, :col])
                buf = self.pool.acquire()
                col = 0
            self._fill_segment(buf, col, offsets, w)
            col += w
        if buf is not None and col:
            if col < self.width and pad_final:
                buf[:, col:] = 0
                yield self._lend(buf, buf)
            else:
                yield self._lend(buf, buf[:, :col] if col < self.width
                                 else buf)

    def close(self) -> None:
        self.pool.close()


class MmapFeed(_FeedBase):
    """Page-cache-mapped stripe feed over one .dat file."""

    def __init__(self, path: str, k: int, width: int,
                 pool_buffers: int = 4, pooled: bool = True):
        super().__init__(k, width, pool_buffers, pooled)
        self.size = os.path.getsize(path)
        self._fd = os.open(path, os.O_RDONLY)
        self._mm: Optional[mmap.mmap] = None
        self._view: Optional[np.ndarray] = None
        if self.size:
            try:
                self._mm = mmap.mmap(self._fd, self.size, mmap.MAP_SHARED,
                                     mmap.PROT_READ)
            except (OSError, ValueError):
                os.close(self._fd)  # open_feed falls back to PreadvFeed
                self._fd = -1
                raise
            # advise sequential so readahead keeps the page cache ahead of
            # the feed (harmless no-op where unsupported)
            try:
                self._mm.madvise(mmap.MADV_SEQUENTIAL)
            except (AttributeError, OSError):
                pass
            self._view = np.frombuffer(self._mm, dtype=np.uint8)

    def _zero_copy(self, offsets: Sequence[int], w: int
                   ) -> Optional[np.ndarray]:
        """[k, w] as_strided view when the segment's rows are uniformly
        strided and fully inside the file — no bytes move at all."""
        if self._view is None or offsets[-1] + w > self.size:
            return None
        if self.k == 1:
            return self._view[offsets[0]:offsets[0] + w].reshape(1, w)
        stride = offsets[1] - offsets[0]
        if any(offsets[i + 1] - offsets[i] != stride
               for i in range(self.k - 1)):
            return None
        return np.lib.stride_tricks.as_strided(
            self._view[offsets[0]:], shape=(self.k, w),
            strides=(stride, 1))

    def _fill_segment(self, buf: np.ndarray, col: int,
                      offsets: Sequence[int], w: int) -> None:
        view, size = self._view, self.size
        if (view is not None and len(offsets) > 1
                and all(offsets[i + 1] - offsets[i] == w
                        for i in range(len(offsets) - 1))
                and offsets[0] + len(offsets) * w <= size):
            # contiguous k-row run (small-block rows): ONE vectorized copy
            start = offsets[0]
            src = view[start:start + len(offsets) * w]
            np.copyto(buf[:, col:col + w], src.reshape(len(offsets), w))
            return
        for i, off in enumerate(offsets):
            n = min(w, size - off) if off < size else 0
            if n > 0:
                np.copyto(buf[i, col:col + n], view[off:off + n])
            if n < w:
                buf[i, col + n:col + w] = 0

    def close(self) -> None:
        super().close()
        self._view = None
        if self._mm is not None:
            try:
                self._mm.close()
            except BufferError:
                # live views (queued batches on an error path) still
                # reference the map; the GC closes it when they die
                pass
            self._mm = None
        if self._fd >= 0:
            os.close(self._fd)
            self._fd = -1


def _readinto(fd: int, dest: np.ndarray, offset: int) -> int:
    """preadv straight into a (contiguous) numpy row; loops on short
    reads, zero-fills past EOF. Returns bytes actually read."""
    done = 0
    n = dest.shape[0]
    while done < n:
        got = os.preadv(fd, [dest[done:]], offset + done)
        if got <= 0:
            dest[done:] = 0
            break
        done += got
    return done


class PreadvFeed(_FeedBase):
    """preadv-into-staging fallback (no mmap): still zero intermediate
    bytes objects, one syscall per contiguous k-row run."""

    def __init__(self, path: str, k: int, width: int,
                 pool_buffers: int = 4, pooled: bool = True):
        super().__init__(k, width, pool_buffers, pooled)
        self.size = os.path.getsize(path)
        self._fd = os.open(path, os.O_RDONLY)

    def _fill_segment(self, buf: np.ndarray, col: int,
                      offsets: Sequence[int], w: int) -> None:
        k = len(offsets)
        if (k > 1 and all(offsets[i + 1] - offsets[i] == w
                          for i in range(k - 1))
                and offsets[0] + k * w <= self.size):
            # contiguous k-row run: one preadv scatters the whole run
            # across the k staging rows
            rows = [buf[i, col:col + w] for i in range(k)]
            done = 0
            total = k * w
            while done < total:
                row, sub = divmod(done, w)
                iov = [rows[row][sub:]] + rows[row + 1:]
                got = os.preadv(self._fd, iov, offsets[0] + done)
                if got <= 0:
                    break
                done += got
            if done < total:  # unexpected EOF: zero the remainder
                row, sub = divmod(done, w)
                rows[row][sub:] = 0
                for r in rows[row + 1:]:
                    r[:] = 0
            return
        for i, off in enumerate(offsets):
            if off >= self.size:
                buf[i, col:col + w] = 0
            else:
                _readinto(self._fd, buf[i, col:col + w], off)

    def close(self) -> None:
        super().close()
        if self._fd >= 0:
            os.close(self._fd)
            self._fd = -1


class ShardFeed(_FeedBase):
    """[k, n] batches whose row i comes from survivor shard file i — the
    rebuild-path twin of the stripe feeds. A short survivor file raises
    IOError (a truncated shard must fail the rebuild, not feed zeros)."""

    def __init__(self, paths: Sequence[str], width: int,
                 pool_buffers: int = 4, pooled: bool = True,
                 use_mmap: Optional[bool] = None):
        super().__init__(len(paths), width, pool_buffers, pooled)
        if use_mmap is None:
            use_mmap = use_mmap_default()
        self.shard_size = os.path.getsize(paths[0])
        # all-or-nothing open: a failure on survivor 7 of 10 (EMFILE, a
        # shard deleted mid-plan) must close the fds already opened —
        # __init__ raising means close() can never be called on us
        self._fds: list[int] = []
        try:
            for p in paths:
                self._fds.append(os.open(p, os.O_RDONLY))
            self._sizes = [os.path.getsize(p) for p in paths]
        except BaseException:
            for fd in self._fds:
                try:
                    os.close(fd)
                except OSError:
                    pass
            raise
        self._paths = list(paths)
        self._mms: list[Optional[mmap.mmap]] = [None] * self.k
        self._views: list[Optional[np.ndarray]] = [None] * self.k
        if use_mmap:
            for i, fd in enumerate(self._fds):
                if not self._sizes[i]:
                    continue
                try:
                    mm = mmap.mmap(fd, self._sizes[i], mmap.MAP_SHARED,
                                   mmap.PROT_READ)
                except (OSError, ValueError):
                    continue  # this file reads via preadv instead
                try:
                    mm.madvise(mmap.MADV_SEQUENTIAL)
                except (AttributeError, OSError):
                    pass
                self._mms[i] = mm
                self._views[i] = np.frombuffer(mm, dtype=np.uint8)

    def batches(self, batch_size: int,
                pad_final: bool = False) -> Iterator[np.ndarray]:
        offset = 0
        while offset < self.shard_size:
            n = min(batch_size, self.shard_size - offset)
            buf = self.pool.acquire()
            for i in range(self.k):
                if offset + n > self._sizes[i]:
                    raise IOError(
                        f"shard file {self._paths[i]} short read "
                        f"{max(self._sizes[i] - offset, 0)} != {n}")
                view = self._views[i]
                if view is not None:
                    np.copyto(buf[i, :n], view[offset:offset + n])
                else:
                    got = _readinto(self._fds[i], buf[i, :n], offset)
                    if got != n:
                        raise IOError(
                            f"shard file {self._paths[i]} short read "
                            f"{got} != {n}")
            if n < batch_size:
                if pad_final:
                    buf[:, n:] = 0
                    yield self._lend(buf, buf)
                else:
                    yield self._lend(buf, buf[:, :n])
            else:
                yield self._lend(buf, buf)
            offset += n

    def close(self) -> None:
        super().close()
        for i, mm in enumerate(self._mms):
            self._views[i] = None
            if mm is not None:
                try:
                    mm.close()
                except BufferError:
                    pass
                self._mms[i] = None
        for i, fd in enumerate(self._fds):
            if fd >= 0:
                os.close(fd)
                self._fds[i] = -1


def open_feed(path: str, k: int, width: int, pool_buffers: int = 4,
              pooled: bool = True,
              use_mmap: Optional[bool] = None) -> "_FeedBase":
    """The stripe feed for <base>.dat: mmap when possible, preadv
    otherwise. width must equal the pipeline batch size."""
    if use_mmap is None:
        use_mmap = use_mmap_default()
    if use_mmap:
        try:
            return MmapFeed(path, k, width, pool_buffers, pooled)
        except (OSError, ValueError):
            pass  # e.g. filesystems that refuse MAP_SHARED; fall through
    return PreadvFeed(path, k, width, pool_buffers, pooled)
