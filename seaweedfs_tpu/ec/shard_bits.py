"""ShardBits: uint32 bitmask of held EC shard ids.

Wire form + algebra of the reference's ShardBits
(weed/storage/erasure_coding/ec_volume_info.go:61-113): each bit i set
means shard i is held; Plus/Minus merge holdings, MinusParityShards drops
the parity tail for data-only views.
"""

from __future__ import annotations

from typing import Iterable


def from_ids(ids: Iterable[int]) -> int:
    bits = 0
    for sid in ids:
        bits |= 1 << sid
    return bits


def to_ids(bits: int) -> list[int]:
    out = []
    i = 0
    while bits >> i:
        if bits & (1 << i):
            out.append(i)
        i += 1
    return out


def plus(bits: int, other: int) -> int:
    return bits | other


def minus(bits: int, other: int) -> int:
    return bits & ~other


def minus_parity_shards(bits: int, data_shards: int) -> int:
    """Keep only data-shard bits (MinusParityShards)."""
    return bits & ((1 << data_shards) - 1)


def count(bits: int) -> int:
    return bin(bits).count("1")
