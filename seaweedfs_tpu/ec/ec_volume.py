"""EC volume serving: read needles straight out of shard files.

Mirrors the reference serving path (weed/storage/erasure_coding/ec_volume.go,
ec_shard.go, ec_volume_delete.go and weed/storage/store_ec.go:122-376):

- .ecx is binary-searched on disk per lookup (entries sorted by needle id)
- a needle decomposes into intervals (locate.py); each interval is read from
  the local shard file when present, fetched from a peer when not, or
  reconstructed on line from any k shards as the last resort
- deletes tombstone the .ecx entry in place and append the id to .ecj

Remote access is abstracted as `shard_reader(shard_id, offset, size) ->
bytes | None`; the server layer plugs gRPC fetches in, tests plug files.
"""

from __future__ import annotations

import os
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Optional

import numpy as np

from ..cache import Singleflight
from ..storage import idx as idx_mod
from ..storage import types as t
from ..storage.needle import Needle
from ..storage.superblock import SuperBlock
from .coder import ErasureCoder

# shared fan-out pool for parallel remote-survivor fetches; sized for one
# reconstruction's worth of peers, shared across volumes
_SURVIVOR_POOL = ThreadPoolExecutor(max_workers=14,
                                    thread_name_prefix="ec-survivor")
from .geometry import DEFAULT, Geometry, to_ext
from .locate import Interval, locate_data

ShardReader = Callable[[int, int, int], Optional[bytes]]


class EcShard:
    """One local .ecNN file (EcVolumeShard, ec_shard.go:16-95).

    Reads come off a shared read-only mmap when available (one page-cache
    copy, no syscall per interval — the serving-path twin of the encode
    feed in ec/feed.py, same WEED_EC_MMAP switch); os.pread is the
    fallback and the out-of-bounds path."""

    def __init__(self, base_file_name: str, shard_id: int):
        self.shard_id = shard_id
        self.path = base_file_name + to_ext(shard_id)
        self._f = open(self.path, "rb")
        self.size = os.path.getsize(self.path)
        self._mm = None
        from .feed import use_mmap_default
        if self.size and use_mmap_default():
            import mmap as mmap_mod
            try:
                self._mm = mmap_mod.mmap(self._f.fileno(), self.size,
                                         mmap_mod.MAP_SHARED,
                                         mmap_mod.PROT_READ)
            except (OSError, ValueError):
                self._mm = None

    def read_at(self, offset: int, size: int) -> bytes:
        if self._mm is not None and 0 <= offset and offset + size <= self.size:
            return self._mm[offset:offset + size]
        # positioned read: no shared seek state, safe under concurrency;
        # short reads past EOF keep the reference semantics
        return os.pread(self._f.fileno(), size, offset)

    def close(self) -> None:
        if self._mm is not None:
            try:
                self._mm.close()
            except BufferError:
                pass
            self._mm = None
        self._f.close()


class EcVolume:
    def __init__(self, directory: str, collection: str, vid: int,
                 geometry: Geometry = DEFAULT,
                 coder: Optional[ErasureCoder] = None):
        self.dir = directory
        self.collection = collection
        self.vid = vid
        self.g = geometry
        self.coder = coder
        self.shards: dict[int, EcShard] = {}
        # shard size learned from a peer, for volumes served with no local
        # shards (the reference assumes Shards[0] exists, ec_volume.go:198)
        self.remote_shard_size = 0
        self._layout_checked = False
        self._lock = threading.RLock()
        # concurrent cold reads of one missing interval collapse into a
        # single peer fetch / reconstruction (a reconstruct reads k
        # shards and runs the coder — the most expensive read this
        # server can serve)
        self.read_flight = Singleflight("ec.read")

        base = self.base_file_name()
        if not os.path.exists(base + ".ecx"):
            raise FileNotFoundError(base + ".ecx")
        self._ecx = open(base + ".ecx", "r+b")
        self.ecx_size = os.path.getsize(base + ".ecx")
        self._ecj = open(base + ".ecj", "a+b")
        # volume version comes from the superblock at the head of .ec00
        # (readEcVolumeVersion, ec_decoder.go:73-90); default v3 if absent
        self.version = t.CURRENT_VERSION
        self.offset_size = t.OFFSET_SIZE
        ec00 = base + to_ext(0)
        if os.path.exists(ec00):
            with open(ec00, "rb") as f:
                head = f.read(8)
            if len(head) == 8:
                sb = SuperBlock.from_bytes(head)
                self.version = sb.version
                self.offset_size = sb.offset_size
        self._entry_size = t.needle_map_entry_size(self.offset_size)

    def base_file_name(self) -> str:
        prefix = f"{self.collection}_" if self.collection else ""
        return os.path.join(self.dir, f"{prefix}{self.vid}")

    # --- shard management ---
    def add_shard(self, shard_id: int) -> bool:
        with self._lock:
            if shard_id in self.shards:
                return False
            self.shards[shard_id] = EcShard(self.base_file_name(), shard_id)
            return True

    def delete_shard(self, shard_id: int) -> bool:
        with self._lock:
            shard = self.shards.pop(shard_id, None)
            if shard is None:
                return False
            shard.close()
            return True

    def shard_ids(self) -> list[int]:
        return sorted(self.shards)

    def shard_size(self) -> int:
        for s in self.shards.values():
            return s.size
        return self.remote_shard_size

    def live_entries(self) -> list[tuple[int, int]]:
        """Live (needle_id, size) pairs from the sorted .ecx, skipping
        tombstones (the fsck inventory for EC volumes)."""
        out = []
        with self._lock:
            n = self.ecx_size // self._entry_size
            for i in range(n):
                entry = os.pread(self._ecx.fileno(),
                                 self._entry_size,
                                 i * self._entry_size)
                key, offset, size = idx_mod.unpack_entry(
                    entry, offset_size=self.offset_size)
                if not t.size_is_deleted(size):
                    out.append((key, size))
        return out

    # --- index lookup ---
    def find_needle(self, needle_id: int) -> tuple[int, int]:
        """(stored_offset, size) via on-disk binary search
        (SearchNeedleFromSortedIndex, ec_volume.go:210-235)."""
        return self._search(needle_id)

    def _search(self, needle_id: int,
                on_found: Optional[Callable[[int], None]] = None
                ) -> tuple[int, int]:
        lo, hi = 0, self.ecx_size // self._entry_size
        while lo < hi:
            mid = (lo + hi) // 2
            entry = os.pread(self._ecx.fileno(), self._entry_size,
                             mid * self._entry_size)
            key, offset, size = idx_mod.unpack_entry(
                entry, offset_size=self.offset_size)
            if key == needle_id:
                if on_found is not None:
                    on_found(mid * self._entry_size)
                return offset, size
            if key < needle_id:
                lo = mid + 1
            else:
                hi = mid
        raise KeyError(f"needle {needle_id:x} not in ec volume {self.vid}")

    def locate(self, needle_id: int) -> tuple[int, int, list[Interval]]:
        """(offset, size, intervals) for a needle
        (LocateEcShardNeedle, ec_volume.go:190-204)."""
        offset, size = self.find_needle(needle_id)
        if t.size_is_deleted(size):
            return offset, size, []
        shard_size = self.shard_size()
        if shard_size == 0:
            raise IOError(
                f"ec volume {self.vid}: shard size unknown (no local shards; "
                f"set remote_shard_size before serving remote-only reads)")
        if not self._layout_checked:
            from .striping import check_layout_marker
            check_layout_marker(self.base_file_name(), shard_size, self.g)
            self._layout_checked = True
        dat_size = self.g.data_shards * shard_size
        intervals = locate_data(
            self.g, dat_size, t.stored_to_offset(offset),
            t.get_actual_size(size, self.version))
        return offset, size, intervals

    # --- read path ---
    def read_needle(self, needle_id: int, cookie: Optional[int] = None,
                    shard_reader: Optional[ShardReader] = None) -> Needle:
        offset, size, intervals = self.locate(needle_id)
        if t.size_is_deleted(size):
            raise KeyError(f"needle {needle_id:x} deleted")
        parts = [self._read_interval(iv, shard_reader) for iv in intervals]
        record = b"".join(parts)
        n = Needle.from_bytes(record, self.version)
        if cookie is not None and n.cookie != cookie:
            raise KeyError(f"needle {needle_id:x} cookie mismatch")
        return n

    def _read_interval(self, iv: Interval,
                       shard_reader: Optional[ShardReader]) -> bytes:
        shard_id, offset = iv.to_shard_id_and_offset(self.g)
        shard = self.shards.get(shard_id)
        if shard is not None:
            data = shard.read_at(offset, iv.size)
            if len(data) == iv.size:
                return data
        # non-local interval: peer fetch or (worst case) an on-line
        # reconstruction from k shards — N concurrent readers of the
        # same cold interval share one flight
        def fetch() -> bytes:
            if shard_reader is not None:
                data = shard_reader(shard_id, offset, iv.size)
                if data is not None and len(data) == iv.size:
                    return data
            return self._reconstruct_interval(shard_id, offset, iv.size,
                                              shard_reader)

        return self.read_flight.do((shard_id, offset, iv.size), fetch)

    def _reconstruct_interval(self, missing_shard: int, offset: int,
                              size: int,
                              shard_reader: Optional[ShardReader]) -> bytes:
        """Online reconstruction of one interval from any k other shards.
        Local shards are read inline; remote survivors are fetched in
        parallel, matching the reference's goroutine fan-out
        (recoverOneRemoteEcShardInterval, store_ec.go:322-376)."""
        if self.coder is None:
            raise IOError(
                f"shard {missing_shard} missing and no coder to reconstruct")
        shards: list[Optional[np.ndarray]] = [None] * self.g.total_shards
        have = 0
        remote_candidates: list[int] = []
        for sid in range(self.g.total_shards):
            if sid == missing_shard:
                continue
            local = self.shards.get(sid)
            if local is not None and have < self.g.data_shards:
                b = local.read_at(offset, size)
                if len(b) == size:
                    shards[sid] = np.frombuffer(b, dtype=np.uint8)
                    have += 1
                    continue
            remote_candidates.append(sid)
        need = self.g.data_shards - have
        if need > 0 and shard_reader is not None and remote_candidates:
            futs = {sid: _SURVIVOR_POOL.submit(shard_reader, sid, offset,
                                               size)
                    for sid in remote_candidates}
            for sid, fut in futs.items():
                if have >= self.g.data_shards:
                    fut.cancel()
                    continue
                try:
                    b = fut.result()
                except Exception:
                    continue
                if b is not None and len(b) == size:
                    shards[sid] = np.frombuffer(b, dtype=np.uint8)
                    have += 1
        if have < self.g.data_shards:
            raise IOError(
                f"cannot reconstruct shard {missing_shard}: "
                f"only {have} of {self.g.data_shards} shards reachable")
        rebuilt = self.coder.reconstruct(shards, targets=(missing_shard,))
        return np.asarray(rebuilt[missing_shard]).tobytes()

    # --- delete path ---
    def delete_needle(self, needle_id: int) -> None:
        """Tombstone in .ecx + journal to .ecj
        (DeleteNeedleFromEcx, ec_volume_delete.go:27-49)."""
        with self._lock:
            def mark(entry_offset: int) -> None:
                os.pwrite(self._ecx.fileno(),
                          t.put_u32(t.size_to_u32(t.TOMBSTONE_FILE_SIZE)),
                          entry_offset + t.NEEDLE_ID_SIZE
                          + self.offset_size)

            try:
                self._search(needle_id, on_found=mark)
            except KeyError:
                return
            self._ecj.seek(0, os.SEEK_END)
            self._ecj.write(t.put_u64(needle_id))
            self._ecj.flush()

    def close(self) -> None:
        with self._lock:
            for shard in self.shards.values():
                shard.close()
            self.shards.clear()
            self._ecx.close()
            self._ecj.close()


def rebuild_ecx_file(base_file_name: str,
                     offset_size: int = t.OFFSET_SIZE) -> None:
    """Re-apply .ecj tombstones into .ecx after a rebuild, then drop .ecj
    (RebuildEcxFile, ec_volume_delete.go:51-97)."""
    ecj_path = base_file_name + ".ecj"
    if not os.path.exists(ecj_path):
        return
    entry_size = t.needle_map_entry_size(offset_size)
    ecx_size = os.path.getsize(base_file_name + ".ecx")
    with open(base_file_name + ".ecx", "r+b") as ecx, \
            open(ecj_path, "rb") as ecj:
        while True:
            b = ecj.read(t.NEEDLE_ID_SIZE)
            if len(b) != t.NEEDLE_ID_SIZE:
                break
            needle_id = t.get_u64(b)
            lo, hi = 0, ecx_size // entry_size
            while lo < hi:
                mid = (lo + hi) // 2
                ecx.seek(mid * entry_size)
                key, _, _ = idx_mod.unpack_entry(
                    ecx.read(entry_size), offset_size=offset_size)
                if key == needle_id:
                    ecx.seek(mid * entry_size
                             + t.NEEDLE_ID_SIZE + offset_size)
                    ecx.write(t.put_u32(t.size_to_u32(t.TOMBSTONE_FILE_SIZE)))
                    break
                if key < needle_id:
                    lo = mid + 1
                else:
                    hi = mid
    os.remove(ecj_path)
