"""File-level EC operations: .dat <-> .ec00..ec13 (+ .ecx/.ecj/.idx).

Capability-parity port of the reference pipeline
(weed/storage/erasure_coding/ec_encoder.go:57-306, ec_decoder.go), with the
RS math routed through the pluggable ErasureCoder (TPU by default). On-disk
artifacts are byte-identical to the reference for the same input:

- shard files are written row-major: while more than one large row of data
  remains, a row is k large blocks RS-encoded batch-by-batch; the tail is
  striped in small-block rows; the final batch is zero-padded but written
  full-length, so shard sizes are whole multiples of the block sizes.
- .ecx is the .idx journal folded and sorted ascending by needle id.
- .ecj is a flat journal of deleted needle ids (8 bytes each).

The batch width fed to the coder is tunable: correctness is invariant to it
(striping layout only depends on block sizes), so the TPU path uses wide
batches to fill the chip while the reference used 256KB buffers.
"""

from __future__ import annotations

import os
from typing import Callable, Iterator, Optional

import numpy as np

from ..storage import idx as idx_mod
from ..storage import types as t
from ..storage.needle_map import SortedNeedleMap
from ..utils import durable
from .coder import ErasureCoder
from .geometry import DEFAULT, Geometry, to_ext

DEFAULT_BUFFER_SIZE = 256 * 1024


def clamp_batch(batch_size: int, block_size: int) -> int:
    """Largest usable stripe-batch width: divides block_size, <= batch_size."""
    b = min(batch_size, block_size)
    while block_size % b:
        b -= 1
    return b


def _open_all(paths: list, mode: str) -> list:
    """Open every path or none: a failure mid-way (EMFILE, ENOSPC, a
    permission wall on shard 7 of 14) closes the handles already opened
    before re-raising — the bare comprehension this replaces leaked
    them with no reference left to close."""
    files: list = []
    try:
        for p in paths:
            files.append(open(p, mode))
    except BaseException:
        for f in files:
            try:
                f.close()
            except OSError:
                pass
        raise
    return files


def stripe_segments(dat_size: int, g: Geometry,
                    batch_size: int) -> Iterator[tuple[list[int], int]]:
    """(k strided .dat offsets, width) per stripe batch, in shard-file
    append order (row-major two-tier striping, ec_encoder.go:194-231).

    This is THE layout iteration — write_ec_files' row loop, the streaming
    pipeline and the zero-copy feed (ec/feed.py) all derive shard bytes
    from these segments, which is what keeps their outputs byte-identical.
    Offsets within one segment are uniformly strided by the block size;
    offsets at or past dat_size read as zeros (final-row padding).
    """
    def rows(start: int, block_size: int) -> Iterator[tuple[list[int], int]]:
        b = clamp_batch(batch_size, block_size)
        for batch_start in range(0, block_size, b):
            yield ([start + block_size * i + batch_start
                    for i in range(g.data_shards)], b)

    remaining = dat_size
    processed = 0
    # same large-row rule as write_ec_files: a tail needing a full
    # large_block worth of small rows would make the shard size ambiguous
    # for locate; pad the final large row instead
    while remaining > g.large_row_size - g.small_row_size:
        yield from rows(processed, g.large_block_size)
        remaining -= g.large_row_size
        processed += g.large_row_size
    while remaining > 0:
        yield from rows(processed, g.small_block_size)
        remaining -= g.small_row_size
        processed += g.small_row_size


def write_sorted_ecx_from_idx(base_file_name: str, ext: str = ".ecx",
                              offset_size: int = t.OFFSET_SIZE) -> None:
    """Generate the sorted EC index from the .idx journal
    (WriteSortedFileFromIdx, ec_encoder.go:27-54)."""
    db = SortedNeedleMap.from_idx_file(base_file_name + ".idx", offset_size)
    db.write_sorted_index(base_file_name + ext)


def write_ec_files(base_file_name: str, coder: ErasureCoder,
                   geometry: Geometry = DEFAULT,
                   buffer_size: int = DEFAULT_BUFFER_SIZE) -> None:
    """Encode <base>.dat into <base>.ec00 .. (WriteEcFiles, ec_encoder.go:57)."""
    g = geometry
    assert coder.k == g.data_shards and coder.m == g.parity_shards
    dat_size = os.path.getsize(base_file_name + ".dat")
    outputs = _open_all([base_file_name + to_ext(i)
                         for i in range(g.total_shards)], "wb")
    try:
        with open(base_file_name + ".dat", "rb") as dat:
            remaining = dat_size
            processed = 0
            # large rows while the tail can't fit in < ratio small rows: a
            # tail of exactly large_block worth of small blocks would make
            # the shard size ambiguous (locate derives the large-row count
            # from k*shard_size, ec_locate.go:19-20 — the reference's own
            # encoder can produce that ambiguous layout and misaddress it;
            # here the final large row is zero-padded instead, same shard
            # size, unambiguous). FORMAT NOTE: this rule changed in-dev
            # (pre-release, no at-rest migration): shards whose dat tail
            # fell in (large_row - small_row, large_row) and were encoded
            # by the older rule must be re-encoded from their volume.
            while remaining > g.large_row_size - g.small_row_size:
                _encode_row(dat, coder, processed, g.large_block_size,
                            min(buffer_size, g.large_block_size), outputs, g)
                remaining -= g.large_row_size
                processed += g.large_row_size
            while remaining > 0:
                _encode_row(dat, coder, processed, g.small_block_size,
                            min(buffer_size, g.small_block_size), outputs, g)
                remaining -= g.small_row_size
                processed += g.small_row_size
        # shard bytes must be on the platter BEFORE the .ecm marker
        # commits the set: lifecycle retires the source .dat once the
        # shard set verifies, so un-synced shards dropped by a power
        # loss after retirement would be unrecoverable acked data
        for f in outputs:
            f.flush()
            os.fsync(f.fileno())
    finally:
        for f in outputs:
            f.close()
    write_layout_marker(base_file_name, dat_size, g)


LAYOUT_VERSION = 2  # padded-final-large-row tail rule (see write_ec_files)


def write_layout_marker(base_file_name: str, dat_size: int,
                        geometry: Optional[Geometry] = None,
                        shard_digests: "Optional[dict[int, int]]" = None
                        ) -> None:
    """Record the striping layout version — and, round 10 on, the RS
    geometry the shards were encoded under — in a .ecm sidecar so a
    shard set encoded under the PRE-round-3 tail rule (small rows where
    the new rule pads a large row) is detected at mount instead of
    silently misaddressing, and so rebuild/mount/decode never have to
    consult the (mutable) cluster geometry policy: the geometry travels
    with the shards. The marker is a sidecar — shard bytes stay
    bit-exact vs the reference's own fixture.

    `shard_digests` ({shard id: uint32 wrapping byte-sum}) stamps the
    scrubber's reference digests in the SAME commit: pipelines that
    accumulate digests while the rows stream through (stream_encode, the
    fused warm-down) establish the truth at encode time and the host
    never re-reads the fresh shards to digest them."""
    import json as json_mod
    meta: dict = {"layout_version": LAYOUT_VERSION, "dat_size": dat_size}
    if geometry is not None:
        meta["geometry"] = {
            "data_shards": geometry.data_shards,
            "parity_shards": geometry.parity_shards,
            "large_block_size": geometry.large_block_size,
            "small_block_size": geometry.small_block_size,
        }
    if shard_digests:
        meta["shard_digests"] = {str(k): int(v) & 0xFFFFFFFF
                                 for k, v in sorted(shard_digests.items())}
    # durable commit point of the whole shard set (see write_ec_files)
    durable.write_json_atomic(base_file_name + ".ecm", meta)


def read_marker_geometry(base_file_name: str) -> Optional[Geometry]:
    """The RS geometry stamped into the .ecm sidecar, or None (pre-
    round-10 markers, missing sidecar). Rebuild, mount and decode
    prefer this over any policy: the record of what the bytes ARE."""
    import json as json_mod
    try:
        with open(base_file_name + ".ecm") as f:
            meta = json_mod.load(f)
    except (OSError, ValueError):
        return None
    g = meta.get("geometry")
    if not isinstance(g, dict):
        return None
    try:
        return Geometry(
            data_shards=int(g["data_shards"]),
            parity_shards=int(g["parity_shards"]),
            large_block_size=int(g.get("large_block_size",
                                       DEFAULT.large_block_size)),
            small_block_size=int(g.get("small_block_size",
                                       DEFAULT.small_block_size)))
    except (KeyError, ValueError, AssertionError):
        return None


def check_layout_marker(base_file_name: str, shard_size: int,
                        g: Geometry) -> None:
    """Detect stale striping layouts at serve time. A marker with the
    wrong version is a hard error (the set was provably encoded under a
    different tail rule). An ABSENT marker on a shard size that is an
    exact multiple of large_block is only a loud warning: every healthy
    v2 volume of L whole large rows also has that size, and markers are
    sidecars that legitimately go missing (remote-only serving, shard
    copies from pre-marker peers) — refusing would take valid data
    offline on a heuristic. Pre-round-3 in-dev shard sets are the only
    ones the warning can actually indicate."""
    import json as json_mod
    path = base_file_name + ".ecm"
    if os.path.exists(path):
        with open(path) as f:
            meta = json_mod.load(f)
        if meta.get("layout_version") != LAYOUT_VERSION:
            raise IOError(
                f"{base_file_name}: EC layout version "
                f"{meta.get('layout_version')} != {LAYOUT_VERSION}; "
                "re-encode this volume (ec.encode)")
        return
    if (shard_size and shard_size >= g.large_block_size
            and shard_size % g.large_block_size == 0):
        import logging
        logging.getLogger("ec").warning(
            "%s: unmarked EC shard set whose size (%d) is a whole number "
            "of large blocks; if it was encoded before the v2 tail rule "
            "it will misaddress — re-encode (ec.encode) to stamp a .ecm",
            base_file_name, shard_size)


def _encode_row(dat, coder: ErasureCoder, start_offset: int, block_size: int,
                buffer_size: int, outputs, g: Geometry) -> None:
    """One stripe row: k blocks of block_size, encoded in buffer_size batches
    (encodeData + encodeDataOneBatch, ec_encoder.go:120-231)."""
    assert block_size % buffer_size == 0
    for batch_start in range(0, block_size, buffer_size):
        data = np.zeros((g.data_shards, buffer_size), dtype=np.uint8)
        for i in range(g.data_shards):
            dat.seek(start_offset + block_size * i + batch_start)
            chunk = dat.read(buffer_size)
            if chunk:
                data[i, :len(chunk)] = np.frombuffer(chunk, dtype=np.uint8)
        parity = coder.encode(data)
        for i in range(g.data_shards):
            outputs[i].write(data[i].tobytes())
        for j in range(g.parity_shards):
            outputs[g.data_shards + j].write(parity[j].tobytes())


def rebuild_ec_files(base_file_name: str, coder: ErasureCoder,
                     geometry: Geometry = DEFAULT,
                     buffer_size: Optional[int] = None) -> list[int]:
    """Regenerate missing shard files from >=k survivors
    (RebuildEcFiles, ec_encoder.go:61,89-118,233-287). Returns rebuilt ids."""
    g = geometry
    stride = buffer_size or g.small_block_size
    present = [i for i in range(g.total_shards)
               if os.path.exists(base_file_name + to_ext(i))]
    missing = [i for i in range(g.total_shards) if i not in present]
    if not missing:
        return []
    if len(present) < g.data_shards:
        raise ValueError(
            f"need {g.data_shards} shards to rebuild, have {len(present)}")

    inputs = dict(zip(present, _open_all(
        [base_file_name + to_ext(i) for i in present], "rb")))
    try:
        outputs = dict(zip(missing, _open_all(
            [base_file_name + to_ext(i) for i in missing], "wb")))
    except BaseException:
        for f in inputs.values():
            f.close()
        raise
    try:
        shard_size = os.path.getsize(base_file_name + to_ext(present[0]))
        offset = 0
        while offset < shard_size:
            n = min(stride, shard_size - offset)
            shards: list[Optional[np.ndarray]] = [None] * g.total_shards
            for i in present:
                inputs[i].seek(offset)
                chunk = inputs[i].read(n)
                if len(chunk) != n:
                    raise IOError(
                        f"shard {i} short read {len(chunk)} != {n}")
                shards[i] = np.frombuffer(chunk, dtype=np.uint8)
            rebuilt = coder.reconstruct(shards)
            for i in missing:
                outputs[i].write(np.asarray(rebuilt[i]).tobytes())
            offset += n
    finally:
        for f in inputs.values():
            f.close()
        for f in outputs.values():
            f.close()
    return missing


def iterate_ecx_file(base_file_name: str,
                     offset_size: int = t.OFFSET_SIZE
                     ) -> Iterator[tuple[int, int, int]]:
    yield from idx_mod.iter_index_file(base_file_name + ".ecx",
                                       offset_size=offset_size)


def iterate_ecj_file(base_file_name: str) -> Iterator[int]:
    path = base_file_name + ".ecj"
    if not os.path.exists(path):
        return
    with open(path, "rb") as f:
        while True:
            b = f.read(t.NEEDLE_ID_SIZE)
            if len(b) != t.NEEDLE_ID_SIZE:
                return
            yield t.get_u64(b)


def find_dat_file_size(base_file_name: str, version: int,
                       offset_size: int = t.OFFSET_SIZE) -> int:
    """Infer the original .dat size from the furthest live .ecx entry
    (FindDatFileSize, ec_decoder.go:48-71)."""
    dat_size = 0
    for key, stored_offset, size in iterate_ecx_file(base_file_name,
                                                     offset_size):
        if t.size_is_deleted(size):
            continue
        stop = (t.stored_to_offset(stored_offset)
                + t.get_actual_size(size, version))
        dat_size = max(dat_size, stop)
    return dat_size


def write_dat_file(base_file_name: str, dat_size: int,
                   geometry: Geometry = DEFAULT) -> None:
    """Reassemble .dat from data shards .ec00..ec09 by de-interleaving rows
    (WriteDatFile, ec_decoder.go:154-195)."""
    g = geometry
    inputs = _open_all([base_file_name + to_ext(i)
                        for i in range(g.data_shards)], "rb")
    try:
        with open(base_file_name + ".dat", "wb") as dat:
            remaining = dat_size
            # inverse of write_ec_files' large-row rule (the final large
            # row may be zero-padded, so clamp to the live remainder)
            while remaining > g.large_row_size - g.small_row_size:
                for f in inputs:
                    n = min(remaining, g.large_block_size)
                    _copy_n(f, dat, n)
                    remaining -= n
                    if remaining <= 0:
                        break
            while remaining > 0:
                for f in inputs:
                    n = min(remaining, g.small_block_size)
                    _copy_n(f, dat, n)
                    remaining -= n
                    if remaining <= 0:
                        break
    finally:
        for f in inputs:
            f.close()


def _copy_n(src, dst, n: int) -> None:
    while n > 0:
        chunk = src.read(min(n, 1 << 20))
        if not chunk:
            raise IOError("short shard file during decode")
        dst.write(chunk)
        n -= len(chunk)


def write_idx_file_from_ec_index(base_file_name: str,
                                 offset_size: int = t.OFFSET_SIZE) -> None:
    """.idx = .ecx copied verbatim + tombstones for every .ecj entry
    (WriteIdxFileFromEcIndex, ec_decoder.go:18-44)."""
    from ..storage.needle_map import remove_sidecars
    remove_sidecars(base_file_name + ".idx")
    with open(base_file_name + ".ecx", "rb") as ecx, \
            open(base_file_name + ".idx", "wb") as out:
        while True:
            chunk = ecx.read(1 << 20)
            if not chunk:
                break
            out.write(chunk)
        for key in iterate_ecj_file(base_file_name):
            out.write(idx_mod.pack_entry(key, 0, t.TOMBSTONE_FILE_SIZE,
                                         offset_size=offset_size))
