"""Streaming EC pipeline: disk -> host buffer -> HBM -> kernel -> shard files.

The naive encode loop (striping.write_ec_files) is the reference shape —
synchronous 256KB batches (weed/storage/erasure_coding/ec_encoder.go:162-231).
It leaves the chip idle while the host reads and writes. This module is the
production path: multi-MB batches with disk read, host->HBM transfer, kernel,
and shard write-back all overlapped.

Stages (bounded queues between them; every file gets its own writer thread so
shard write-back parallelizes across the 14 files):

  reader thread   -- assemble [k, B] uint8 batches through the zero-copy
                     host feed (ec/feed.py): mmap'd page-cache views where
                     the stripe allows, pooled double-buffered staging
                     otherwise (preadv fallback when mmap is unavailable),
                     push to a depth-bounded queue
  main thread     -- pop a batch, dispatch coder.encode_async (device_put +
                     jitted kernel; JAX dispatch is asynchronous so this
                     returns immediately with computation in flight)
  materializer    -- block on the parity handle (only this thread waits on
                     the device), then fan rows out to the per-file queues;
                     data rows go straight from the host buffer — data shards
                     never round-trip through the device
  k+m writers     -- one thread per shard file, coalescing queued rows into
                     single writev appends

Batch size and queue depths default to the adaptive governor's operating
point (ec/governor.py), tuned from the per-stage observe spans this module
emits — including the kernel FORMULATION axis (_steer_formulation:
governed runs apply the governor's planned lut/bitplane/xorsched choice
to the coder between runs, and under "xorsched" the staged-window sinks'
stage step also transposes each batch to uint32-packed bit-plane rows on
the stager pool, so every window kernel runs bit-plane-resident and the
expand/repack cost amortizes per-window, not per-batch). Explicit
arguments pin the plan. Only parity bytes (m/k of the input) cross
device->host. Layout semantics are identical to striping.write_ec_files:
row-major two-tier striping, final batch zero-padded and written
full-length (tests assert byte-identical output between the two paths).
"""

from __future__ import annotations

import contextlib
import os
import queue
import threading
import time
from typing import Iterator, Optional, Sequence

import numpy as np

from .. import observe
from ..utils import durable
from . import feed as feed_mod
from . import governor
from .coder import ErasureCoder
from .geometry import DEFAULT, Geometry, to_ext
from .striping import stripe_segments

# fallback operating point when the governor is bypassed (explicit args):
# 8MB per shard-row batch = 80MB host buffer per in-flight batch at RS(10,4)
DEFAULT_BATCH_SIZE = 8 * 1024 * 1024
DEFAULT_DEPTH = 4

_SENTINEL = None


def _resolve_op(batch_size: Optional[int], depth: Optional[int],
                nbytes: int, k: int,
                chips: int = 1) -> tuple["governor.OperatingPoint",
                                         bool]:
    """(operating point, governed?) — explicit args pin the plan and opt
    the run out of the governor entirely: no retuning from this run's
    shapes AND no export of a plan the run isn't using (tests and
    benches must neither steer nor misreport the process-global
    operating point). `chips` is the coder's mesh width — the governor
    scales the batch with it before deepening queues."""
    if batch_size is None and depth is None:
        return governor.get().plan(nbytes, k, chips=chips), True
    b = batch_size if batch_size is not None else DEFAULT_BATCH_SIZE
    d = depth if depth is not None else DEFAULT_DEPTH
    return governor.OperatingPoint(b, d, d,
                                   feed_mod.reader_count_default(),
                                   max(chips, 1)), False


def coder_chips(coder: ErasureCoder) -> int:
    """The device-mesh width a coder spreads each batch over (1 for
    every single-chip backend; parallel/mesh_coder.MeshCoder exports
    mesh_devices)."""
    return int(getattr(coder, "mesh_devices", 1) or 1)


def _steer_formulation(coder: ErasureCoder,
                       op: "governor.OperatingPoint"
                       ) -> "governor.OperatingPoint":
    """Apply the governor's planned kernel formulation to the coder
    BEFORE the run starts (a formulation switch swaps executables, so
    like every governor axis it lands between runs only). The coder
    reports the formulation it actually runs — env-pinned or explicitly
    constructed coders ignore the plan — and the returned op carries
    that, so finish_run's formulation model never attributes one
    kernel's spans to another. Coders without the hook (numpy, pallas,
    cpp) report "" which opts the run out of the formulation model."""
    retune = getattr(coder, "retune_formulation", None)
    if retune is None:
        return op._replace(formulation="")
    return op._replace(formulation=retune(op.formulation))


def stager_count_default() -> int:
    """WEED_EC_STAGERS: concurrent device_put threads for the staged-
    window sink (device_put releases the GIL, so stagers overlap the
    H2D copies with the reader pool's page faults instead of
    serializing fault -> copy -> fault). Same env rule as the reader
    pool: positive = clamped, unset/0 = one per core up to 4."""
    return feed_mod.env_thread_count("WEED_EC_STAGERS", 16)


class _FanOut:
    """One writer thread per output file, each with a bounded row queue;
    writers drain their queue greedily and append every waiting row in
    ONE os.writev call (straight from the row memory — no userspace
    write buffer, no per-row syscall)."""

    MAX_COALESCE = 16  # rows per writev: bounds latency and iov count

    def __init__(self, paths: Sequence[str], depth: int):
        self.queues = [queue.Queue(maxsize=depth) for _ in paths]
        self.errors: list[BaseException] = []
        self.threads = []
        for q, path in zip(self.queues, paths):
            th = threading.Thread(target=self._writer, args=(q, path),
                                  daemon=True)
            th.start()
            self.threads.append(th)

    @staticmethod
    def _writev_all(fd: int, rows: list) -> None:
        bufs = [memoryview(r) for r in rows]
        while bufs:
            n = os.writev(fd, bufs)
            if n <= 0:
                raise IOError("writev wrote nothing")
            while bufs and n >= bufs[0].nbytes:
                n -= bufs[0].nbytes
                bufs.pop(0)
            if n:
                bufs[0] = bufs[0][n:]

    def _writer(self, q: queue.Queue, path: str) -> None:
        batch: list = []
        stop = False  # close()'s sentinel already consumed
        try:
            fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
            try:
                while True:
                    item = q.get()
                    if item is _SENTINEL:
                        # sync before the .ecm marker commits the set:
                        # shards a power loss can drop must not be
                        # reachable from a durable marker
                        os.fsync(fd)
                        return
                    batch = [item]
                    while len(batch) < self.MAX_COALESCE and not q.empty():
                        nxt = q.get_nowait()
                        if nxt is _SENTINEL:
                            stop = True
                            break
                        batch.append(nxt)
                    self._writev_all(fd, [row for row, _ in batch])
                    for _, cb in batch:
                        if cb is not None:
                            cb()
                    batch = []
                    if stop:
                        os.fsync(fd)
                        return
            finally:
                os.close(fd)
        except BaseException as e:
            self.errors.append(e)
            # the coalesced rows already popped when the write failed
            # still need their callbacks: each belongs to a different
            # put_rows batch, and a skipped callback strands that
            # batch's pooled staging buffer for the rest of the run
            for _, cb in batch:
                if cb is not None:
                    cb()
            while not stop:  # drain (unless the sentinel was already
                item = q.get()  # swallowed mid-coalesce); never
                if item is _SENTINEL:  # deadlock the producer
                    return
                _, cb = item
                if cb is not None:
                    cb()  # keep buffers recycling on the error path

    def put_rows(self, rows: Iterator[np.ndarray],
                 on_done=None) -> None:
        """Enqueue one batch's rows; on_done fires once after EVERY row
        of this call has been handed to the kernel (the host batch may be
        a pooled staging buffer that must not be reused earlier)."""
        rows = [np.ascontiguousarray(r) for r in rows]
        cb = None
        if on_done is not None:
            state = {"left": len(rows)}
            lock = threading.Lock()

            def cb() -> None:
                with lock:
                    state["left"] -= 1
                    done = state["left"] == 0
                if done:
                    on_done()

        for q, row in zip(self.queues, rows):
            q.put((row, cb))

    def close(self) -> None:
        for q in self.queues:
            q.put(_SENTINEL)
        for th in self.threads:
            th.join()


def _traced_batches(batches: Iterator[np.ndarray],
                    ctx: "observe.TraceCtx") -> Iterator[np.ndarray]:
    """Wrap the read stage with one ec.read span per batch (runs in the
    reader thread, so spans use the explicit captured context). Manual
    record_span rather than observe.stage: the final next() pull only
    learns it was the sentinel after the timing window closes, and that
    empty pull must not record a span."""
    import time as time_mod
    it = iter(batches)
    i = 0
    while True:
        start_us = int(time_mod.time() * 1e6)
        t0 = time_mod.perf_counter()
        item = next(it, None)
        if item is None:
            return
        observe.record_span(
            "ec.read", ctx, start_us,
            int((time_mod.perf_counter() - t0) * 1e6),
            tags={"batch": i, "bytes": int(item.nbytes)})
        yield item
        i += 1


def _run_pipeline(batches: Iterator[np.ndarray], dispatch, consume,
                  depth: int, start_d2h: bool = True,
                  trace_ctx: "observe.TraceCtx | None" = None,
                  recycle=None) -> None:
    """reader thread -> main dispatch -> materializer thread.

    consume=None runs without the materializer stage entirely (sink mode:
    dispatch chains its own on-device state and nothing blocks per
    batch). recycle (optional) is called on batches drained without being
    consumed on error paths, so pooled feed buffers keep circulating."""
    read_q: queue.Queue = queue.Queue(maxsize=depth)
    mat_q: queue.Queue = queue.Queue(maxsize=depth)
    errors: list[BaseException] = []

    def _recycle(batch) -> None:
        if recycle is not None:
            try:
                recycle(batch)
            except Exception:
                pass

    def reader_main() -> None:
        try:
            for item in batches:
                read_q.put(item)
        except BaseException as e:
            errors.append(e)
        finally:
            read_q.put(_SENTINEL)

    def mat_main() -> None:
        try:
            while True:
                item = mat_q.get()
                if item is _SENTINEL:
                    return
                consume(*item)
        except BaseException as e:
            errors.append(e)
            while True:
                item = mat_q.get()
                if item is _SENTINEL:
                    return
                _recycle(item[0])

    reader = threading.Thread(target=reader_main, daemon=True)
    mat = None
    if consume is not None:
        mat = threading.Thread(target=mat_main, daemon=True)
        mat.start()
    reader.start()
    drained = False
    batch_i = 0
    try:
        while True:
            batch = read_q.get()
            if batch is _SENTINEL:
                drained = True
                break
            from ..observe.profiler import trace_annotation
            with contextlib.ExitStack() as stack:
                if trace_ctx is not None:
                    stack.enter_context(observe.stage(
                        "ec.dispatch", trace_ctx,
                        tags={"batch": batch_i}))
                stack.enter_context(
                    trace_annotation("ec_pipeline_dispatch"))
                try:
                    handle = dispatch(batch)
                except BaseException:
                    # the in-flight batch is nobody else's to recycle:
                    # the drain below only sees batches still QUEUED, so
                    # a dispatch that dies here would strand this one's
                    # pooled staging buffer lent forever
                    _recycle(batch)
                    raise
            batch_i += 1
            # kick the device->host copy off immediately so it overlaps the
            # next batch's H2D + kernel instead of starting at materialize
            # time (matters most when the transfer link is the bottleneck)
            start_async = (getattr(handle, "copy_to_host_async", None)
                           if start_d2h else None)
            if start_async is not None:
                try:
                    start_async()
                except Exception:
                    pass
            if mat is not None:
                mat_q.put((batch, handle))
    finally:
        if mat is not None:
            mat_q.put(_SENTINEL)
        # drain read_q so a reader blocked on a full queue can finish
        # (otherwise a dispatch() exception would deadlock reader.join())
        while not drained:
            item = read_q.get()
            if item is _SENTINEL:
                break
            _recycle(item)
        reader.join()
        if mat is not None:
            mat.join()
    if errors:
        raise errors[0]


def _stream_encode_core(batches: Iterator[np.ndarray], coder: ErasureCoder,
                        shard_paths: Sequence[str],
                        op: "governor.OperatingPoint",
                        tctx: "observe.TraceCtx",
                        recycle=None,
                        digests: "np.ndarray | None" = None) -> None:
    """The encode engine shared by stream_encode and the fused warm-down
    (ec/fused.py): host batches -> async dispatch -> materialize -> one
    writer thread per shard file. Returns with every shard file written
    AND fsynced; writes NO .ecm marker — committing the set is the
    caller's decision (the fused path orders the marker after its own
    .dat/.idx/.ecx finalization).

    `digests` (uint64[total_shards]) accumulates each shard row's
    wrapping byte-sum inline while the rows stream through — the
    scrubber's reference digest comes out of the encode pass itself and
    the host never re-reads the fresh shards to compute it."""
    fan = _FanOut(list(shard_paths), op.write_depth)

    def consume(data: np.ndarray, handle) -> None:
        from ..observe.profiler import trace_annotation
        with observe.stage("ec.kernel", tctx), \
                trace_annotation("ec_pipeline_kernel_wait"):
            parity = coder.materialize(handle)
        rows = [*data, *parity]
        if digests is not None:
            with observe.stage("ec.digest", tctx):
                for i, row in enumerate(rows):
                    digests[i] += np.sum(row, dtype=np.uint64)
        with observe.stage("ec.write", tctx):
            # data rows are written straight from the host batch (a
            # page-cache view or a pooled staging buffer); the buffer
            # recycles only after every row has been handed off
            cb = None
            if recycle is not None:
                cb = (lambda b=data: recycle(b))
            fan.put_rows(iter(rows), on_done=cb)

    try:
        _run_pipeline(
            _traced_batches(batches, tctx),
            coder.encode_async, consume, op.depth, trace_ctx=tctx,
            recycle=recycle)
    finally:
        fan.close()
    if fan.errors:
        raise fan.errors[0]


def stream_encode(base_file_name: str, coder: ErasureCoder,
                  geometry: Geometry = DEFAULT,
                  batch_size: Optional[int] = None,
                  depth: Optional[int] = None,
                  _op: "governor.OperatingPoint | None" = None) -> None:
    """Encode <base>.dat into shard files with the overlapped pipeline.

    Byte-identical output to striping.write_ec_files (WriteEcFiles,
    ec_encoder.go:57) — only the schedule differs. batch_size/depth
    default to the adaptive governor's operating point; passing them
    explicitly pins the schedule and skips retuning. _op pins a full
    operating point (stream_encode_many shares one across a window and
    does the window-level finish_run itself).
    """
    g = geometry
    assert coder.k == g.data_shards and coder.m == g.parity_shards
    run_t0 = time.perf_counter()
    dat_size = os.path.getsize(base_file_name + ".dat")
    if _op is not None:
        op, governed = _op, False
    else:
        op, governed = _resolve_op(batch_size, depth, dat_size,
                                   g.data_shards, coder_chips(coder))
        if governed:
            op = _steer_formulation(coder, op)
    src = feed_mod.open_feed(base_file_name + ".dat", g.data_shards,
                             op.batch_size, pool_buffers=op.depth + 2,
                             readers=op.readers)
    # per-stage spans share the caller's trace (volume server passes its
    # request context into this thread via observe.run_with); a fresh
    # root is minted when none is active (CLI/bench encodes)
    tctx = observe.ensure_ctx("ec")
    digests = np.zeros(g.total_shards, dtype=np.uint64)
    try:
        _stream_encode_core(
            src.batches(stripe_segments(dat_size, g, op.batch_size)),
            coder, [base_file_name + to_ext(i)
                    for i in range(g.total_shards)],
            op, tctx, recycle=src.recycle, digests=digests)
    finally:
        src.close()
    from .striping import write_layout_marker
    write_layout_marker(base_file_name, dat_size, g,
                        shard_digests={i: int(digests[i]) & 0xFFFFFFFF
                                       for i in range(g.total_shards)})
    if governed:
        governor.get().finish_run(tctx.trace_id, op, dat_size,
                                  g.data_shards)
    # chip-side runs report through the same wide-event plane as serving
    # requests, so cluster.tail attributes encode time by stage too
    from ..observe import wideevents
    wideevents.emit_stages(
        "ec", f"ec.encode {os.path.basename(base_file_name)}",
        tctx.trace_id, int((time.perf_counter() - run_t0) * 1e6),
        observe.stage_totals(tctx.trace_id, prefix="ec."))


def stream_encode_many(base_file_names: Sequence[str], coder: ErasureCoder,
                       geometry: Geometry = DEFAULT,
                       batch_size: Optional[int] = None,
                       depth: Optional[int] = None) -> int:
    """Encode N volumes back-to-back through ONE governed operating
    point — the encode-queue regime (lifecycle daemon batches, `ec.encode`
    multi-volume plans). The operating point is planned once for the
    whole window, so every volume feeds the same [k, B] batch shape and
    the coder's jit cache serves ONE executable for all of them (no
    per-volume recompiles, no per-volume program loads); the governor
    retunes once from the window's aggregate read/h2d/kernel/write
    spans. Returns the number of volumes encoded."""
    g = geometry
    bases = [b for b in base_file_names]
    if not bases:
        return 0
    total = sum(os.path.getsize(b + ".dat") for b in bases)
    op, governed = _resolve_op(batch_size, depth, total, g.data_shards,
                               coder_chips(coder))
    if governed:
        op = _steer_formulation(coder, op)
    tctx = observe.ensure_ctx("ec")
    for base in bases:
        with observe.stage("ec.volume", tctx, tags={"base": base}):
            observe.run_with(tctx, stream_encode, base, coder, g,
                             _op=op)
    if governed:
        governor.get().finish_run(tctx.trace_id, op, total, g.data_shards)
    return len(bases)


# staged window default: bounded so a >HBM volume streams in windows; one
# window should still swallow a bench-sized volume in one kernel launch
DEFAULT_WINDOW_BYTES = 2 * 1024 * 1024 * 1024


def _windowed_digest_sink(batches: Iterator[np.ndarray], dispatch_window,
                          stage, depth: int, window_bytes: int,
                          stats: dict | None,
                          stagers: Optional[int] = None) -> object:
    """The latency-aware sink schedule (round 4).

    Round 3 interleaved one digest dispatch per batch with the H2D puts;
    on the axon tunnel each launch costs ~0.3-0.4s AND the transfer path
    degrades ~100x once any encode kernel has executed, so the pipeline
    ran at per-op latency (0.02 GB/s), not link bandwidth. This schedule:

      reader thread -> host batches (bounded queue, disk overlaps staging)
      stager pool   -> stage_async each batch (H2D only, healthy link);
                       `stagers` > 1 keeps several device_puts in flight
                       (each releases the GIL) so the H2D copies overlap
                       the reader pool's page faults instead of
                       serializing fault -> copy -> fault on one thread
      window full   -> ONE multi-batch digest executable per window

    Within a window no kernel runs between transfers, and launch latency
    amortizes over the window. On healthy hosts window N+1's staging
    overlaps window N's (async) kernels — the schedule costs nothing.

    Fills `stats` (when given) with a measured components ledger:
    read-wait, stage seconds/bytes (plus the overlapped staging WALL
    span when stagers > 1), dispatch and materialize-wait seconds,
    batch/window counts — enough to compute each phase's rate and bound
    the pipeline arithmetically.
    """
    import time

    stagers = stagers if stagers is not None else stager_count_default()
    read_q: queue.Queue = queue.Queue(maxsize=depth)
    errors: list[BaseException] = []
    tctx = observe.ensure_ctx("ec")

    def reader_main() -> None:
        try:
            for item in batches:
                read_q.put(item)
        except BaseException as e:
            errors.append(e)
        finally:
            read_q.put(_SENTINEL)

    reader = threading.Thread(target=reader_main, daemon=True)
    reader.start()

    acc = None
    staged: list = []   # handles, or futures of handles (stagers > 1)
    staged_bytes = 0
    t_read = t_stage = t_dispatch = 0.0
    stage_span = [None, None]  # wall [first submit, last complete]
    n_batches = n_windows = 0
    total_bytes = 0

    executor = None
    if stagers > 1:
        from concurrent.futures import ThreadPoolExecutor
        executor = ThreadPoolExecutor(max_workers=stagers,
                                      thread_name_prefix="ec-stager")

    def do_stage(b):
        h = stage(b)
        block = getattr(h, "block_until_ready", None)
        if block is not None:
            block()
        stage_span[1] = time.perf_counter()
        return h

    def resolve(staged_items: list) -> list:
        return [h.result() if hasattr(h, "result") else h
                for h in staged_items]

    def flush_window() -> None:
        nonlocal acc, staged, staged_bytes, n_windows, t_dispatch, t_stage
        if not staged:
            return
        t0 = time.perf_counter()
        handles = resolve(staged)
        t_stage += time.perf_counter() - t0
        t0 = time.perf_counter()
        with observe.stage("ec.dispatch_window", tctx,
                           tags={"batches": len(handles)}):
            acc = dispatch_window(handles, acc)
        t_dispatch += time.perf_counter() - t0
        n_windows += 1
        staged = []
        staged_bytes = 0

    drained = False
    try:
        while True:
            t0 = time.perf_counter()
            batch = read_q.get()
            t_read += time.perf_counter() - t0
            if batch is _SENTINEL:
                drained = True
                break
            t0 = time.perf_counter()
            if stage_span[0] is None:
                stage_span[0] = t0
            if executor is not None:
                staged.append(executor.submit(do_stage, batch))
            else:
                staged.append(do_stage(batch))
            t_stage += time.perf_counter() - t0
            staged_bytes += batch.nbytes
            total_bytes += batch.nbytes
            n_batches += 1
            if staged_bytes >= window_bytes:
                flush_window()
        flush_window()
    finally:
        while not drained and read_q.get() is not _SENTINEL:
            pass  # unblock a reader stuck on a full queue after an error
        reader.join()
        if executor is not None:
            executor.shutdown(wait=True)
    if errors:
        raise errors[0]
    if stats is not None:
        stage_wall = (round(stage_span[1] - stage_span[0], 3)
                      if stage_span[0] is not None
                      and stage_span[1] is not None else 0.0)
        # the effective staging time: with one stager the main thread's
        # blocked time IS the wall; with a pool the wall span covers the
        # overlapped copies (blocked time alone would under-report)
        stage_eff = t_stage if executor is None else (stage_wall
                                                      or t_stage)
        stats.update({
            "staged_bytes": total_bytes, "n_batches": n_batches,
            "n_windows": n_windows, "read_wait_s": round(t_read, 3),
            "stage_s": round(stage_eff, 3),
            "stage_blocked_s": round(t_stage, 3),
            "stagers": stagers,
            "stage_gbps": (round(total_bytes / stage_eff / 1e9, 3)
                           if stage_eff > 1e-9 else None),
            "dispatch_s": round(t_dispatch, 3),
        })
    return acc


def stream_encode_device_sink(base_file_name: str, coder: ErasureCoder,
                              geometry: Geometry = DEFAULT,
                              batch_size: int = DEFAULT_BATCH_SIZE,
                              depth: int = DEFAULT_DEPTH,
                              window_bytes: int = DEFAULT_WINDOW_BYTES,
                              stats: dict | None = None,
                              materialize: bool = True,
                              stagers: Optional[int] = None,
                              readers: Optional[int] = None) -> np.ndarray:
    """stream_encode with the parity landing in an on-device sink.

    Runs the same reader schedule as stream_encode but stages batches onto
    the device first and reduces each window's parity to a [m] uint32
    wrapping byte-sum digest in ONE executable per window
    (_windowed_digest_sink) — only 4*m bytes ever cross device->host and
    no shard files are written. Returns the combined digest.

    Two uses:
      * bench.py: measures the disk->host->HBM->kernel pipeline end-to-end
        on links whose device->host direction is degraded (tunneled dev
        chips), where stream_encode is bound by the D2H link parity must
        cross to reach disk; `stats` returns the measured-phase ledger.
      * tests: the digest equals the per-row byte sums of the parity shard
        files stream_encode writes (padding encodes to zeros), so the sink
        is provably the same computation, not a shortcut XLA could elide.
    """
    import time

    g = geometry
    assert coder.k == g.data_shards and coder.m == g.parity_shards
    dat_size = os.path.getsize(base_file_name + ".dat")
    # unpooled feed: a whole window of batches stays referenced until its
    # single dispatch, so buffers are fresh (zero-copy mmap views where
    # the stripe allows — those reference no buffer at all; the reader
    # pool prefaults their pages so the stagers' gathers never stall
    # single-threaded on disk)
    src = feed_mod.open_feed(base_file_name + ".dat", g.data_shards,
                             batch_size, pooled=False, readers=readers)
    t_all = time.perf_counter()
    try:
        acc = _windowed_digest_sink(
            src.batches(stripe_segments(dat_size, g, batch_size),
                        pad_final=True),
            coder.encode_digest_window_async, coder.stage_async,
            depth, window_bytes, stats, stagers=stagers)
    finally:
        src.close()
    if acc is None:
        out = np.zeros(g.parity_shards, dtype=np.uint32)
    elif not materialize:
        # deferred mode for multi-volume batches: return the on-device
        # acc so windows pipeline across volumes (each device->host sync
        # costs tunnel round-trip latency; a batch pays it once at the
        # end via coder.materialize on each returned acc)
        if stats is not None:
            stats["total_s"] = round(time.perf_counter() - t_all, 3)
            stats["volume_bytes"] = dat_size
        return acc
    else:
        t0 = time.perf_counter()
        out = np.asarray(coder.materialize(acc), dtype=np.uint32)
        if stats is not None:
            stats["wait_s"] = round(time.perf_counter() - t0, 3)
    if stats is not None:
        stats["total_s"] = round(time.perf_counter() - t_all, 3)
        stats["volume_bytes"] = dat_size
    return out


def stream_rebuild_device_sink(base_file_name: str, coder: ErasureCoder,
                               victims: Sequence[int],
                               geometry: Geometry = DEFAULT,
                               batch_size: int = DEFAULT_BATCH_SIZE,
                               depth: int = DEFAULT_DEPTH,
                               window_bytes: int = DEFAULT_WINDOW_BYTES,
                               stats: dict | None = None,
                               materialize: bool = True,
                               stagers: Optional[int] = None,
                               readers: Optional[int] = None) -> np.ndarray:
    """stream_rebuild with the reconstructed shards landing in an on-device
    digest sink (BASELINE config 3's link-independent measurement).

    Treats `victims` as missing, streams k survivor shard files through
    the staged-window schedule, reconstructs the victim rows on device and
    digests them to [len(victims)] uint32 wrapping byte sums — verifiable
    against shard_file_digest() of the real shard files, so the measured
    path provably performs the full reconstruction compute without pushing
    shard bytes across a degraded D2H link.
    Matches RebuildEcFiles' survivor->missing math (ec_encoder.go:233-287).
    """
    import time

    g = geometry
    victims = tuple(victims)  # digest rows follow CALLER order
    present = [i for i in range(g.total_shards)
               if i not in victims
               and os.path.exists(base_file_name + to_ext(i))]
    if len(present) < g.data_shards:
        raise ValueError(
            f"need {g.data_shards} survivors, have {len(present)}")
    survivors_ids = tuple(present[:g.data_shards])
    src = feed_mod.ShardFeed(
        [base_file_name + to_ext(i) for i in survivors_ids],
        batch_size, pooled=False, readers=readers)
    shard_size = src.shard_size
    t_all = time.perf_counter()

    def dispatch_window(staged, acc):
        return coder.rec_digest_window_async(survivors_ids, victims,
                                             staged, acc)

    try:
        acc = _windowed_digest_sink(
            src.batches(batch_size, pad_final=True), dispatch_window,
            coder.stage_async, depth, window_bytes, stats,
            stagers=stagers)
    finally:
        src.close()
    if acc is None:
        out = np.zeros(len(victims), dtype=np.uint32)
    elif not materialize:
        # deferred mode: see stream_encode_device_sink
        if stats is not None:
            stats["total_s"] = round(time.perf_counter() - t_all, 3)
            stats["shard_bytes"] = shard_size
        return acc
    else:
        t0 = time.perf_counter()
        out = np.asarray(coder.materialize(acc), dtype=np.uint32)
        if stats is not None:
            stats["wait_s"] = round(time.perf_counter() - t0, 3)
    if stats is not None:
        stats["total_s"] = round(time.perf_counter() - t_all, 3)
        stats["shard_bytes"] = shard_size
    return out


def shard_file_digest(base_file_name: str,
                      shard_ids: Sequence[int]) -> np.ndarray:
    """[len(ids)] uint32 wrapping byte-sum of each shard file — the
    host-side cross-check for the device digest sinks. Accumulates in
    uint64 and masks once at the end: explicit wrapping arithmetic, no
    overflow warnings (a full uint64 holds > 2^56 bytes of sum)."""
    out = []
    for i in shard_ids:
        total = np.uint64(0)
        with open(base_file_name + to_ext(i), "rb") as f:
            while True:
                chunk = f.read(1 << 24)
                if not chunk:
                    break
                total += np.sum(np.frombuffer(chunk, dtype=np.uint8),
                                dtype=np.uint64)
        out.append(int(total) & 0xFFFFFFFF)
    return np.asarray(out, dtype=np.uint32)


def read_stamped_digests(base_file_name: str) -> dict[int, int]:
    """shard id -> stamped uint32 byte-sum digest from the .ecm sidecar
    ({} when the marker is absent or carries no digests)."""
    import json as json_mod
    try:
        with open(base_file_name + ".ecm") as f:
            meta = json_mod.load(f)
    except (OSError, ValueError):
        return {}
    return {int(k): int(v)
            for k, v in (meta.get("shard_digests") or {}).items()}


def stamp_shard_digests(base_file_name: str,
                        geometry: Geometry = DEFAULT) -> dict[int, int]:
    """Record each local shard file's digest into the .ecm sidecar — the
    reference the EC scrubber verifies against. Merge-only: a shard id
    already stamped keeps its original value (recomputing over a shard
    that has since rotted would launder the corruption into the record),
    so the truth is established exactly once, at encode/rebuild time
    when the bytes are known-good. No-op without an existing marker: a
    digests-only .ecm would fail the layout-version check at mount."""
    import json as json_mod
    path = base_file_name + ".ecm"
    try:
        with open(path) as f:
            meta = json_mod.load(f)
    except (OSError, ValueError):
        return {}
    digests = {int(k): int(v)
               for k, v in (meta.get("shard_digests") or {}).items()}
    from ..utils import metrics as metrics_mod
    recomputed = 0
    for sid in range(geometry.total_shards):
        if sid in digests or not os.path.exists(
                base_file_name + to_ext(sid)):
            continue
        digests[sid] = int(shard_file_digest(base_file_name, [sid])[0])
        recomputed += 1
    if recomputed:
        # encode passes that stamp digests inline (stream_encode, the
        # fused warm-down) leave nothing to recompute; this counter is
        # how the bench proves "scrubber re-digest count 0"
        metrics_mod.shared("ec").count("ec_digest_host_recompute",
                                       recomputed)
    meta["shard_digests"] = {str(k): v
                             for k, v in sorted(digests.items())}
    durable.write_json_atomic(path, meta)
    return digests


def parity_file_digest(base_file_name: str,
                       geometry: Geometry = DEFAULT) -> np.ndarray:
    """[m] uint32 wrapping byte-sum of each parity shard file — the
    host-side cross-check for stream_encode_device_sink."""
    g = geometry
    return shard_file_digest(
        base_file_name, range(g.data_shards, g.total_shards))


def stream_rebuild(base_file_name: str, coder: ErasureCoder,
                   geometry: Geometry = DEFAULT,
                   batch_size: Optional[int] = None,
                   depth: Optional[int] = None) -> list[int]:
    """Regenerate missing shard files from k survivors, overlapped
    (RebuildEcFiles, ec_encoder.go:233-287 — but with multi-MB strides and
    read/compute/write overlap instead of synchronous 1MB loops).
    Returns the rebuilt shard ids. Runs on the same zero-copy feed and
    governed operating point as stream_encode.
    """
    g = geometry
    run_t0 = time.perf_counter()
    present = [i for i in range(g.total_shards)
               if os.path.exists(base_file_name + to_ext(i))]
    missing = [i for i in range(g.total_shards) if i not in present]
    if not missing:
        return []
    if len(present) < g.data_shards:
        raise ValueError(
            f"need {g.data_shards} shards to rebuild, have {len(present)}")
    survivors_ids = tuple(present[:g.data_shards])
    shard_size = os.path.getsize(base_file_name + to_ext(survivors_ids[0]))
    op, governed = _resolve_op(batch_size, depth,
                               g.data_shards * shard_size, g.data_shards,
                               coder_chips(coder))
    if governed:
        # steer BEFORE rec_apply_async binds the reconstruction program
        # to a formulation
        op = _steer_formulation(coder, op)
    fn = coder.rec_apply_async(survivors_ids, tuple(missing))
    src = feed_mod.ShardFeed(
        [base_file_name + to_ext(i) for i in survivors_ids],
        op.batch_size, pool_buffers=op.depth + 2, readers=op.readers)
    fan = _FanOut([base_file_name + to_ext(i) for i in missing],
                  op.write_depth)
    tctx = observe.ensure_ctx("ec")

    def consume(survivors: np.ndarray, handle) -> None:
        from ..observe.profiler import trace_annotation
        with observe.stage("ec.kernel", tctx), \
                trace_annotation("ec_pipeline_kernel_wait"):
            rebuilt = coder.materialize(handle)
        # the kernel has consumed the survivor batch: recycle it now —
        # the rebuilt rows fanned out below are device-materialized
        # arrays, not views of the staging buffer
        src.recycle(survivors)
        with observe.stage("ec.write", tctx):
            fan.put_rows(iter(rebuilt))

    try:
        _run_pipeline(
            _traced_batches(src.batches(op.batch_size), tctx), fn,
            consume, op.depth, trace_ctx=tctx, recycle=src.recycle)
    finally:
        fan.close()
        src.close()
    if fan.errors:
        raise fan.errors[0]
    if governed:
        governor.get().finish_run(tctx.trace_id, op,
                                  g.data_shards * shard_size,
                                  g.data_shards)
    from ..observe import wideevents
    wideevents.emit_stages(
        "ec", f"ec.rebuild {os.path.basename(base_file_name)}",
        tctx.trace_id, int((time.perf_counter() - run_t0) * 1e6),
        observe.stage_totals(tctx.trace_id, prefix="ec."))
    return missing
