"""Fused maintenance pipeline: compaction + gzip + RS encode (BASELINE
config 5).

One call takes a live volume with deleted space straight to erasure-coded
shards: live needles are copied out (compaction — the Compact2 snapshot
walk, weed/storage/volume_vacuum.go:66-89), payloads gzipped where it pays
(weed/util/compression.go), and the compacted `.dat` stream feeds the
overlapped TPU encode pipeline (ec/pipeline.py) — so the chip starts
encoding while the host is still compacting the tail.

The output is a fresh volume (`<dst>.dat/.idx`) plus its `.ec00-13`/`.ecx`
shard set; the source volume is untouched.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from ..storage import idx as idx_mod
from ..storage import types as t
from ..storage.needle import FLAG_IS_COMPRESSED
from ..storage.superblock import SuperBlock
from ..utils import compression
from . import striping
from .coder import ErasureCoder
from .geometry import DEFAULT, Geometry
from .pipeline import DEFAULT_BATCH_SIZE, stream_encode


def fused_vacuum_gzip_encode(volume, dst_base: str, coder: ErasureCoder,
                             geometry: Geometry = DEFAULT,
                             batch_size: int = DEFAULT_BATCH_SIZE,
                             gzip_level: int = 1) -> dict:
    """Compact `volume` into <dst_base>.dat (gzipping payloads), then
    erasure-code the result through the overlapped pipeline. The two-tier
    stripe layout needs the final compacted size before shard rows can be
    assigned, so the phases chain (the encode itself overlaps disk/H2D/
    kernel/write-back internally).

    Returns {live_needles, src_bytes, compacted_bytes, shard_files}.
    """
    src_size = volume.data_file_size()
    with volume._lock:
        snapshot = [nv for nv in volume.nm.values()
                    if t.size_is_valid(nv.size)]
        sb = SuperBlock(
            version=volume.super_block.version,
            replica_placement=volume.super_block.replica_placement,
            ttl=volume.super_block.ttl,
            compaction_revision=volume.super_block.compaction_revision + 1,
            extra=volume.super_block.extra)
    snapshot.sort(key=lambda nv: nv.offset)

    with open(dst_base + ".dat", "wb", buffering=1 << 20) as dat, \
            open(dst_base + ".idx", "wb") as idx:
        dat.write(sb.to_bytes())
        offset = len(sb.to_bytes())
        for nv in snapshot:
            n = volume.read_needle_at(t.stored_to_offset(nv.offset),
                                      nv.size)
            if n.data and not n.is_compressed:
                # sniff a 4KB prefix first: gzipping already-incompressible
                # payloads (media, ciphertext) is the single biggest waste
                # in a mixed-content vacuum — half the volume in the bench
                head = n.data[:4096]
                trial = compression.compress(head, level=gzip_level)
                if len(trial) * 10 < len(head) * 9:
                    comp = compression.compress(n.data, level=gzip_level)
                    if len(comp) * 10 < len(n.data) * 9:
                        n.data = comp
                        n.set_flag(FLAG_IS_COMPRESSED)
            record = n.to_bytes(volume.version)
            if offset % t.NEEDLE_PADDING_SIZE:
                pad = (-offset) % t.NEEDLE_PADDING_SIZE
                dat.write(bytes(pad))
                offset += pad
            dat.write(record)
            idx.write(idx_mod.pack_entry(
                nv.key, t.offset_to_stored(offset, volume.offset_size),
                n.size, offset_size=volume.offset_size))
            offset += len(record)

    stream_encode(dst_base, coder, geometry, batch_size=batch_size)
    striping.write_sorted_ecx_from_idx(
        dst_base, offset_size=volume.offset_size)
    return {
        "live_needles": len(snapshot),
        "src_bytes": src_size,
        "compacted_bytes": os.path.getsize(dst_base + ".dat"),
        "shard_files": geometry.total_shards,
    }
