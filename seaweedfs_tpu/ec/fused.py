"""One-pass warm-down: fused compaction + gzip + RS encode + digest.

One call takes a live volume with deleted space straight to erasure-coded
shards: live needles are copied out (compaction — the Compact2 snapshot
walk, weed/storage/volume_vacuum.go:66-89), payloads gzipped where it pays
(weed/util/compression.go), the compacted `.dat` stream feeds the
overlapped encode pipeline (ec/pipeline.py), and the scrubber's reference
digests fall out of the same pass into `.ecm`.

Unlike the round-5 sketch this module replaced, the phases genuinely
overlap — the chip encodes the head of the compacted volume while the
host is still compacting its tail:

- live-needle extents become per-chunk jobs on the PR 9 reader-pool
  machinery (ec/feed.py): each job preads its (coalesced) source
  extents, CRC-verifies, and gzip-splices records on a pool thread,
  while an ordered consumer appends them to `<dst>.dat`/`.idx` in
  snapshot order. The pool width is the governor's `gzip_workers`
  operating-point axis (`WEED_EC_GZIP_WORKERS`); preads, crc32c and
  deflate all release the GIL, so workers scale with real cores.
- records move as RAW BYTES: a needle that declines gzip is copied
  verbatim (after its CRC check — Compact2's discipline), and a needle
  that adopts it has the compressed payload SPLICED into the stored
  record (header size + data_size + flags + checksum rewritten, the
  optionals tail and v3 timestamp preserved byte-for-byte). No needle
  object is built, so compaction costs ~the deflate, not the codec.
- the two-tier stripe layout streams: `_gated_segments` reproduces
  striping.stripe_segments over a file still being written. The
  live-needle size sum from the in-memory needle map (an upper bound —
  gzip only shrinks records) sizes the feed up front, a flushed-bytes
  watermark proves each large-row decision before the final size is
  known, and every segment waits only until its own bytes are flushed —
  so encode starts after the first chunk lands, not after compaction.
- shard-row digests accumulate inside the encode pass
  (pipeline._stream_encode_core) and land in the `.ecm` marker: the
  scrubber's first verification rides the fused pass, no fourth host
  re-digest (pipeline.stamp_shard_digests is merge-only and finds
  nothing left to compute).

Durability: shard files are fsynced by their writers, then `.dat`/`.idx`
are fsynced and `.ecx` written+fsynced, and only then is the `.ecm`
marker committed (utils/durable atomic write). A crash anywhere mid-pass
leaves the source volume intact plus an uncommitted partial destination
(no `.ecm`), which a re-run simply overwrites; any mid-pass exception
fail-closes by deleting every partial destination file. Fault points:
``ec.fused.read`` (a drop FAILS the chunk read), ``ec.fused.gzip``
(a drop fails the transform), ``ec.fused.commit`` (a drop aborts just
before the marker — the crash-window the crashsim workload walks).

v1 volumes are compacted verbatim without gzip: a v1 record has no
flags byte, so the old sketch's "compress and set the flag" silently
stored ciphertext-looking bytes a reader would return uncompressed.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Iterator, Optional

import numpy as np

from .. import faults, observe
from ..storage import idx as idx_mod
from ..storage import types as t
from ..storage.needle import (FLAG_IS_COMPRESSED, CrcError, crc32c_update,
                              crc_value)
from ..storage.superblock import SuperBlock
from ..utils import compression
from . import feed as feed_mod
from . import governor, striping
from .coder import ErasureCoder
from .geometry import DEFAULT, Geometry, to_ext
from .pipeline import _resolve_op, _stream_encode_core, coder_chips

# stored extent per compaction chunk job: big enough that pread/deflate
# dominate the per-job overhead, small enough that the ordered window
# (gzip_workers + 2 chunks in flight) stays tens of MB
_CHUNK_BYTES = 4 * 1024 * 1024
_CHUNK_NEEDLES = 1024


class _Watermark:
    """Flushed-byte watermark of the growing compacted .dat — the
    handshake between the compaction consumer (advances it after each
    flushed chunk) and the gated segment generator (waits on it)."""

    def __init__(self) -> None:
        self._cv = threading.Condition()
        self.flushed = 0
        self.total: Optional[int] = None
        self.error: Optional[BaseException] = None

    def advance(self, flushed: int) -> None:
        with self._cv:
            self.flushed = flushed
            self._cv.notify_all()

    def finish(self, total: int) -> None:
        with self._cv:
            self.total = total
            self.flushed = total
            self._cv.notify_all()

    def fail(self, exc: BaseException) -> None:
        with self._cv:
            self.error = exc
            self._cv.notify_all()

    def _check(self) -> None:
        if self.error is not None:
            raise RuntimeError("fused compaction failed") from self.error

    def wait_decidable(self, processed: int,
                       threshold: int) -> Optional[int]:
        """Block until the next stripe row's tier is decidable. Returns
        the exact total once compaction finished; None means the
        watermark already proves the remainder exceeds `threshold` (the
        row is LARGE — sound because flushed is a lower bound on the
        final size)."""
        with self._cv:
            while True:
                self._check()
                if self.total is not None:
                    return self.total
                if self.flushed - processed > threshold:
                    return None
                self._cv.wait(0.05)

    def wait_cover(self, end: int) -> None:
        """Block until the compacted file covers [0, end) — or is final
        (reads past the real EOF are layout padding and zero-fill)."""
        with self._cv:
            while True:
                self._check()
                if self.total is not None or self.flushed >= end:
                    return
                self._cv.wait(0.05)


def _gated_segments(g: Geometry, batch_size: int,
                    wm: _Watermark) -> Iterator[tuple[list[int], int]]:
    """striping.stripe_segments over a file still being written.

    Provably the same sequence as stripe_segments(final_size, g,
    batch_size): a large row is emitted only when total - processed >
    large_row - small_row, which wait_decidable either proves early from
    the watermark (flushed <= total) or answers exactly from the final
    total; the small regime and termination always use the exact total.
    Each segment additionally waits for its own byte coverage, so the
    feed never preads bytes compaction hasn't flushed."""
    threshold = g.large_row_size - g.small_row_size
    processed = 0
    while True:
        total = wm.wait_decidable(processed, threshold)
        if total is None or total - processed > threshold:
            block, row = g.large_block_size, g.large_row_size
        elif total - processed > 0:
            block, row = g.small_block_size, g.small_row_size
        else:
            return
        b = striping.clamp_batch(batch_size, block)
        for batch_start in range(0, block, b):
            offsets = [processed + block * i + batch_start
                       for i in range(g.data_shards)]
            wm.wait_cover(offsets[-1] + b)
            yield (offsets, b)
        processed += row


def _transform_record(raw, size: int, version: int,
                      gzip_level: int) -> tuple:
    """CRC-verify one stored record and splice in a gzipped payload when
    it pays. Returns (record_bytes, body_size, gzip_seconds, adopted).

    The passthrough record is the raw stored extent (zero codec work);
    the spliced record is byte-identical to what Needle.to_bytes would
    produce for the compressed needle — header cookie/id preserved, size
    and data_size rewritten, FLAG_IS_COMPRESSED set, the optionals tail
    (name/mime/lm/ttl/pairs) and v3 append_at_ns copied verbatim, CRC
    recomputed over the compressed payload, zero padding to the 8-byte
    grain."""
    if version == t.VERSION1:
        # no flags byte in a v1 body: compression is not representable,
        # copy verbatim (still CRC-verified)
        data = raw[t.NEEDLE_HEADER_SIZE:t.NEEDLE_HEADER_SIZE + size]
        if size > 0 and t.get_u32(raw, t.NEEDLE_HEADER_SIZE + size) != \
                crc_value(crc32c_update(0, data)):
            raise CrcError(f"needle {t.get_u64(raw, 4):x} CRC mismatch "
                           "during fused compaction")
        return raw, size, 0.0, False
    if size <= 0:
        return raw, size, 0.0, False
    data_size = t.get_u32(raw, 16)
    data = bytes(raw[20:20 + data_size])
    stored_crc = t.get_u32(raw, 16 + size)
    if stored_crc != crc_value(crc32c_update(0, data)):
        raise CrcError(f"needle {t.get_u64(raw, 4):x} CRC mismatch "
                       "during fused compaction")
    flags = raw[20 + data_size]
    if (flags & FLAG_IS_COMPRESSED) or not data:
        return raw, size, 0.0, False
    gz0 = time.perf_counter()
    # sniff a 4KB prefix first: gzipping already-incompressible payloads
    # (media, ciphertext) is the single biggest waste in a mixed-content
    # vacuum — half the volume in the bench
    head = data[:4096]
    trial = compression.compress(head, level=gzip_level)
    if len(trial) * 10 >= len(head) * 9:
        return raw, size, time.perf_counter() - gz0, False
    comp = compression.compress(data, level=gzip_level)
    if len(comp) * 10 >= len(data) * 9:
        return raw, size, time.perf_counter() - gz0, False
    tail = bytes(raw[21 + data_size:16 + size])
    new_size = 4 + len(comp) + 1 + len(tail)
    parts = [bytes(raw[0:12]), t.put_u32(t.size_to_u32(new_size)),
             t.put_u32(len(comp)), comp,
             bytes([(flags | FLAG_IS_COMPRESSED) & 0xFF]), tail,
             t.put_u32(crc_value(crc32c_update(0, comp)))]
    if version == t.VERSION3:
        parts.append(bytes(raw[20 + size:28 + size]))  # append_at_ns
    parts.append(bytes(t.padding_length(new_size, version)))
    return b"".join(parts), new_size, time.perf_counter() - gz0, True


def _transform_chunk(read_at, entries: list, version: int, gzip_level: int,
                     tctx) -> tuple[list, int, float]:
    """One reader-pool job: pread a chunk's live extents (adjacent
    extents coalesced into single positioned reads), verify + gzip-splice
    each record. Returns ([(key, body_size, record)], gzipped, gzip_s).
    Emits one ec.compact + one ec.gzip span (explicit captured ctx —
    this runs on a pool thread)."""
    start_us = int(time.time() * 1e6)
    t0 = time.perf_counter()
    if faults.fire("ec.fused.read"):
        # a drop must FAIL the read: silently skipping live extents
        # would compact acked needles out of existence
        raise IOError("injected drop at ec.fused.read")
    raws: list = []
    i, n_e = 0, len(entries)
    while i < n_e:
        lo = entries[i][1]
        end, j = lo, i
        while j < n_e and entries[j][1] == end:
            end += entries[j][3]
            j += 1
        blob = read_at(end - lo, lo)
        if len(blob) != end - lo:
            raise IOError(f"fused compaction short read at {lo}: "
                          f"{len(blob)} != {end - lo}")
        mv = memoryview(blob)
        pos = 0
        for kk in range(i, j):
            ln = entries[kk][3]
            raws.append(mv[pos:pos + ln])
            pos += ln
        i = j
    if faults.fire("ec.fused.gzip"):
        raise IOError("injected drop at ec.fused.gzip")
    out: list = []
    gzipped = 0
    gzip_s = 0.0
    for (key, _off, size, _ln), raw in zip(entries, raws):
        rec, body_size, gz, adopted = _transform_record(
            raw, size, version, gzip_level)
        gzip_s += gz
        gzipped += 1 if adopted else 0
        out.append((key, body_size, rec))
    dur_us = int((time.perf_counter() - t0) * 1e6)
    gzip_us = int(gzip_s * 1e6)
    observe.record_span("ec.gzip", tctx, start_us, gzip_us,
                        tags={"needles": len(entries)})
    observe.record_span("ec.compact", tctx, start_us,
                        max(dur_us - gzip_us, 0),
                        tags={"needles": len(entries)})
    return out, gzipped, gzip_s


def _cleanup_dst(dst_base: str, g: Geometry) -> None:
    """Fail-closed: remove every partial destination file so an aborted
    pass leaves ONLY the intact source volume (never a half shard set a
    later mount could mistake for data)."""
    paths = [dst_base + ext for ext in (".dat", ".idx", ".ecx", ".ecm")]
    paths += [dst_base + to_ext(i) for i in range(g.total_shards)]
    for p in paths:
        try:
            os.remove(p)
        except OSError:
            pass


def fused_vacuum_gzip_encode(volume, dst_base: str, coder: ErasureCoder,
                             geometry: Geometry = DEFAULT,
                             batch_size: Optional[int] = None,
                             gzip_level: int = 1,
                             depth: Optional[int] = None) -> dict:
    """Compact `volume` into <dst_base>.dat (gzipping payloads where it
    pays) while erasure-coding the growing result through the overlapped
    pipeline — one pass, byte-identical output to sequential
    vacuum -> gzip -> encode. The source volume is untouched.

    batch_size/depth default to the governor's operating point (which
    also sets the compaction pool width, `gzip_workers`); passing them
    explicitly pins the schedule. Returns {live_needles, src_bytes,
    compacted_bytes, shard_files, gzipped_needles, shard_digests,
    op bookkeeping, wall/commit seconds}.
    """
    g = geometry
    assert coder.k == g.data_shards and coder.m == g.parity_shards
    run_t0 = time.perf_counter()
    src_size = volume.data_file_size()
    version = volume.version
    offset_size = volume.offset_size
    with volume._lock:
        snapshot = [(nv.key, t.stored_to_offset(nv.offset), nv.size)
                    for nv in volume.nm.values()
                    if t.size_is_valid(nv.size)]
        sb = SuperBlock(
            version=volume.super_block.version,
            replica_placement=volume.super_block.replica_placement,
            ttl=volume.super_block.ttl,
            compaction_revision=volume.super_block.compaction_revision + 1,
            extra=volume.super_block.extra)
    snapshot.sort(key=lambda e: e[1])
    entries = [(key, off, size, t.get_actual_size(size, version))
               for key, off, size in snapshot]
    sb_bytes = sb.to_bytes()
    head_len = len(sb_bytes) + ((-len(sb_bytes)) % t.NEEDLE_PADDING_SIZE)
    # upper bound on the compacted size, known BEFORE any byte moves:
    # gzip only ever shrinks a record and the 8-byte grain is preserved,
    # so the layout/feed can be sized from the live-needle sum up front
    upper = head_len + sum(e[3] for e in entries)
    op, governed = _resolve_op(batch_size, depth, upper, g.data_shards,
                               coder_chips(coder))
    tctx = observe.ensure_ctx("ec")
    wm = _Watermark()
    read_at = volume._dat.read_at
    counters = {"gzipped": 0, "gzip_s": 0.0}

    chunks: list[list] = []
    cur: list = []
    cur_bytes = 0
    for e in entries:
        cur.append(e)
        cur_bytes += e[3]
        if cur_bytes >= _CHUNK_BYTES or len(cur) >= _CHUNK_NEEDLES:
            chunks.append(cur)
            cur, cur_bytes = [], 0
    if cur:
        chunks.append(cur)

    pool = feed_mod._ReaderPool(max(1, op.gzip_workers))
    dat_f, idx_f = striping._open_all(
        [dst_base + ".dat", dst_base + ".idx"], "wb")
    shard_paths = [dst_base + to_ext(i) for i in range(g.total_shards)]
    digests = np.zeros(g.total_shards, dtype=np.uint64)

    def compactor() -> None:
        try:
            dat_f.write(sb_bytes)
            offset = len(sb_bytes)
            jobs = (
                (lambda chunk=chunk: _transform_chunk(
                    read_at, chunk, version, gzip_level, tctx))
                for chunk in chunks)
            for results, gzipped, gzip_s in feed_mod.ordered_pool_map(
                    pool, jobs, op.gzip_workers + 2):
                counters["gzipped"] += gzipped
                counters["gzip_s"] += gzip_s
                buf: list = []
                for key, body_size, rec in results:
                    pad = (-offset) % t.NEEDLE_PADDING_SIZE
                    if pad:
                        buf.append(bytes(pad))
                        offset += pad
                    buf.append(rec)
                    idx_f.write(idx_mod.pack_entry(
                        key, t.offset_to_stored(offset, offset_size),
                        body_size, offset_size=offset_size))
                    offset += len(rec)
                dat_f.writelines(buf)
                # flush BEFORE advancing: the encode feed preads this
                # range through its own fd the moment the watermark
                # covers it, so the bytes must be in the page cache
                dat_f.flush()
                wm.advance(offset)
            dat_f.flush()
            wm.finish(offset)
        except BaseException as e:
            wm.fail(e)

    try:
        try:
            feed = feed_mod.PreadvFeed(
                dst_base + ".dat", g.data_shards, op.batch_size,
                pool_buffers=op.depth + 2, readers=op.readers,
                odirect=False)
            # the file is growing under the feed: size gates nothing (the
            # gated segments do), it only bounds the zero-fill shortcuts
            feed.size = upper
            compact_th = threading.Thread(target=compactor, daemon=True,
                                          name="ec-fused-compact")
            compact_th.start()
            try:
                _stream_encode_core(
                    feed.batches(_gated_segments(g, op.batch_size, wm)),
                    coder, shard_paths, op, tctx,
                    recycle=feed.recycle, digests=digests)
            finally:
                compact_th.join()
                feed.close()
            if wm.error is not None:
                raise RuntimeError(
                    "fused compaction failed") from wm.error
            total = wm.total or 0
            # shards are fsynced (fan writers); now the volume pair
            dat_f.flush()
            os.fsync(dat_f.fileno())
            idx_f.flush()
            os.fsync(idx_f.fileno())
        finally:
            pool.close()
            dat_f.close()
            idx_f.close()
        commit_t0 = time.perf_counter()
        striping.write_sorted_ecx_from_idx(dst_base,
                                           offset_size=offset_size)
        fd = os.open(dst_base + ".ecx", os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
        if faults.fire("ec.fused.commit"):
            raise IOError("injected abort at ec.fused.commit")
        # the durable commit point: everything above is on the platter
        # before the marker makes the shard set reachable
        shard_digests = {i: int(digests[i]) & 0xFFFFFFFF
                         for i in range(g.total_shards)}
        striping.write_layout_marker(dst_base, total, g,
                                     shard_digests=shard_digests)
        commit_s = time.perf_counter() - commit_t0
    except BaseException:
        _cleanup_dst(dst_base, g)
        raise
    if governed:
        governor.get().finish_run(tctx.trace_id, op, upper, g.data_shards)
    from ..observe import wideevents
    wall_s = time.perf_counter() - run_t0
    wideevents.emit_stages(
        "ec", f"ec.fused {os.path.basename(dst_base)}", tctx.trace_id,
        int(wall_s * 1e6), observe.stage_totals(tctx.trace_id,
                                                prefix="ec."))
    return {
        "live_needles": len(entries),
        "src_bytes": src_size,
        "compacted_bytes": total,
        "shard_files": g.total_shards,
        "gzipped_needles": counters["gzipped"],
        "gzip_s": round(counters["gzip_s"], 3),
        "shard_digests": shard_digests,
        "batch_size": op.batch_size,
        "readers": op.readers,
        "gzip_workers": op.gzip_workers,
        "wall_s": round(wall_s, 3),
        "commit_s": round(commit_s, 3),
    }


def fused_vacuum_gzip_encode_many(volumes, dst_bases, coder: ErasureCoder,
                                  geometry: Geometry = DEFAULT,
                                  gzip_level: int = 1) -> list[dict]:
    """Warm-down a window of volumes through ONE governed operating
    point — the _EncodeBatcher regime: every volume feeds the same
    [k, B] batch shape so the coder's jit cache serves one executable
    for the whole window; the governor retunes once from the window's
    aggregate compact/gzip/read/kernel/write spans."""
    vols = list(volumes)
    bases = list(dst_bases)
    if not vols:
        return []
    total = sum(v.data_file_size() for v in vols)
    op, governed = _resolve_op(None, None, total, geometry.data_shards,
                               coder_chips(coder))
    tctx = observe.ensure_ctx("ec")
    out = []
    for v, base in zip(vols, bases):
        with observe.stage("ec.volume", tctx, tags={"base": base}):
            out.append(observe.run_with(
                tctx, fused_vacuum_gzip_encode, v, base, coder, geometry,
                batch_size=op.batch_size, gzip_level=gzip_level,
                depth=op.depth))
    if governed:
        governor.get().finish_run(tctx.trace_id, op, total,
                                  geometry.data_shards)
    return out
