from .coder import ErasureCoder, JaxCoder, NumpyCoder, get_coder, register_coder
from .ec_volume import EcShard, EcVolume, rebuild_ecx_file
from .geometry import (DEFAULT, MAX_TOTAL_SHARDS, Geometry, GeometryPolicy,
                       parse_geometry, to_ext)
from .locate import Interval, locate_data
from .striping import (find_dat_file_size, iterate_ecj_file, iterate_ecx_file,
                       rebuild_ec_files, write_dat_file, write_ec_files,
                       write_idx_file_from_ec_index, write_sorted_ecx_from_idx)

__all__ = [
    "ErasureCoder", "JaxCoder", "NumpyCoder", "get_coder", "register_coder",
    "EcShard", "EcVolume", "rebuild_ecx_file",
    "DEFAULT", "MAX_TOTAL_SHARDS", "Geometry", "GeometryPolicy",
    "parse_geometry", "to_ext",
    "Interval", "locate_data",
    "find_dat_file_size", "iterate_ecj_file", "iterate_ecx_file",
    "rebuild_ec_files", "write_dat_file", "write_ec_files",
    "write_idx_file_from_ec_index", "write_sorted_ecx_from_idx",
]
