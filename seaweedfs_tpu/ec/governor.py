"""Adaptive operating-point governor for the EC host feed.

The streaming pipeline used to run a fixed 8 MB batch at queue depth 4
regardless of what actually binds it — but the binding stage is a host
property (page-cache memcpy on a 1-core container, disk on spinners, the
device link on tunneled chips), and the right batch/depth follows from
the measured stage times, not from a constant. This governor closes the
loop:

- every ``stream_encode``/``stream_rebuild`` run already emits per-batch
  ``ec.read`` / ``ec.dispatch`` / ``ec.kernel`` / ``ec.write`` spans into
  the observe ring; ``finish_run`` aggregates them (observe.stage_totals)
  into a per-stage time model,
- the model retunes the operating point within hard bounds: the batch
  grows while per-batch read time is overhead-dominated, the queues
  deepen when the chip or the writers are the slow stage, and everything
  is clamped so pooled staging memory stays under a budget,
- the chosen operating point and the measured stage model are exported
  as gauges through the shared "ec" metrics registry, which every
  server's /metrics includes — so the operating point is observable, not
  folklore.

Tuning is applied BETWEEN runs (the operating point persists across
volumes in the process — the 1000-volume regime), never mid-stream:
changing the batch width mid-run would force kernel recompiles and
change nothing about the bytes written.

Env knobs (all optional):
  WEED_EC_GOVERNOR=0            disable adaptation (fixed defaults/env)
  WEED_EC_BATCH_BYTES           starting batch size   (default 8 MiB)
  WEED_EC_DEPTH                 starting queue depth  (default 4)
  WEED_EC_BATCH_MIN/MAX         batch bounds          (1 MiB / 64 MiB)
  WEED_EC_DEPTH_MIN/MAX         depth bounds          (2 / 8)
  WEED_EC_HOST_BUDGET_MB        pooled staging budget (512 MiB)
  WEED_EC_READERS               starting reader-pool width (cores, <=4)
  WEED_EC_READERS_MIN/MAX       reader bounds         (1 / min(8, cores))
  WEED_EC_GZIP_WORKERS          fused compaction/gzip pool (cores, <=4)
  WEED_EC_GZIP_MIN/MAX          gzip-worker bounds    (1 / min(8, cores))
  WEED_EC_MMAP=0                force the preadv feed (see ec/feed.py)
  WEED_EC_ODIRECT=1             page-cache-bypassing reads (ec/feed.py)
  WEED_EC_FORMULATION           pin the GF kernel formulation
                                (lut|bitplane|xorsched — ops/rs_jax.py);
                                unset, the governor explores bitplane vs
                                xorsched per geometry and exploits the
                                faster measured kernel rate
"""

from __future__ import annotations

import os
import threading
from typing import NamedTuple

from .. import observe
from ..utils import metrics as metrics_mod
from . import feed as feed_mod

MB = 1024 * 1024


def _env_int(name: str, default: int) -> int:
    try:
        v = int(os.environ.get(name, ""))
        return v if v > 0 else default
    except ValueError:
        return default


class OperatingPoint(NamedTuple):
    batch_size: int
    depth: int        # read + materialize queue depth
    write_depth: int  # per-shard-file writer queue depth
    readers: int = 1  # feed reader-pool width (ec/feed.py)
    chips: int = 1    # device-mesh width (parallel/mesh_coder.py)
    gzip_workers: int = 1  # fused warm-down compaction/gzip pool (ec/fused.py)
    # GF kernel formulation (ops/rs_jax.FORMULATIONS); "" on runs whose
    # coder exposes no retune hook, so finish_run never mis-attributes
    formulation: str = "bitplane"


# per-batch read time below this is dispatch/syscall-overhead-dominated:
# widen the batch so fixed costs amortize
_READ_OVERHEAD_S = 0.02
# stage share above which a stage counts as "binding"
_BIND_FRACTION = 0.5


class FeedGovernor:
    """Process-global tuner; one instance via get()."""

    # formulation candidates the governor explores per geometry; lut is
    # reachable only by env pin (it measured slower than both everywhere
    # the kernel bench has run, so exploration cycles aren't spent on it)
    _FORM_CANDIDATES = ("bitplane", "xorsched")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.enabled = os.environ.get("WEED_EC_GOVERNOR", "1") not in (
            "0", "false", "no")
        self.batch_min = _env_int("WEED_EC_BATCH_MIN", 1 * MB)
        self.batch_max = _env_int("WEED_EC_BATCH_MAX", 64 * MB)
        self.depth_min = _env_int("WEED_EC_DEPTH_MIN", 2)
        self.depth_max = _env_int("WEED_EC_DEPTH_MAX", 8)
        self.budget = _env_int("WEED_EC_HOST_BUDGET_MB", 512) * MB
        self.readers_min = _env_int("WEED_EC_READERS_MIN", 1)
        self.readers_max = _env_int(
            "WEED_EC_READERS_MAX", max(1, min(8, os.cpu_count() or 1)))
        self.gzip_min = _env_int("WEED_EC_GZIP_MIN", 1)
        self.gzip_max = _env_int(
            "WEED_EC_GZIP_MAX", max(1, min(8, os.cpu_count() or 1)))
        self._batch = min(max(_env_int("WEED_EC_BATCH_BYTES", 8 * MB),
                              self.batch_min), self.batch_max)
        self._depth = min(max(_env_int("WEED_EC_DEPTH", 4),
                              self.depth_min), self.depth_max)
        self._write_depth = self._depth
        self._readers = min(max(feed_mod.reader_count_default(),
                                self.readers_min), self.readers_max)
        self._gzip_workers = min(
            max(feed_mod.env_thread_count("WEED_EC_GZIP_WORKERS", 64),
                self.gzip_min), self.gzip_max)
        self.metrics = metrics_mod.shared("ec")
        self.stage_gbps: dict[str, float] = {}
        # measured kernel-stage rate per (k, formulation) — the
        # formulation axis's model, fed by finish_run
        self.form_gbps: dict[tuple[int, str], float] = {}
        self._form_by_k: dict[int, str] = {}
        self.runs = 0

    # --- planning ---

    def plan(self, nbytes: int, k: int, chips: int = 1) -> OperatingPoint:
        """The operating point for the next run, memory-clamped.  The
        pooled staging footprint is (depth + 2) buffers of k * batch
        bytes (depth queued + one assembling + one in flight). `chips`
        is the coder's mesh width (parallel/mesh_coder.py): each batch's
        column axis splits across that many devices, so the batch is
        clamped no smaller than one reasonable slice per chip."""
        with self._lock:
            batch, depth = self._batch, self._depth
            # a mesh run's effective batch floor scales with the mesh:
            # below chips * batch_min each chip's slice is narrower than
            # the single-chip minimum and per-dispatch overhead dominates
            floor = min(max(self.batch_min, self.batch_min * max(chips, 1)),
                        self.batch_max)
            batch = max(batch, floor)
            while (depth + 2) * k * batch > self.budget:
                if batch > floor:
                    batch = max(batch // 2, floor)
                elif batch > self.batch_min:
                    batch = max(batch // 2, self.batch_min)
                elif depth > self.depth_min:
                    depth -= 1
                else:
                    break
            op = OperatingPoint(batch, depth, self._write_depth,
                                self._readers, max(chips, 1),
                                self._gzip_workers,
                                self._plan_formulation(k))
            self._export(op)
            return op

    def _plan_formulation(self, k: int) -> str:
        """The kernel formulation for the next run at geometry k (lock
        held): an operator pin (WEED_EC_FORMULATION) always wins; else
        explore each candidate once, then exploit the argmax of the
        EMA'd measured kernel rate. Formulation switches are a
        between-runs retune like every other axis — never mid-stream."""
        from ..ops import rs_jax
        pin = rs_jax.formulation_env()
        if pin is not None:
            self._form_by_k[k] = pin
            return pin
        if not self.enabled:
            return self._form_by_k.get(k, "bitplane")
        for cand in self._FORM_CANDIDATES:
            if (k, cand) not in self.form_gbps:
                self._form_by_k[k] = cand
                return cand
        best = max(self._FORM_CANDIDATES,
                   key=lambda f: self.form_gbps[(k, f)])
        self._form_by_k[k] = best
        return best

    # --- measurement + retune ---

    _STAGES = {"read": "ec.read", "dispatch": "ec.dispatch",
               "kernel": "ec.kernel", "write": "ec.write",
               # fused warm-down stages (ec/fused.py): compaction-filter
               # reads+splices, payload deflate, inline shard digests
               "compact": "ec.compact", "gzip": "ec.gzip",
               "digest": "ec.digest"}

    def finish_run(self, trace_id: str, op: OperatingPoint,
                   nbytes: int, k: int) -> None:
        """Fold one run's spans into the model and retune for the next.

        The observe ring is bounded, so a long run's earliest spans may
        have been evicted; rates therefore use the bytes COVERED by the
        spans actually counted (count * batch bytes), never the full
        volume size — a truncated sample stays a correct sample."""
        totals = observe.stage_totals(trace_id, prefix="ec.")
        stages: dict[str, tuple[int, float]] = {}
        for stage, span_name in self._STAGES.items():
            count, total_us = totals.get(span_name, (0, 0))
            stages[stage] = (count, total_us / 1e6)
        batch_bytes = k * op.batch_size
        with self._lock:
            self.runs += 1
            kernel_gbps = None
            for stage, (count, secs) in stages.items():
                covered = min(count * batch_bytes, nbytes)
                if secs > 1e-6 and covered:
                    gbps = covered / secs / 1e9
                    if stage == "kernel":
                        kernel_gbps = gbps
                    prev = self.stage_gbps.get(stage)
                    self.stage_gbps[stage] = (
                        gbps if prev is None else 0.5 * prev + 0.5 * gbps)
                self.metrics.gauge("feed_stage_seconds", round(secs, 6),
                                   labels={"stage": stage})
                g = self.stage_gbps.get(stage)
                if g is not None:
                    self.metrics.gauge("feed_stage_gbps", round(g, 3),
                                       labels={"stage": stage})
            if kernel_gbps is not None and op.formulation:
                fkey = (k, op.formulation)
                prev = self.form_gbps.get(fkey)
                self.form_gbps[fkey] = (
                    kernel_gbps if prev is None
                    else 0.5 * prev + 0.5 * kernel_gbps)
                self.metrics.gauge(
                    "feed_formulation_gbps",
                    round(self.form_gbps[fkey], 3),
                    labels={"k": str(k), "formulation": op.formulation})
            if self.enabled:
                self._retune(stages, op)
            self._export(OperatingPoint(
                self._batch, self._depth, self._write_depth,
                self._readers, op.chips, self._gzip_workers,
                self._form_by_k.get(k, op.formulation)))

    def _retune(self, stages: dict[str, tuple[int, float]],
                op: OperatingPoint) -> None:
        """One bounded step toward the measured bottleneck (lock held)."""
        total = sum(s for _, s in stages.values())
        if total <= 1e-6:
            return
        slowest = max(stages, key=lambda st: stages[st][1])
        count, secs = stages[slowest]
        share = secs / total
        if slowest == "read":
            per_batch = secs / max(count, 1)
            if per_batch < _READ_OVERHEAD_S and op.batch_size < self.batch_max:
                # reads finish faster than their fixed per-batch costs:
                # wider batches amortize syscalls/dispatches
                self._batch = min(op.batch_size * 2, self.batch_max)
            elif share > _BIND_FRACTION and op.readers < self.readers_max:
                # genuinely read-bound: widen the reader pool FIRST —
                # parallel preads/page-faults add disk bandwidth, while
                # deeper prefetch only smooths bursts
                self._readers = min(max(op.readers * 2, 2),
                                    self.readers_max)
            elif share > _BIND_FRACTION and op.depth < self.depth_max:
                # reader pool maxed: deeper prefetch smooths bursts
                self._depth = min(op.depth + 1, self.depth_max)
        elif slowest in ("kernel", "dispatch"):
            if (share > _BIND_FRACTION and op.chips > 1
                    and op.batch_size < self.batch_max):
                # mesh runs: each chip sees batch/chips columns, so the
                # batch must scale WITH the mesh before queues deepen —
                # a wider batch restores full per-chip slices (amortizing
                # per-dispatch overhead across the fabric), while deeper
                # queues only buffer more undersized dispatches
                self._batch = min(op.batch_size * 2, self.batch_max)
            elif share > _BIND_FRACTION and op.depth < self.depth_max:
                # the chip is the slow stage: keep more host batches
                # queued so it never waits on the feed
                self._depth = min(op.depth + 1, self.depth_max)
        elif slowest in ("gzip", "compact"):
            if share > _BIND_FRACTION and op.gzip_workers < self.gzip_max:
                # the fused pass is host-compaction/deflate-bound: widen
                # the chunk-job pool — deflate and preads both release
                # the GIL, so extra workers add real cores when the box
                # has them (a 1-core container stays at 1)
                self._gzip_workers = min(max(op.gzip_workers * 2, 2),
                                         self.gzip_max)
        elif slowest == "write":
            if share > _BIND_FRACTION:
                # deeper writer queues absorb disk jitter without
                # stalling materialize. Capped at the staging pool size
                # (depth + 2): queued rows reference pooled batches, so a
                # writer queue deeper than the pool can never fill — the
                # extra depth would buy nothing and only widen error
                # windows
                self._write_depth = min(max(op.write_depth * 2, 2),
                                        self._depth + 2)

    def _export(self, op: OperatingPoint) -> None:
        if op.formulation:
            for f in self._FORM_CANDIDATES:
                self.metrics.gauge(
                    "feed_formulation_active",
                    1.0 if f == op.formulation else 0.0,
                    labels={"formulation": f})
        self.metrics.gauge("feed_batch_bytes", op.batch_size)
        self.metrics.gauge("feed_queue_depth", op.depth,
                           labels={"queue": "read"})
        self.metrics.gauge("feed_queue_depth", op.depth,
                           labels={"queue": "materialize"})
        self.metrics.gauge("feed_queue_depth", op.write_depth,
                           labels={"queue": "write"})
        self.metrics.gauge("feed_reader_threads", op.readers)
        self.metrics.gauge("feed_mesh_devices", op.chips)
        self.metrics.gauge("feed_gzip_workers", op.gzip_workers)
        self.metrics.gauge("feed_governor_enabled", 1.0 if self.enabled
                           else 0.0)
        self.metrics.gauge("feed_runs", self.runs)


_GOV: FeedGovernor | None = None
_GOV_LOCK = threading.Lock()


def get() -> FeedGovernor:
    global _GOV
    with _GOV_LOCK:
        if _GOV is None:
            _GOV = FeedGovernor()
        return _GOV


def reset() -> None:
    """Drop the singleton (tests re-read env bounds)."""
    global _GOV
    with _GOV_LOCK:
        _GOV = None
