"""EC geometry: RS(k,m) plus the two-tier striping block sizes.

The reference hard-codes RS(10,4) with 1GB large / 1MB small blocks
(weed/storage/erasure_coding/ec_encoder.go:17-23); here geometry is a value
so the variable-geometry sweep (BASELINE config 4) and the shrunk-geometry
test trick (reference ec_test.go:16-19) are first-class.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Geometry:
    data_shards: int = 10
    parity_shards: int = 4
    large_block_size: int = 1024 * 1024 * 1024  # 1GB
    small_block_size: int = 1024 * 1024         # 1MB

    @property
    def total_shards(self) -> int:
        return self.data_shards + self.parity_shards

    @property
    def large_row_size(self) -> int:
        return self.large_block_size * self.data_shards

    @property
    def small_row_size(self) -> int:
        return self.small_block_size * self.data_shards

    def __post_init__(self):
        assert self.data_shards > 0 and self.parity_shards > 0
        assert self.large_block_size % self.small_block_size == 0


DEFAULT = Geometry()


def to_ext(shard_id: int) -> str:
    """Shard file extension: .ec00 ... .ec13 (ec_encoder.go ToExt)."""
    return f".ec{shard_id:02d}"
