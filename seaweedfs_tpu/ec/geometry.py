"""EC geometry: RS(k,m) plus the two-tier striping block sizes.

The reference hard-codes RS(10,4) with 1GB large / 1MB small blocks
(weed/storage/erasure_coding/ec_encoder.go:17-23); here geometry is a value
so the variable-geometry sweep (BASELINE config 4) and the shrunk-geometry
test trick (reference ec_test.go:16-19) are first-class.

Round 10 adds the per-collection geometry POLICY: ``WEED_EC_GEOMETRY``
maps collections to RS(k,m), e.g.::

    WEED_EC_GEOMETRY="default=10+4,archive=20+4,media=12+4"

Wider geometries pay: the bitplane kernel's expand/repack cost amortizes
over k, so RS(20,4) clears 60+ GB/s where RS(10,4) caps near 52 (kernel
sweep, BENCH_r05) — at a durability profile archival collections happily
take (any 4 of 24 lost). The policy is validated by the master at
startup (a bad spec must kill the process, not mis-stripe a volume) and
plumbed assign -> encode plan -> the per-volume ``.ecm`` sidecar ->
rebuild, so a REBUILD never consults the policy at all: the geometry a
volume was encoded under travels with its shards.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

# ShardBits is a uint32 holdings bitmask (ec/shard_bits.py) and the
# repair planner counts live shards through it: k+m must fit 32 bits
MAX_TOTAL_SHARDS = 32


@dataclass(frozen=True)
class Geometry:
    data_shards: int = 10
    parity_shards: int = 4
    large_block_size: int = 1024 * 1024 * 1024  # 1GB
    small_block_size: int = 1024 * 1024         # 1MB

    @property
    def total_shards(self) -> int:
        return self.data_shards + self.parity_shards

    @property
    def large_row_size(self) -> int:
        return self.large_block_size * self.data_shards

    @property
    def small_row_size(self) -> int:
        return self.small_block_size * self.data_shards

    def __post_init__(self):
        assert self.data_shards > 0 and self.parity_shards > 0
        assert self.large_block_size % self.small_block_size == 0


DEFAULT = Geometry()


def to_ext(shard_id: int) -> str:
    """Shard file extension: .ec00 ... .ec13 (ec_encoder.go ToExt)."""
    return f".ec{shard_id:02d}"


def parse_geometry(spec: str) -> Geometry:
    """'k+m' (or 'k,m') -> Geometry with the default block sizes.
    Raises ValueError on anything a cluster must refuse to run with."""
    s = spec.strip().replace(",", "+")
    parts = s.split("+")
    if len(parts) != 2:
        raise ValueError(f"bad EC geometry {spec!r} (want 'k+m')")
    try:
        k, m = int(parts[0]), int(parts[1])
    except ValueError:
        raise ValueError(f"bad EC geometry {spec!r} (want 'k+m')")
    if k < 1 or m < 1:
        raise ValueError(
            f"EC geometry {spec!r}: k and m must both be >= 1")
    if k + m > MAX_TOTAL_SHARDS:
        raise ValueError(
            f"EC geometry {spec!r}: k+m = {k + m} exceeds "
            f"{MAX_TOTAL_SHARDS} (ShardBits is a uint32 bitmask)")
    return Geometry(data_shards=k, parity_shards=m)


class GeometryPolicy:
    """Per-collection RS(k,m) mapping with a default. Immutable after
    parse; lookups never fail (unknown collections get the default)."""

    def __init__(self, per_collection: "dict[str, Geometry] | None" = None,
                 default: Geometry = DEFAULT):
        self.default = default
        self.per_collection = dict(per_collection or {})

    @classmethod
    def parse(cls, spec: str) -> "GeometryPolicy":
        """'default=10+4,archive=20+4' (a bare 'k+m' sets the default).
        Raises ValueError — callers validate at startup, loudly."""
        default = DEFAULT
        mapping: dict[str, Geometry] = {}
        for entry in (spec or "").split(","):
            entry = entry.strip()
            if not entry:
                continue
            if "=" in entry:
                name, _, geo = entry.partition("=")
                name = name.strip()
            else:
                name, geo = "default", entry
            g = parse_geometry(geo)
            if name in ("default", "*", ""):
                default = g
            elif name in mapping:
                raise ValueError(
                    f"EC geometry policy names collection {name!r} twice")
            else:
                mapping[name] = g
        return cls(mapping, default)

    # what an unset WEED_EC_GEOMETRY ships: RS(10,4) for everything,
    # except the archive collection at RS(20,4) — wide geometries are
    # where the fused warm-down's economics land (the kernel amortizes
    # expand/repack over k, parity overhead drops 40% -> 20%, and the
    # durability profile — any 4 of 24 lost — is one archival data is
    # happy with). Operators override the whole policy with the env.
    DEFAULT_SPEC = "default=10+4,archive=20+4"

    @classmethod
    def from_env(cls) -> "GeometryPolicy":
        return cls.parse(os.environ.get("WEED_EC_GEOMETRY",
                                        cls.DEFAULT_SPEC))

    def for_collection(self, collection: str = "") -> Geometry:
        return self.per_collection.get(collection or "", self.default)

    def to_dict(self) -> dict:
        """{'default': 'k+m', collections...} — the wire form the master
        serves in /dir/status and the shell planners read back."""
        out = {"default":
               f"{self.default.data_shards}+{self.default.parity_shards}"}
        for name, g in sorted(self.per_collection.items()):
            out[name] = f"{g.data_shards}+{g.parity_shards}"
        return out

    @classmethod
    def from_dict(cls, d: dict) -> "GeometryPolicy":
        default = DEFAULT
        mapping: dict[str, Geometry] = {}
        for name, geo in (d or {}).items():
            g = parse_geometry(str(geo))
            if name == "default":
                default = g
            else:
                mapping[name] = g
        return cls(mapping, default)
