"""weedlint engine: pluggable AST static analysis for the async storage
plane.

Every invariant the serving/EC/lifecycle planes fought for — no blocking
calls on the event loop, every outbound hop bounded and trace-carrying,
every daemon shedable and cancellable — is invisible to pytest but
trivial for a tree walk. This engine gives those walks one home: a rule
registry, file/line-precise diagnostics, inline suppressions, and a
checked-in baseline for grandfathered findings, so a new invariant is a
~50-line Rule subclass instead of a new one-off test file.

Vocabulary:

  * Rule        — one named invariant; checks a module tree (and/or the
                  whole project for cross-file invariants) and yields
                  Diagnostics. Ships its own seeded-violation fixture so
                  the registry is self-testing.
  * Diagnostic  — (rule, path, line, message) with a content-addressed
                  fingerprint that survives unrelated line drift.
  * suppression — ``# weedlint: disable=<rule>[,<rule>...]`` on the
                  flagged line (or alone on the line above); ``*``
                  disables all rules; ``disable-file=`` scopes to the
                  whole file.
  * baseline    — JSON map of grandfathered fingerprints. New findings
                  fail; a baseline entry that no longer matches anything
                  fails too (stale entries must not linger).
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Optional, Sequence

__all__ = [
    "Diagnostic", "Module", "Rule", "Report", "Baseline",
    "register", "registry", "load_module", "run",
]


# ------------------------------------------------------------ diagnostics

@dataclass(frozen=True)
class Diagnostic:
    rule: str
    path: str          # repo-root-relative, posix separators
    line: int
    message: str
    line_text: str = ""   # stripped source of the flagged line
    occurrence: int = 0   # index among identical (rule,path,line_text)

    @property
    def fingerprint(self) -> str:
        """Content-addressed id: stable when unrelated edits shift line
        numbers, invalidated when the flagged line itself changes (a
        changed line is a new finding — re-judge it, don't grandfather
        it silently)."""
        h = hashlib.sha1()
        h.update(f"{self.rule}|{self.path}|{self.line_text}|"
                 f"{self.occurrence}".encode())
        return h.hexdigest()[:16]

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


# ------------------------------------------------------------ modules

_SUPPRESS_RE = re.compile(
    r"#\s*weedlint:\s*disable(?P<file>-file)?\s*=\s*"
    r"(?P<rules>[\w\-*]+(?:\s*,\s*[\w\-*]+)*)")


@dataclass
class Module:
    path: str          # absolute
    relpath: str       # repo-root-relative, posix
    source: str
    tree: ast.Module
    lines: List[str] = field(default_factory=list)
    # lineno -> set of rule names suppressed there ("*" = all)
    line_suppressions: Dict[int, set] = field(default_factory=dict)
    file_suppressions: set = field(default_factory=set)
    _walk_cache: Optional[List[ast.AST]] = field(default=None,
                                                 repr=False)
    _alias_cache: Optional[Dict[str, str]] = field(default=None,
                                                   repr=False)

    def walk(self) -> List[ast.AST]:
        """Every AST node, computed once — fifteen rules re-walking a
        231-file tree is the difference between a 2s and a 7s gate."""
        if self._walk_cache is None:
            self._walk_cache = list(ast.walk(self.tree))
        return self._walk_cache

    def aliases(self) -> Dict[str, str]:
        if self._alias_cache is None:
            from .astutil import import_aliases
            self._alias_cache = import_aliases(self.tree)
        return self._alias_cache

    def suppressed(self, diag: Diagnostic) -> bool:
        for names in (self.file_suppressions,
                      self.line_suppressions.get(diag.line, ())):
            if "*" in names or diag.rule in names:
                return True
        return False

    def line_at(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""


def _statement_spans(tree: ast.Module) -> List[tuple]:
    """Line ranges a suppression comment may expand over: full spans of
    SIMPLE statements, but only the header of compound ones (a comment
    trailing a multi-line ``with``/``if`` header reaches the header's
    first line without silencing the whole body)."""
    spans = []
    for node in ast.walk(tree):
        # excepthandlers aren't stmts but carry diagnostics (cancelled-
        # swallow anchors at the except line) — their headers count too
        if not isinstance(node, (ast.stmt, ast.excepthandler)) or \
                not getattr(node, "end_lineno", None):
            continue
        # a decorated def/class: the decorators ARE part of the logical
        # header (node.lineno is the `def` line, so findings anchored at
        # a decorator line used to live in no span and a suppression
        # comment elsewhere in the header could never reach them)
        start = node.lineno
        for dec in getattr(node, "decorator_list", ()) or ():
            start = min(start, dec.lineno)
        body = getattr(node, "body", None)
        if body and isinstance(body, list) and body \
                and hasattr(body[0], "lineno"):
            spans.append((start, max(node.lineno, body[0].lineno - 1)))
        else:
            spans.append((start, node.end_lineno))
    return spans


def _innermost_span(lineno: int, spans: List[tuple]) -> tuple:
    best = None
    for a, b in spans:
        if a <= lineno <= b and (best is None
                                 or (b - a) < (best[1] - best[0])):
            best = (a, b)
    return best or (lineno, lineno)


def _parse_suppressions(mod: Module) -> None:
    spans = None
    for i, raw in enumerate(mod.lines, start=1):
        m = _SUPPRESS_RE.search(raw)
        if not m:
            continue
        names = {r.strip() for r in m.group("rules").split(",") if r.strip()}
        if m.group("file"):
            mod.file_suppressions |= names
            continue
        if spans is None:
            spans = _statement_spans(mod.tree)

        def mark(lineno: int) -> None:
            # suppress the WHOLE logical statement containing the
            # comment: a trailing comment on the last line of a
            # multi-line call must reach the diagnostic anchored at
            # the call's first line
            a, b = _innermost_span(lineno, spans)
            for ln in range(a, b + 1):
                mod.line_suppressions.setdefault(ln, set()).update(names)

        mark(i)
        if raw.lstrip().startswith("#"):
            # standalone comment line: also covers the next statement
            mark(i + 1)


def load_module(path: str, relpath: str,
                source: Optional[str] = None) -> Module:
    if source is None:
        with open(path, encoding="utf-8") as f:
            source = f.read()
    tree = ast.parse(source, filename=path)
    mod = Module(path=path, relpath=relpath.replace(os.sep, "/"),
                 source=source, tree=tree, lines=source.splitlines())
    _parse_suppressions(mod)
    return mod


# ------------------------------------------------------------ rules

_REGISTRY: Dict[str, "Rule"] = {}


def register(cls):
    """Class decorator: instantiate and enroll a Rule. Import order is
    registration order; names must be unique."""
    inst = cls()
    if inst.name in _REGISTRY:
        raise ValueError(f"duplicate weedlint rule name: {inst.name}")
    _REGISTRY[inst.name] = inst
    return cls


def registry() -> Dict[str, "Rule"]:
    """name -> Rule for every registered rule (rules self-register on
    import of seaweedfs_tpu.analysis.rules)."""
    from . import rules  # noqa: F401  (import side effect: registration)
    return dict(_REGISTRY)


class Rule:
    """One named invariant.

    Subclasses set ``name``/``rationale``/``fixture`` and override
    ``check_module`` (per-file walks) and/or ``check_project``
    (cross-file invariants, called once with every in-scope module).

    ``scope`` entries are repo-root-relative posix prefixes; an entry
    ending in "/" matches the subtree, otherwise the exact file.
    ``fixture`` is a seeded-violation source string the rule MUST flag
    and ``clean_fixture`` (optional) one it must NOT — the registry
    self-test in tests/test_weedlint.py iterates these, so a rule
    without a firing fixture cannot ship.
    """

    name: str = ""
    rationale: str = ""
    scope: Sequence[str] = ("seaweedfs_tpu/",)
    fixture: str = ""
    clean_fixture: str = ""
    # relpath the fixture pretends to live at (some scopes are per-dir)
    fixture_relpath: str = "seaweedfs_tpu/server/_fixture.py"

    def applies_to(self, relpath: str) -> bool:
        for entry in self.scope:
            if entry.endswith("/"):
                if relpath.startswith(entry):
                    return True
            elif relpath == entry:
                return True
        return False

    def check_module(self, mod: Module) -> Iterator[Diagnostic]:
        return iter(())

    def check_project(self, mods: List[Module]) -> Iterator[Diagnostic]:
        return iter(())

    # -- helpers for subclasses ------------------------------------

    def diag(self, mod: Module, line: int, message: str) -> Diagnostic:
        return Diagnostic(rule=self.name, path=mod.relpath, line=line,
                          message=message, line_text=mod.line_at(line))


def _number_occurrences(diags: List[Diagnostic]) -> List[Diagnostic]:
    """Assign occurrence indexes so identical lines (e.g. two equal
    calls in one file) fingerprint distinctly."""
    seen: Dict[tuple, int] = {}
    out = []
    for d in diags:
        key = (d.rule, d.path, d.line_text)
        n = seen.get(key, 0)
        seen[key] = n + 1
        out.append(Diagnostic(rule=d.rule, path=d.path, line=d.line,
                              message=d.message, line_text=d.line_text,
                              occurrence=n))
    return out


# ------------------------------------------------------------ baseline

class Baseline:
    """Checked-in grandfather list. Matching is by fingerprint only;
    line/message are carried for human diffing and refreshed on write."""

    def __init__(self, entries: Optional[Dict[str, dict]] = None,
                 path: str = ""):
        self.entries = entries or {}
        self.path = path

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls(path=path)
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        if data.get("version") != 1:
            raise ValueError(f"{path}: unsupported baseline version "
                             f"{data.get('version')!r}")
        return cls({e["fp"]: e for e in data.get("entries", [])},
                   path=path)

    @classmethod
    def from_findings(cls, findings: Iterable[Diagnostic],
                      path: str = "") -> "Baseline":
        return cls({d.fingerprint: {
            "fp": d.fingerprint, "rule": d.rule, "path": d.path,
            "line": d.line, "message": d.message} for d in findings},
            path=path)

    def write(self, path: Optional[str] = None) -> None:
        path = path or self.path
        entries = sorted(self.entries.values(),
                         key=lambda e: (e["rule"], e["path"], e["line"]))
        with open(path, "w", encoding="utf-8") as f:
            json.dump({"version": 1, "entries": entries}, f, indent=1,
                      sort_keys=True)
            f.write("\n")

    def __contains__(self, diag: Diagnostic) -> bool:
        return diag.fingerprint in self.entries


# ------------------------------------------------------------ runner

@dataclass
class Report:
    new: List[Diagnostic] = field(default_factory=list)
    baselined: List[Diagnostic] = field(default_factory=list)
    suppressed: List[Diagnostic] = field(default_factory=list)
    stale_baseline: List[dict] = field(default_factory=list)
    files_checked: int = 0
    # what this run actually looked at — partial runs (one file, one
    # --rules subset) must neither report out-of-scope baseline entries
    # stale nor let --write-baseline erase them
    rules_run: set = field(default_factory=set)
    analyzed_files: set = field(default_factory=set)
    analyzed_dirs: List[str] = field(default_factory=list)

    def covers(self, relpath: str) -> bool:
        """Was this (possibly deleted) path within the run's scope?"""
        if relpath in self.analyzed_files:
            return True
        return any(relpath.startswith(d) for d in self.analyzed_dirs)

    @property
    def clean(self) -> bool:
        return not self.new and not self.stale_baseline

    def render(self, show_baselined: bool = False) -> str:
        out = []
        for d in sorted(self.new, key=lambda d: (d.path, d.line, d.rule)):
            out.append(d.render())
        if show_baselined:
            for d in sorted(self.baselined,
                            key=lambda d: (d.path, d.line, d.rule)):
                out.append(f"{d.render()}  (baselined)")
        for e in sorted(self.stale_baseline,
                        key=lambda e: (e["rule"], e["path"], e["line"])):
            out.append(
                f"{e['path']}:{e['line']}: [{e['rule']}] STALE baseline "
                f"entry {e['fp']} no longer matches any finding — the "
                f"violation was fixed or the line changed; remove the "
                f"entry (or --write-baseline) so it cannot mask a "
                f"future regression")
        return "\n".join(out)


def _iter_py_files(paths: Sequence[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in ("__pycache__", ".git"))
            for name in sorted(filenames):
                if name.endswith(".py"):
                    yield os.path.join(dirpath, name)


def _load_one(args: tuple) -> object:
    """Process-pool worker: parse one file, returning either the Module
    or a parse-error Diagnostic. Top-level (picklable) by necessity."""
    apath, rel = args
    try:
        return load_module(apath, rel)
    except SyntaxError as e:
        return Diagnostic(rule="parse-error", path=rel,
                          line=e.lineno or 1,
                          message=f"does not parse: {e.msg}")


def collect_modules(root: str, paths: Sequence[str], jobs: int = 1
                    ) -> tuple[List[Module], List[Diagnostic]]:
    """Parse every .py under paths. Unparseable files become findings
    (rule ``parse-error``) rather than crashing the run — a syntax error
    in the tree is itself the worst lint finding there is.

    ``jobs > 1`` fans the parse (the dominant cost of a full-tree run)
    across a process pool; results come back in deterministic file
    order either way, so fingerprints and occurrence indexes match the
    serial run exactly. Any pool-level failure falls back to serial —
    a lint gate must never fail because fork/pickle did."""
    work = []
    seen = set()
    for path in _iter_py_files(paths):
        apath = os.path.abspath(path)
        if apath in seen:
            continue
        seen.add(apath)
        work.append((apath,
                     os.path.relpath(apath, root).replace(os.sep, "/")))

    results: List[object] = []
    if jobs > 1 and len(work) > 1:
        try:
            import multiprocessing
            from concurrent.futures import ProcessPoolExecutor
            # spawn, never fork: a forked child of a multithreaded
            # parent (pytest with JAX imported) inherits locks held
            # mid-operation by threads that don't exist in the child —
            # an intermittent hang the serial fallback cannot catch
            # because a deadlocked map never raises
            with ProcessPoolExecutor(
                    max_workers=jobs,
                    mp_context=multiprocessing.get_context("spawn")) \
                    as pool:
                results = list(pool.map(_load_one, work,
                                        chunksize=max(1, len(work) // (jobs * 4))))
        except Exception:
            # pool/pickle trouble only — real parse errors come back as
            # values, and anything genuine re-raises from the serial
            # fallback below
            results = []
    if not results:
        results = [_load_one(w) for w in work]

    mods: List[Module] = []
    errors: List[Diagnostic] = []
    for r in results:
        (errors if isinstance(r, Diagnostic) else mods).append(r)
    return mods, errors


def run(root: str, paths: Sequence[str],
        rule_names: Optional[Sequence[str]] = None,
        baseline: Optional[Baseline] = None, jobs: int = 1) -> Report:
    """Analyze paths (files or directories) against the registry.

    root anchors relpaths (and therefore fingerprints): pass the repo
    root so baselines are stable regardless of invocation cwd.
    """
    rules = registry()
    if rule_names:
        unknown = [r for r in rule_names if r not in rules]
        if unknown:
            raise ValueError(f"unknown rule(s): {', '.join(unknown)}; "
                             f"known: {', '.join(sorted(rules))}")
        rules = {k: v for k, v in rules.items() if k in rule_names}

    mods, parse_errors = collect_modules(root, paths, jobs=jobs)
    # unparseable files still count as checked — they produced findings
    report = Report(files_checked=len(mods) + len(parse_errors),
                    rules_run=set(rules))
    report.analyzed_files = {m.relpath for m in mods}
    for p in paths:
        ap = os.path.abspath(p)
        if os.path.isdir(ap):
            rel = os.path.relpath(ap, root).replace(os.sep, "/")
            report.analyzed_dirs.append("" if rel == "." else rel + "/")
    raw: List[Diagnostic] = list(parse_errors)
    by_path = {m.relpath: m for m in mods}

    for rule in rules.values():
        in_scope = [m for m in mods if rule.applies_to(m.relpath)]
        for m in in_scope:
            raw.extend(rule.check_module(m))
        raw.extend(rule.check_project(in_scope))

    matched_fps = set()
    for d in _number_occurrences(raw):
        mod = by_path.get(d.path)
        if mod is not None and mod.suppressed(d):
            report.suppressed.append(d)
            continue
        # parse errors are never baselineable: a file that stops
        # parsing is the one finding that must always fail, and its
        # empty line_text would otherwise grandfather EVERY future
        # syntax error in that file under one fingerprint
        if baseline is not None and d.rule != "parse-error" \
                and d in baseline:
            matched_fps.add(d.fingerprint)
            report.baselined.append(d)
            continue
        report.new.append(d)

    if baseline is not None:
        # stale detection is scoped to files and rules actually analyzed
        # this run: linting one file (or --rules one-rule) must not
        # declare the rest of the baseline stale. Scope is covers(), not
        # mere existence — an entry for a DELETED file under an analyzed
        # directory is stale too, or it would linger forever and silently
        # re-grandfather the violation if the file ever came back
        for fp, entry in baseline.entries.items():
            rule_active = (entry.get("rule") in rules
                           or entry.get("rule") == "parse-error")
            if fp not in matched_fps and rule_active \
                    and report.covers(entry.get("path", "")):
                report.stale_baseline.append(entry)
    return report
