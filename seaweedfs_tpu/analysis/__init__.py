"""weedlint: the storage plane's static-analysis engine.

``python -m seaweedfs_tpu.analysis --baseline .weedlint-baseline.json
seaweedfs_tpu/ tests/`` is the CI gate; tests/test_weedlint.py iterates
the registry so every rule is tier-1-enforced and self-tested against
its seeded-violation fixture. See README "Static analysis" for the rule
catalog, suppression syntax and baseline workflow.
"""

from .engine import (  # noqa: F401
    Baseline, Diagnostic, Module, Report, Rule, load_module, register,
    registry, run,
)


def check_source(rule: Rule, source: str, relpath: str = "") -> list:
    """Run one rule against an in-memory source string (fixture tests,
    editor integrations). Suppression comments apply; baseline does
    not."""
    mod = load_module(path=relpath or rule.fixture_relpath,
                      relpath=relpath or rule.fixture_relpath,
                      source=source)
    diags = list(rule.check_module(mod))
    diags.extend(rule.check_project([mod]))
    return [d for d in diags if not mod.suppressed(d)]
