"""Every intra-cluster call site must be time-bounded. Ported from
tests/test_timeout_guard.py."""

from __future__ import annotations

import ast

from ..astutil import resolve_call_path
from ..engine import Rule, register

_GUARDED = {
    ("urllib", "request", "urlopen"): "urllib.request.urlopen",
    ("aiohttp", "ClientSession"): "aiohttp.ClientSession",
    ("http", "client", "HTTPConnection"): "http.client.HTTPConnection",
    ("http", "client", "HTTPSConnection"): "http.client.HTTPSConnection",
}


@register
class HttpTimeout(Rule):
    name = "http-timeout"
    rationale = ("a urlopen/ClientSession/HTTPConnection without "
                 "timeout= hangs forever on a wedged peer — self-"
                 "healing depends on failures surfacing")
    scope = ("seaweedfs_tpu/",)
    fixture = (
        "import urllib.request\n"
        "import aiohttp\n"
        "import http.client\n"
        "from aiohttp import ClientSession\n"
        "def bad1(u):\n"
        "    return urllib.request.urlopen(u)\n"
        "def bad2():\n"
        "    return aiohttp.ClientSession()\n"
        "def bad3(h):\n"
        "    return http.client.HTTPConnection(h)\n"
        "def bad4():\n"
        "    return ClientSession()\n"
    )
    clean_fixture = (
        "import urllib.request\n"
        "import aiohttp\n"
        "import http.client\n"
        "def good1(u):\n"
        "    return urllib.request.urlopen(u, timeout=5)\n"
        "def good2():\n"
        "    return aiohttp.ClientSession(timeout=object())\n"
        "def good3(h, kw):\n"
        "    return http.client.HTTPConnection(h, **kw)\n"
    )

    def check_module(self, mod):
        aliases = mod.aliases()
        for node in mod.walk():
            if not isinstance(node, ast.Call):
                continue
            path = resolve_call_path(node, aliases)
            label = _GUARDED.get(path)
            if label is None:
                continue
            kwargs = {k.arg for k in node.keywords}
            if "timeout" not in kwargs and None not in kwargs:  # **kw exempt
                yield self.diag(
                    mod, node.lineno,
                    f"{label}() without an explicit timeout= — a wedged "
                    f"peer hangs this call site forever")
