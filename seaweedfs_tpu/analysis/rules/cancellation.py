"""Cancellation must terminate daemons. A loop whose broad except
handler swallows CancelledError and keeps looping is a daemon that
``cancel()`` cannot stop — shutdown hangs, tests leak event loops."""

from __future__ import annotations

import ast

from ..astutil import attr_path, walk_body
from ..engine import Rule, register


def _handler_names(handler: ast.ExceptHandler):
    """Dotted names the handler catches; [''] for a bare ``except:``."""
    t = handler.type
    if t is None:
        return [""]
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    return [".".join(attr_path(e)) for e in elts]


def _catches_cancellation(handler: ast.ExceptHandler) -> bool:
    # on py3.8+ CancelledError derives from BaseException, so
    # ``except Exception`` does NOT swallow it — only these do
    for name in _handler_names(handler):
        if name == "" or name.endswith("BaseException") or \
                name.endswith("CancelledError"):
            return True
    return False


def _exits(handler: ast.ExceptHandler) -> bool:
    """True when the handler re-raises or leaves the enclosing loop —
    i.e. a cancellation that lands here still terminates the daemon.
    A ``break`` nested inside a loop WITHIN the handler only exits
    that inner loop, so it does not count."""

    def scan(nodes, loop_depth: int) -> bool:
        for n in nodes:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            # raise and return escape the daemon loop from any depth
            if isinstance(n, (ast.Raise, ast.Return)):
                return True
            if isinstance(n, ast.Break) and loop_depth == 0:
                return True
            depth = loop_depth + (1 if isinstance(
                n, (ast.While, ast.For, ast.AsyncFor)) else 0)
            if scan(ast.iter_child_nodes(n), depth):
                return True
        return False

    return scan(handler.body, 0)


@register
class CancelledSwallow(Rule):
    name = "cancelled-swallow"
    rationale = ("a loop whose except swallows CancelledError (bare/"
                 "BaseException/CancelledError with no raise/return/"
                 "break) is a daemon cancel() cannot stop — shutdown "
                 "hangs until SIGKILL")
    scope = ("seaweedfs_tpu/",)
    fixture = (
        "async def bad_loop(self):\n"
        "    while True:\n"
        "        try:\n"
        "            await self._pass()\n"
        "        except (ConnectionError, asyncio.CancelledError):\n"
        "            pass\n"
        "        await asyncio.sleep(1)\n"
        "async def bare(self):\n"
        "    try:\n"
        "        await self._pass()\n"
        "    except:\n"
        "        pass\n"
        "async def nested_break(self):\n"
        "    while True:\n"
        "        try:\n"
        "            await self._pass()\n"
        "        except BaseException:\n"
        "            for x in self.items:\n"
        "                break\n"       # exits the for, NOT the daemon
    )
    clean_fixture = (
        "async def good_loop(self):\n"
        "    while True:\n"
        "        try:\n"
        "            await self._pass()\n"
        "        except asyncio.CancelledError:\n"
        "            raise\n"
        "        except Exception:\n"   # does not catch CancelledError
        "            log.warning('pass failed')\n"
        "        await asyncio.sleep(1)\n"
        "async def good_return(self):\n"
        "    while True:\n"
        "        try:\n"
        "            await self._pass()\n"
        "        except asyncio.CancelledError:\n"
        "            return\n"
        "async def good_reraise_first(self):\n"
        "    while True:\n"
        "        try:\n"
        "            await self._pass()\n"
        "        except asyncio.CancelledError:\n"
        "            raise\n"
        "        except BaseException as e:\n"   # unreachable for
        "            log.warning('pass: %s', e)\n"  # cancellation
    )

    def check_module(self, mod):
        for fn in mod.walk():
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            yield from self._check_fn(mod, fn)

    def _check_fn(self, mod, fn):
        def visit(node, in_loop: bool):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                loop_now = in_loop or isinstance(
                    child, (ast.While, ast.For, ast.AsyncFor))
                if isinstance(child, ast.Try):
                    shielded = False
                    for h in child.handlers:
                        if not _catches_cancellation(h):
                            continue
                        if shielded:
                            # an earlier handler already consumed
                            # cancellation: this one can never see it
                            # (the re-raise-first idiom stays clean)
                            continue
                        # only the FIRST cancellation-catching handler
                        # is judged; whatever it does, later ones are
                        # unreachable for CancelledError
                        shielded = True
                        if _exits(h):
                            continue
                        names = [n or "<bare>"
                                 for n in _handler_names(h)]
                        if in_loop:
                            yield self.diag(
                                mod, h.lineno,
                                f"async def {fn.name}: except "
                                f"{'/'.join(names)} inside a loop "
                                f"swallows CancelledError and keeps "
                                f"looping — this daemon cannot be "
                                f"cancelled; re-raise (or return/"
                                f"break)")
                        elif h.type is None:
                            yield self.diag(
                                mod, h.lineno,
                                f"async def {fn.name}: bare except "
                                f"swallows CancelledError (and every "
                                f"error) — catch specific exceptions "
                                f"or re-raise")
                yield from visit(child, loop_now)

        yield from visit(fn, False)
