"""Diagnostics go through glog, not print(). Ported from
tests/test_http_surface.py's lint-style check."""

from __future__ import annotations

import ast

from ..engine import Rule, register

# files whose prints ARE their output contract
_EXEMPT = (
    "seaweedfs_tpu/cli.py",
    "seaweedfs_tpu/analysis/__main__.py",
    "seaweedfs_tpu/crashsim/__main__.py",
    "seaweedfs_tpu/clustersim/__main__.py",
)


@register
class BarePrint(Rule):
    name = "bare-print"
    rationale = ("diagnostics must go through glog (utils/glog.py) so "
                 "they carry severity/timestamps and obey -v levels; "
                 "cli.py and the lint CLI are exempt (their prints are "
                 "the output contract)")
    scope = ("seaweedfs_tpu/",)
    fixture = "def f():\n    print('debug')\n"
    clean_fixture = ("import logging\n"
                     "log = logging.getLogger(__name__)\n"
                     "def f():\n    log.info('debug')\n")

    def applies_to(self, relpath: str) -> bool:
        return super().applies_to(relpath) and relpath not in _EXEMPT

    def check_module(self, mod):
        for node in mod.walk():
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name) and \
                    node.func.id == "print":
                yield self.diag(
                    mod, node.lineno,
                    "bare print() — route diagnostics through glog "
                    "(utils/glog.py)")
