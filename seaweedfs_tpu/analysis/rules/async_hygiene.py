"""Event-loop hygiene: nothing blocking and nothing re-imported inside
``async def`` bodies. Ported from tests/test_async_guard.py."""

from __future__ import annotations

import ast
import sys

from ..astutil import resolve_call_path, walk_body
from ..callgraph import BLOCKING_PRIMITIVES as BLOCKING
from ..engine import Rule, register


@register
class AsyncBlockingCall(Rule):
    name = "async-blocking-call"
    rationale = ("a single synchronous fsync/sleep/subprocess inside a "
                 "coroutine stalls every in-flight request on that "
                 "server's event loop")
    # package-wide: a blocking call on any event loop is a bug, not just
    # on the serving planes the original guard covered
    scope = ("seaweedfs_tpu/",)
    fixture = (
        "import os\n"
        "import time as t\n"
        "from time import sleep as zzz\n"
        "async def bad1(fd):\n"
        "    os.fsync(fd)\n"
        "async def bad2():\n"
        "    t.sleep(1)\n"
        "async def bad3():\n"
        "    zzz(2)\n"
    )
    clean_fixture = (
        "import os\n"
        "async def good(loop, fd):\n"
        "    def _sync():\n"
        "        os.fsync(fd)\n"  # nested sync def = executor body
        "    await loop.run_in_executor(None, _sync)\n"
        "def sync_path(fd):\n"
        "    os.fsync(fd)\n"      # sync functions may block freely
    )

    def check_module(self, mod):
        aliases = mod.aliases()
        for node in mod.walk():
            if not isinstance(node, ast.AsyncFunctionDef):
                continue
            for n in walk_body(node):
                if not isinstance(n, ast.Call):
                    continue
                path = resolve_call_path(n, aliases)
                if len(path) == 2 and tuple(path) in BLOCKING:
                    yield self.diag(
                        mod, n.lineno,
                        f"async def {node.name} calls "
                        f"{path[0]}.{path[1]}() on the event loop — "
                        f"{BLOCKING[tuple(path)]}")


@register
class AsyncStdlibImport(Rule):
    name = "async-stdlib-import"
    rationale = ("a function-local stdlib import inside a request "
                 "handler is pure per-call overhead (import-lock "
                 "traffic showed up in write-path profiles); package/"
                 "third-party lazy loads stay exempt")
    # the hot serving planes only: elsewhere a local stdlib import is a
    # style nit, here it is measured per-request cost
    scope = ("seaweedfs_tpu/server/", "seaweedfs_tpu/ec/pipeline.py",
             "seaweedfs_tpu/s3/", "seaweedfs_tpu/overload/",
             "seaweedfs_tpu/filer/")
    fixture = (
        "async def bad():\n"
        "    import uuid\n"
        "    from time import sleep\n"
    )
    clean_fixture = (
        "import os\n"
        "async def good(loop):\n"
        "    from ..utils import cipher\n"   # package-relative: exempt
        "    from aiohttp import web\n"      # third-party: exempt
        "    def _sync():\n"
        "        import json\n"              # executor body: exempt
        "    await loop.run_in_executor(None, _sync)\n"
    )

    def check_module(self, mod):
        stdlib = sys.stdlib_module_names
        for node in mod.walk():
            if not isinstance(node, ast.AsyncFunctionDef):
                continue
            for n in walk_body(node):
                if isinstance(n, ast.Import):
                    for a in n.names:
                        if a.name.split(".")[0] in stdlib:
                            yield self.diag(
                                mod, n.lineno,
                                f"async def {node.name} imports "
                                f"{a.name} per call — hoist it to "
                                f"module level")
                elif isinstance(n, ast.ImportFrom) and n.level == 0 \
                        and n.module \
                        and n.module.split(".")[0] in stdlib:
                    yield self.diag(
                        mod, n.lineno,
                        f"async def {node.name} imports {n.module} per "
                        f"call — hoist it to module level")
