"""weedlint rule modules. Importing this package registers every rule;
the import list below IS the rule catalog load order (stable so
--list-rules and the README table stay in one order)."""

from . import async_hygiene      # noqa: F401  async-blocking-call, async-stdlib-import
from . import http_timeout       # noqa: F401  http-timeout
from . import app_construction   # noqa: F401  app-client-max-size, app-admission-middleware
from . import daemon_loops       # noqa: F401  daemon-loop-shedable
from . import bare_print         # noqa: F401  bare-print
from . import locks              # noqa: F401  lock-held-await, lock-ordering
from . import task_leak          # noqa: F401  task-leak
from . import cancellation       # noqa: F401  cancelled-swallow
from . import resources          # noqa: F401  resource-leak
from . import propagation        # noqa: F401  ctx-propagation
from . import registries         # noqa: F401  fault-point-registry, metric-label-registry
from . import interproc          # noqa: F401  blocking-call-transitive, lock-held-await-transitive, deadline-propagation, resource-leak-interproc
from . import durability         # noqa: F401  atomic-replace
from . import fork_asyncio       # noqa: F401  fork-then-asyncio
