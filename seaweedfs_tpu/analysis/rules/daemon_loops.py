"""Background daemon loops must be shedable (CLASS_BG) and de-
synchronized (jittered sleep). Ported from tests/test_async_guard.py's
lifecycle-loop guard."""

from __future__ import annotations

import ast

from ..astutil import walk_body
from ..engine import Rule, register


def _is_bg_priority_call(node: ast.Call) -> bool:
    """overload.set_priority(overload.CLASS_BG) / overload.priority(...)
    (or the bare-name variants after a from-import)."""
    f = node.func
    name = f.attr if isinstance(f, ast.Attribute) else \
        (f.id if isinstance(f, ast.Name) else "")
    if name not in ("set_priority", "priority"):
        return False
    for arg in node.args:
        if isinstance(arg, ast.Attribute) and arg.attr == "CLASS_BG":
            return True
        if isinstance(arg, ast.Name) and arg.id == "CLASS_BG":
            return True
    return False


def _daemon_loop_violations(node: ast.AsyncFunctionDef):
    calls = [n for n in ast.walk(node) if isinstance(n, ast.Call)]
    has_sleep = any(isinstance(c.func, ast.Attribute)
                    and c.func.attr == "sleep"
                    and isinstance(c.func.value, ast.Name)
                    and c.func.value.id == "asyncio" for c in calls)
    has_forever = any(isinstance(n, ast.While) and
                      isinstance(n.test, ast.Constant) and
                      n.test.value is True
                      for n in ast.walk(node))
    # a daemon loop is a *_loop-named coroutine, or a while-True that
    # paces itself with asyncio.sleep; bounded pagination loops (no
    # sleep) are request-scoped work, not daemons
    if not (node.name.endswith("_loop") or (has_forever and has_sleep)):
        return
    if not any(_is_bg_priority_call(c) for c in calls):
        yield (node.lineno,
               f"async def {node.name}: daemon loop without overload "
               f"CLASS_BG binding — its fan-out can never be shed")
    for c in calls:
        f = c.func
        is_sleep = (isinstance(f, ast.Attribute) and f.attr == "sleep"
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "asyncio")
        if not is_sleep:
            continue
        arg = c.args[0] if c.args else None
        ok = (isinstance(arg, ast.Call) and
              ((isinstance(arg.func, ast.Name)
                and arg.func.id == "jittered") or
               (isinstance(arg.func, ast.Attribute)
                and arg.func.attr == "jittered")))
        if not ok:
            yield (c.lineno,
                   f"async def {node.name}: asyncio.sleep without "
                   f"jittered(interval) — a fleet of masters would "
                   f"scan in lockstep")


@register
class DaemonLoopShedable(Rule):
    name = "daemon-loop-shedable"
    rationale = ("every lifecycle/geo/metaring/balance daemon loop must "
                 "bind CLASS_BG (so its fan-out sheds before foreground "
                 "traffic) and sleep on a jittered interval (no "
                 "fleet-wide lockstep scans)")
    scope = ("seaweedfs_tpu/lifecycle/", "seaweedfs_tpu/geo/",
             "seaweedfs_tpu/metaring/", "seaweedfs_tpu/balance/",
             "seaweedfs_tpu/clustersim/")
    fixture_relpath = "seaweedfs_tpu/lifecycle/_fixture.py"
    fixture = (
        "async def scan_loop():\n"
        "    while True:\n"
        "        await asyncio.sleep(60)\n"
    )
    clean_fixture = (
        "async def scan_loop(self):\n"
        "    overload.set_priority(overload.CLASS_BG)\n"
        "    while True:\n"
        "        await asyncio.sleep(jittered(self.cfg.interval))\n"
        "async def other_loop(self):\n"
        "    with priority(CLASS_BG):\n"
        "        while True:\n"
        "            await asyncio.sleep(lifecycle.jittered(3.0))\n"
    )

    def check_module(self, mod):
        for node in mod.walk():
            if not isinstance(node, ast.AsyncFunctionDef):
                continue
            for lineno, problem in _daemon_loop_violations(node):
                yield self.diag(mod, lineno, problem)

    def check_project(self, mods):
        # the guard must be guarding something — PER PLANE: each scoped
        # directory that ships a daemon.py must still contain an async
        # daemon loop, or the guard certifies air for that plane while
        # the other plane's loop keeps it green
        for prefix in self.scope:
            plane = [m for m in mods if m.relpath.startswith(prefix)]
            daemon_mod = next((m for m in plane
                               if m.relpath.endswith("/daemon.py")),
                              None)
            if daemon_mod is None:
                continue
            has_loop = any(
                isinstance(node, ast.AsyncFunctionDef) and any(
                    isinstance(n, ast.While) for n in walk_body(node))
                for mod in plane for node in mod.walk())
            if not has_loop:
                yield self.diag(
                    daemon_mod, 1,
                    f"{prefix} contains no async daemon loop — the "
                    f"daemon-loop guard guards nothing there")
