"""Fork/event-loop ordering: ``os.fork()`` must happen before any
event loop exists.  A loop's epoll fd created pre-fork is inherited by
every child — the shards then steal each other's readiness events and
the fleet livelocks in ways that only reproduce under load.  The shard
runner (server/sharded.py) forks from the CLI for exactly this reason;
this rule pins the ordering tree-wide."""

from __future__ import annotations

import ast

from ..astutil import resolve_call_path, walk_body
from ..engine import Rule, register

#: calls that create (or imply) an event loop in this process
_LOOP_MAKERS = {
    ("asyncio", "new_event_loop"),
    ("asyncio", "get_event_loop"),
    ("asyncio", "get_running_loop"),
    ("asyncio", "run"),
}

_FORK = ("os", "fork")


@register
class ForkThenAsyncio(Rule):
    name = "fork-then-asyncio"
    rationale = ("os.fork() after an event loop exists shares the "
                 "loop's epoll fd with every child — shards steal each "
                 "other's readiness events; fork first, then build the "
                 "loop per process (server/sharded.py ordering)")
    scope = ("seaweedfs_tpu/",)
    fixture = (
        "import asyncio\n"
        "import os\n"
        "def bad():\n"
        "    loop = asyncio.new_event_loop()\n"
        "    pid = os.fork()\n"
        "async def worse():\n"
        "    os.fork()\n"
    )
    clean_fixture = (
        "import asyncio\n"
        "import os\n"
        "def good():\n"
        "    pid = os.fork()\n"
        "    if pid == 0:\n"
        "        loop = asyncio.new_event_loop()\n"
        "def fork_only():\n"
        "    return os.fork()\n"
        "def loop_only():\n"
        "    return asyncio.new_event_loop()\n"
    )

    def check_module(self, mod):
        aliases = mod.aliases()
        for node in mod.walk():
            if isinstance(node, ast.AsyncFunctionDef):
                # a coroutine runs ON a loop by definition: any fork
                # inside one inherits that loop's fds
                for n in walk_body(node):
                    if isinstance(n, ast.Call) and \
                            tuple(resolve_call_path(n, aliases)) == _FORK:
                        yield self.diag(
                            mod, n.lineno,
                            f"async def {node.name} calls os.fork() — "
                            f"the child inherits this loop's epoll fd; "
                            f"fork before any loop exists")
            elif isinstance(node, ast.FunctionDef):
                # lexical ordering within one sync function: a loop-
                # creating call before os.fork() (ast.walk is not
                # source-ordered, so sort by line first)
                calls = sorted(
                    (n for n in walk_body(node) if isinstance(n, ast.Call)),
                    key=lambda n: (n.lineno, n.col_offset))
                loop_line = None
                for n in calls:
                    path = tuple(resolve_call_path(n, aliases))
                    if path in _LOOP_MAKERS and loop_line is None:
                        loop_line = n.lineno
                    elif path == _FORK and loop_line is not None:
                        yield self.diag(
                            mod, n.lineno,
                            f"def {node.name} calls os.fork() after "
                            f"creating an event loop (line {loop_line})"
                            f" — the child shares its epoll fd; fork "
                            f"first, loop per process")
