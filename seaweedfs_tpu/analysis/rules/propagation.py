"""Context propagation: the trace id and overload priority live in
contextvars, which do NOT cross executor/thread hops or plain aiohttp
sessions by themselves. Every hop must use the blessed bridges:
``observe.run_with`` for threads/executors, ``observe.
client_trace_config()`` for outbound sessions (it injects both the
``X-Seaweed-Trace`` and priority headers)."""

from __future__ import annotations

import ast
from typing import Set

from ..astutil import resolve_call_path, walk_body
from ..engine import Rule, register

# observe.span() reads the AMBIENT contextvar; observe.stage()/
# record_span() take an explicit ctx argument and are hop-safe
_SPAN_EMITTERS = ("span",)


def _emits_spans(fn) -> bool:
    """Does this (nested) def call observe.span directly? Such a
    function reads the ambient trace context."""
    for n in walk_body(fn):
        if isinstance(n, ast.Call) and \
                isinstance(n.func, ast.Attribute) and \
                n.func.attr in _SPAN_EMITTERS and \
                isinstance(n.func.value, ast.Name) and \
                n.func.value.id == "observe":
            return True
    return False


@register
class CtxPropagation(Rule):
    name = "ctx-propagation"
    rationale = ("contextvars don't cross executor/thread hops or "
                 "plain sessions: span-emitting work shipped to an "
                 "executor must go through observe.run_with, and "
                 "every intra-cluster ClientSession must install "
                 "observe.client_trace_config() so trace id + "
                 "overload priority ride every outbound request")
    scope = ("seaweedfs_tpu/",)
    # observe/ implements the bridges; its own sessions are exempt
    _exempt = ("seaweedfs_tpu/observe/",)
    fixture = (
        "import aiohttp\n"
        "async def bad(self):\n"
        "    self._session = aiohttp.ClientSession(timeout=T)\n"
        "async def bad2(self, loop):\n"
        "    def work():\n"
        "        with observe.span('ec.read'):\n"
        "            return 1\n"
        "    await loop.run_in_executor(None, work)\n"
        "async def bad3(self):\n"
        "    self._s = aiohttp.ClientSession(trace_configs=[])\n"
    )
    clean_fixture = (
        "import aiohttp\n"
        "async def good(self):\n"
        "    self._session = aiohttp.ClientSession(\n"
        "        timeout=T,\n"
        "        trace_configs=[observe.client_trace_config()])\n"
        "async def good2(self, loop):\n"
        "    ctx = observe.capture()\n"
        "    def work():\n"
        "        with observe.span('ec.read'):\n"
        "            return 1\n"
        "    await loop.run_in_executor(\n"
        "        None, lambda: observe.run_with(ctx, work))\n"
        "async def good3(self, loop):\n"
        "    def plain():\n"
        "        return 1\n"           # no spans: no context needed
        "    await loop.run_in_executor(None, plain)\n"
    )

    def applies_to(self, relpath: str) -> bool:
        if any(relpath.startswith(e) for e in self._exempt):
            return False
        return super().applies_to(relpath)

    def check_module(self, mod):
        aliases = mod.aliases()
        yield from self._check_sessions(mod, aliases)
        yield from self._check_executor_hops(mod)

    def _check_sessions(self, mod, aliases):
        for node in mod.walk():
            if not isinstance(node, ast.Call):
                continue
            if resolve_call_path(node, aliases) != \
                    ("aiohttp", "ClientSession"):
                continue
            ok = False
            for kw in node.keywords:
                if kw.arg is None:     # **kwargs: can't judge
                    ok = True
                elif kw.arg == "trace_configs" and \
                        "client_trace_config" in ast.dump(kw.value):
                    # the kwarg must actually install the blessed
                    # config — trace_configs=[] still drops the headers
                    ok = True
            if not ok:
                yield self.diag(
                    mod, node.lineno,
                    "aiohttp.ClientSession() without trace_configs=["
                    "observe.client_trace_config()] — requests through "
                    "this session drop the trace id and overload "
                    "priority at the process boundary")

    def _check_executor_hops(self, mod):
        # only TOP-LEVEL functions: each owns its whole nested subtree
        # (span_fns may be defined in an outer def and handed off in an
        # inner one), and visiting nested defs again would report the
        # same hand-off twice
        fdefs = (ast.FunctionDef, ast.AsyncFunctionDef)
        nested = set()
        for f in mod.walk():
            if isinstance(f, fdefs):
                nested.update(id(sub) for sub in ast.walk(f)
                              if sub is not f and isinstance(sub, fdefs))
        for fn in mod.walk():
            if not isinstance(fn, fdefs) or id(fn) in nested:
                continue
            span_fns: Set[str] = {
                child.name for child in ast.walk(fn)
                if isinstance(child, ast.FunctionDef) and child is not fn
                and _emits_spans(child)}
            if not span_fns:
                continue
            for n in walk_body(fn, into_nested_defs=True):
                if not (isinstance(n, ast.Call) and
                        isinstance(n.func, ast.Attribute) and
                        n.func.attr in ("run_in_executor", "submit")):
                    continue
                for arg in n.args:
                    if isinstance(arg, ast.Name) and arg.id in span_fns:
                        yield self.diag(
                            mod, n.lineno,
                            f"span-emitting '{arg.id}' handed raw to "
                            f"{n.func.attr} — run_in_executor does not "
                            f"copy contextvars, so its spans lose the "
                            f"request's trace id; wrap as lambda: "
                            f"observe.run_with(observe.capture(), "
                            f"{arg.id})")
                for kw in n.keywords:
                    if isinstance(kw.value, ast.Name) and \
                            kw.value.id in span_fns:
                        yield self.diag(
                            mod, n.lineno,
                            f"span-emitting '{kw.value.id}' handed raw "
                            f"to {n.func.attr} — wrap with "
                            f"observe.run_with")
