"""Unclosed resources: files, mmaps, sockets and aiohttp sessions must
be closed on EVERY path — ``with``/``async with``, or a close under
``finally``. A close only on the happy path leaks the fd/session the
first time the code between open and close raises."""

from __future__ import annotations

import ast
from typing import Iterator, List

from ..astutil import FUNC_DEFS, resolve_call_path, walk_body
from ..callgraph import RESOURCE_CONSTRUCTORS as _CONSTRUCTORS
from ..engine import Rule, register

# raw-handle constructors additionally tracked in comprehensions: a
# failure mid-comprehension leaks every handle already produced (the
# list doesn't exist yet, so no cleanup path can reach them)
_COMPREHENSION_CONSTRUCTORS = dict(_CONSTRUCTORS)
_COMPREHENSION_CONSTRUCTORS[("os", "open")] = "os.open"


def _resource_label(call: ast.Call, aliases) -> str:
    path = resolve_call_path(call, aliases)
    return _CONSTRUCTORS.get(path, "")


@register
class ResourceLeak(Rule):
    name = "resource-leak"
    rationale = ("a file/mmap/socket/ClientSession closed only on the "
                 "happy path leaks the first time anything between "
                 "open and close raises; use with/async with or a "
                 "finally")
    scope = ("seaweedfs_tpu/",)
    fixture = (
        "import mmap\n"
        "import os\n"
        "def bad(p):\n"
        "    fh = open(p)\n"
        "    data = fh.read()\n"       # raises -> fh leaks
        "    fh.close()\n"
        "    return data\n"
        "def bad2(p):\n"
        "    open(p)\n"                # opened and dropped
        "def bad3(self, paths):\n"
        "    self._fds = [os.open(p, os.O_RDONLY) for p in paths]\n"
        # the reader-pool shape: a worker that maps its source then runs
        # fill jobs — any job raising leaks the map (happy-path close)
        "def bad4(fd, jobs):\n"
        "    mm = mmap.mmap(fd, 0, mmap.MAP_SHARED, mmap.PROT_READ)\n"
        "    for job in jobs:\n"
        "        job.fill(memoryview(mm))\n"
        "    mm.close()\n"
    )
    clean_fixture = (
        "import os\n"
        "def good(p):\n"
        "    with open(p) as fh:\n"
        "        return fh.read()\n"
        "def good2(p):\n"
        "    fh = open(p)\n"
        "    try:\n"
        "        return fh.read()\n"
        "    finally:\n"
        "        fh.close()\n"
        "def good3(self, p):\n"
        "    self._f = open(p)\n"      # lifecycle-managed elsewhere
        "def good4(p):\n"
        "    fh = open(p)\n"
        "    return fh\n"              # ownership transferred out
        "def good5(p, sink):\n"
        "    fh = open(p)\n"
        "    sink.adopt(fh)\n"         # ownership transferred
        # the reader pool's all-or-nothing fd open (ec/feed.py
        # ShardFeed/_DirectReader): append-in-loop with BaseException
        # cleanup is the sanctioned multi-open shape — no comprehension,
        # every already-opened fd closed before the raise propagates
        "def good6(self, paths):\n"
        "    fds = []\n"
        "    try:\n"
        "        for p in paths:\n"
        "            fds.append(os.open(p, os.O_RDONLY))\n"
        "    except BaseException:\n"
        "        for fd in fds:\n"
        "            os.close(fd)\n"
        "        raise\n"
        "    self._fds = fds\n"
    )

    def check_module(self, mod):
        aliases = mod.aliases()
        # the module body is a scope too (module-level opens), and each
        # function is visited exactly once — _check_scope never crosses
        # into nested defs, so nothing is reported twice
        yield from self._check_scope(mod, mod.tree, aliases)
        for fn in mod.walk():
            if not isinstance(fn, FUNC_DEFS):
                continue
            yield from self._check_scope(mod, fn, aliases)

    def _check_scope(self, mod, fn, aliases) -> Iterator:
        with_ctx_calls = set()
        for node in walk_body(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    with_ctx_calls.add(id(item.context_expr))

        # a close is error-safe only under a finally block
        finally_nodes = collect_finally_nodes(fn)

        # a resource constructor as a comprehension element: a failure
        # mid-comprehension leaks every handle already opened, and no
        # caller can ever close them (the container never materialized).
        # Stays within THIS scope — a comprehension inside a nested def
        # is reported when that def's own scope is visited
        for node in walk_body(fn):
            if not isinstance(node, (ast.ListComp, ast.SetComp,
                                     ast.GeneratorExp, ast.DictComp)):
                continue
            elts = ([node.key, node.value]
                    if isinstance(node, ast.DictComp) else [node.elt])
            for elt in elts:
                for sub in ast.walk(elt):
                    if not isinstance(sub, ast.Call):
                        continue
                    path = resolve_call_path(sub, aliases)
                    label = _COMPREHENSION_CONSTRUCTORS.get(path, "")
                    if label:
                        yield self.diag(
                            mod, sub.lineno,
                            f"{label}(...) inside a comprehension — if "
                            f"a later element raises, every handle "
                            f"already opened leaks with no reference "
                            f"to close; open in a loop with "
                            f"try/except cleanup")

        for node in walk_body(fn):
            if isinstance(node, ast.Expr) and \
                    isinstance(node.value, ast.Call):
                label = _resource_label(node.value, aliases)
                if label and id(node.value) not in with_ctx_calls:
                    yield self.diag(
                        mod, node.lineno,
                        f"{label}(...) opened and immediately dropped "
                        f"— the handle can never be closed")
            elif isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    len(node.targets) == 1:
                label = _resource_label(node.value, aliases)
                if not label:
                    continue
                target = node.targets[0]
                if not isinstance(target, ast.Name):
                    continue  # self.x / container slot: managed elsewhere
                yield from self._check_local(mod, fn, node, target.id,
                                             label, finally_nodes)

    def _check_local(self, mod, fn, assign, name: str, label: str,
                     finally_nodes) -> Iterator:
        verdict = classify_local_ownership(fn, name, finally_nodes)
        if verdict is None:
            return
        kind, close_line = verdict
        if kind == "unclosed":
            yield self.diag(
                mod, assign.lineno,
                f"{label}(...) assigned to '{name}' but never closed "
                f"in this scope — use with, or close in a finally")
        else:
            yield self.diag(
                mod, assign.lineno,
                f"{label}(...) assigned to '{name}' is closed only on "
                f"the happy path — an exception before "
                f"{name}.close() (line {close_line}) leaks it; "
                f"use with, or move the close into a finally")


def collect_finally_nodes(fn) -> set:
    """ids of every node running under a finally block in this scope —
    a close is error-safe only there."""
    out = set()
    for node in walk_body(fn):
        if isinstance(node, ast.Try):
            for stmt in node.finalbody:
                for n in ast.walk(stmt):
                    out.add(id(n))
    return out


def classify_local_ownership(fn, name: str, finally_nodes):
    """Escape analysis for a local holding a fresh close-needing
    handle. Returns None when the scope manages it (with/transfer/
    finally-close), ('unclosed', None) when nothing ever closes it, or
    ('happy-path', close_lineno) when the only closes can be skipped
    by an exception. Shared by resource-leak (direct constructors) and
    resource-leak-interproc (factory returns)."""
    closes: List[ast.AST] = []
    transferred = False
    in_with = False
    for n in ast.walk(fn):
        if isinstance(n, (ast.With, ast.AsyncWith)):
            for item in n.items:
                ce = item.context_expr
                if isinstance(ce, ast.Name) and ce.id == name:
                    in_with = True
        elif isinstance(n, ast.Call):
            f = n.func
            if isinstance(f, ast.Attribute) and \
                    f.attr in ("close", "detach", "release") and \
                    isinstance(f.value, ast.Name) and \
                    f.value.id == name:
                closes.append(n)
            # bare handle passed to another call: ownership moves
            for arg in list(n.args) + [k.value for k in n.keywords]:
                if isinstance(arg, ast.Name) and arg.id == name:
                    transferred = True
        elif isinstance(n, (ast.Return, ast.Yield, ast.YieldFrom)) \
                and isinstance(getattr(n, "value", None), ast.Name) \
                and n.value.id == name:
            transferred = True
        elif isinstance(n, ast.Assign):
            # stored into an attribute/subscript/tuple: managed
            # beyond this scope
            if isinstance(n.value, ast.Name) and n.value.id == name:
                transferred = True
        elif isinstance(n, ast.Await) and \
                isinstance(n.value, ast.Name) and \
                n.value.id == name:
            transferred = True
    if in_with or transferred:
        return None
    if not closes:
        return ("unclosed", None)
    if not any(id(c) in finally_nodes for c in closes):
        return ("happy-path", closes[0].lineno)
    return None
