"""Race detectors for the breaker/cache/lease-pool state mutexes:
holding a thread lock across a suspension point, and cross-file lock
acquisition-order cycles."""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from ..astutil import dotted, enclosing_class_map, walk_body
from ..engine import Rule, register

_LOCKISH = ("lock", "mutex")


def _lock_name(expr) -> str:
    """Normalized-ish dotted name when the expression looks like a lock
    ('' otherwise). A name is lock-ish when its last segment mentions
    lock/mutex — matches this codebase's naming (_lock, _shared_lock,
    klock, _vacuum_lock...)."""
    d = dotted(expr)
    if not d:
        return ""
    last = d.rsplit(".", 1)[-1].lower()
    if any(s in last for s in _LOCKISH):
        return d
    return ""


def _with_lock_items(node) -> List[Tuple[str, ast.AST]]:
    out = []
    for item in node.items:
        name = _lock_name(item.context_expr)
        if name:
            out.append((name, item.context_expr))
    return out


@register
class LockHeldAwait(Rule):
    name = "lock-held-await"
    rationale = ("awaiting while holding a threading lock parks the "
                 "mutex across a suspension point: every thread (and "
                 "any coroutine sharing the lock) blocks for the full "
                 "await — the never-held-across-network rule the lease "
                 "pool fought for")
    scope = ("seaweedfs_tpu/",)
    fixture = (
        "async def bad(self, session):\n"
        "    with self._lock:\n"
        "        await session.get('http://peer/refill')\n"
    )
    clean_fixture = (
        "async def good(self, session):\n"
        "    with self._lock:\n"
        "        state = dict(self._cache)\n"
        "    await session.get('http://peer/refill')\n"
        "async def also_good(self):\n"
        "    async with self._alock:\n"   # asyncio locks may span awaits
        "        await self._refresh()\n"
    )

    def check_module(self, mod):
        for fn in mod.walk():
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            for node in walk_body(fn):
                if not isinstance(node, ast.With):
                    continue
                locks = _with_lock_items(node)
                if not locks:
                    continue
                for inner in walk_body(node):
                    if isinstance(inner, (ast.Await, ast.AsyncFor,
                                          ast.AsyncWith)):
                        yield self.diag(
                            mod, node.lineno,
                            f"async def {fn.name} awaits at line "
                            f"{inner.lineno} while holding "
                            f"{locks[0][0]} (sync with) — a thread "
                            f"mutex held across a suspension point; "
                            f"copy state out, release, then await")
                        break


@register
class LockOrdering(Rule):
    name = "lock-ordering"
    rationale = ("two code paths that nest the same pair of locks in "
                 "opposite orders deadlock under load; acquisition "
                 "edges are collected tree-wide — lexical nestings AND "
                 "summarized edges through call chains (a helper that "
                 "takes lock B, called under lock A, is an A->B edge "
                 "even across module boundaries)")
    scope = ("seaweedfs_tpu/",)
    fixture = (
        "class A:\n"
        "    def one(self):\n"
        "        with self._map_lock:\n"
        "            with self._flush_lock:\n"
        "                pass\n"
        "    def two(self):\n"
        "        with self._flush_lock:\n"
        "            with self._map_lock:\n"
        "                pass\n"
        "class B:\n"
        # the call-mediated shape: one() nests j under i lexically;
        # two() reaches i while holding j only THROUGH _grab_i()
        "    def one(self):\n"
        "        with self._i_lock:\n"
        "            with self._j_lock:\n"
        "                pass\n"
        "    def _grab_i(self):\n"
        "        with self._i_lock:\n"
        "            pass\n"
        "    def two(self):\n"
        "        with self._j_lock:\n"
        "            self._grab_i()\n"
    )
    clean_fixture = (
        "class A:\n"
        "    def one(self):\n"
        "        with self._map_lock:\n"
        "            with self._flush_lock:\n"
        "                pass\n"
        "    def two(self):\n"
        "        with self._map_lock:\n"
        "            with self._flush_lock:\n"
        "                pass\n"
    )

    def _edges(self, mod) -> List[Tuple[str, str, int]]:
        """(outer_lock, inner_lock, lineno) for every lexically nested
        acquisition. Lock ids are class-qualified so A._lock and
        B._lock stay distinct across files."""
        classes = enclosing_class_map(mod.tree)
        edges: List[Tuple[str, str, int]] = []

        def qualify(name: str, node) -> str:
            # module-prefixed class qualification: two unrelated classes
            # both named Store in different files must NOT merge their
            # lock ids, or their unrelated nestings could fabricate a
            # deadlock cycle that cannot happen
            cls = classes.get(node, "")
            if name.startswith("self."):
                owner = f"{mod.relpath}:{cls}" if cls else mod.relpath
                return f"{owner}.{name[5:]}"
            return f"{mod.relpath}:{name}"

        def visit(node, held: List[str]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    visit(child, [])   # fresh hold-set per function
                    continue
                acquired = []
                if isinstance(child, (ast.With, ast.AsyncWith)):
                    for name, expr in _with_lock_items(child):
                        q = qualify(name, child)
                        for h in held:
                            if h != q:
                                edges.append((h, q, child.lineno))
                        acquired.append(q)
                visit(child, held + acquired)

        visit(mod.tree, [])
        return edges

    def check_project(self, mods):
        graph: Dict[str, set] = {}
        sites: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
        for mod in mods:
            for a, b, line in self._edges(mod):
                graph.setdefault(a, set()).add(b)
                sites.setdefault((a, b), (mod.relpath, line, ""))

        # v2: summarized acquisition edges — a call made while holding
        # lock A, to a function whose transitive closure acquires lock
        # B, is an A->B edge even when the nesting spans modules
        from .. import callgraph as cg
        cgraph = cg.get(mods)
        for summary in cgraph.functions.values():
            for site in summary.calls:
                if not site.held_locks:
                    continue
                for callee in site.callees:
                    for b, (bpath, bline, via) in \
                            cgraph.transitive_acquires(callee).items():
                        for a in site.held_locks:
                            if a == b:
                                continue
                            graph.setdefault(a, set()).add(b)
                            sites.setdefault(
                                (a, b),
                                (summary.mod.relpath, site.lineno,
                                 f" (via {via.split(':', 1)[-1]}, "
                                 f"which acquires {b} at "
                                 f"{bpath}:{bline})"))

        def reaches(src: str, dst: str) -> bool:
            seen, stack = set(), [src]
            while stack:
                n = stack.pop()
                if n == dst:
                    return True
                if n in seen:
                    continue
                seen.add(n)
                stack.extend(graph.get(n, ()))
            return False

        # an edge participates in a cycle when its head reaches back to
        # its tail; every such edge is a diagnostic (the graphs here are
        # tiny, BFS per edge is fine)
        by_path = {m.relpath: m for m in mods}
        for a in sorted(graph):
            for b in sorted(graph[a]):
                if not reaches(b, a):
                    continue
                path, line, via = sites[(a, b)]
                mod = by_path.get(path)
                if mod is None:
                    continue
                yield self.diag(
                    mod, line,
                    f"lock-order cycle: {a} -> {b} acquired here"
                    f"{via}, but another path acquires {b} before "
                    f"{a} — opposite nesting orders deadlock under "
                    f"load")
