"""atomic-replace: every temp-file-and-rename commit must carry the
full durability recipe (fsync file -> os.replace -> fsync directory).

PR 4's raft state writer carried all three barriers because a vanished
vote breaks election safety; the `.ecm`/`.vif`/offset/snapshot writers
each re-invented part of the dance and a power loss could revoke their
commits. The recipe now lives ONCE in ``utils/durable.py`` — this rule
holds every other ``os.replace`` in the tree to it, riding the PR 13
call graph so a helper that fsyncs on the caller's behalf (or a caller
that delegates to ``durable.*``) is recognized wherever it lives.

A finding fires at an ``os.replace`` call site whose enclosing function

  * cannot transitively reach an ``os.fsync``/``os.fdatasync`` (the
    temp file's pages may still be dirty when the rename lands:
    power loss surfaces an empty/partial file), or
  * reaches a file fsync but never a directory fsync (``durable``
    helper): the rename itself is revocable.

Deliberately loss-tolerant writers (e.g. the disk cache tier) carry an
inline ``# weedlint: disable=atomic-replace`` with their justification.
"""

from __future__ import annotations

import ast
from typing import Dict, Optional, Set

from .. import callgraph
from ..astutil import attr_path, walk_body
from ..engine import Rule, register

_DURABLE_HELPERS = ("fsync_dir", "replace_atomic", "write_atomic",
                    "write_json_atomic")
_DURABLE_MODULE = "seaweedfs_tpu/utils/durable.py"


def _canonical(mod, call: ast.Call) -> tuple:
    path = attr_path(call.func)
    if not path:
        return ()
    aliases = mod.aliases()
    head = aliases.get(path[0], path[0])
    return tuple(head.split(".")) + tuple(path[1:])


def _is_durable_call(mod, call: ast.Call) -> bool:
    """Name-level recognition of the durable helpers: resolution-free so
    it works on single-module fixture runs too."""
    path = _canonical(mod, call)
    return bool(path) and path[-1] in _DURABLE_HELPERS and (
        len(path) == 1 or "durable" in path[:-1]
        or path[-2:-1] == ("durable",))


@register
class AtomicReplace(Rule):
    name = "atomic-replace"
    rationale = ("an os.replace whose temp file was never fsynced — or "
                 "whose directory never is — commits state a power loss "
                 "can tear or revoke; route it through "
                 "utils/durable.py's fsync-file -> rename -> fsync-dir "
                 "recipe")
    scope = ("seaweedfs_tpu/",)
    fixture = (
        "import os, json\n"
        "def save_no_fsync(path, obj):\n"
        "    tmp = path + '.tmp'\n"
        "    with open(tmp, 'w') as f:\n"
        "        json.dump(obj, f)\n"
        "    os.replace(tmp, path)\n"          # no fsync at all
        "def _persist(f):\n"
        "    f.flush()\n"
        "    os.fsync(f.fileno())\n"
        "def save_no_dirsync(path, data):\n"
        "    tmp = path + '.tmp'\n"
        "    with open(tmp, 'wb') as f:\n"
        "        f.write(data)\n"
        "        _persist(f)\n"
        "    os.replace(tmp, path)\n"          # file synced, dir not
    )
    clean_fixture = (
        "import os\n"
        "from ..utils import durable\n"
        "def good(path, data):\n"
        "    durable.write_atomic(path, data)\n"
        "def good2(tmp, path, f):\n"
        "    os.fsync(f.fileno())\n"
        "    durable.replace_atomic(tmp, path, sync_file=False)\n"
        "def good3(tmp, path, f):\n"
        "    os.fsync(f.fileno())\n"
        "    os.replace(tmp, path)\n"
        "    durable.fsync_dir(os.path.dirname(path))\n"
    )

    def check_project(self, mods):
        graph = callgraph.get(mods)

        # transitive effect closures over the call graph, cycle-safe
        fsync_memo: Dict[str, bool] = {}
        durable_memo: Dict[str, bool] = {}

        def reaches(qname: str, memo: Dict[str, bool], probe,
                    stack: Optional[Set[str]] = None) -> bool:
            # positives memoize (definitive); negatives are re-derived —
            # a negative computed under a cycle would be provisional
            # (PR 13's cycle-taint discipline), and the tree has few
            # os.replace roots so the re-walk is cheap
            if memo.get(qname):
                return True
            if stack is None:
                stack = set()
            if qname in stack:
                return False
            summary = graph.functions.get(qname)
            if summary is None:
                return False
            if probe(summary):
                memo[qname] = True
                return True
            stack.add(qname)
            try:
                for site in summary.calls:
                    for callee in site.callees:
                        if reaches(callee, memo, probe, stack):
                            memo[qname] = True
                            return True
            finally:
                stack.discard(qname)
            return False

        def has_own_fsync(summary) -> bool:
            if any(label in ("os.fsync", "os.fdatasync")
                   for label, _ln in summary.blocking):
                return True
            return self._calls_durable(summary)

        def has_own_durable(summary) -> bool:
            return self._calls_durable(summary)

        for summary in graph.functions.values():
            if summary.mod.relpath == _DURABLE_MODULE:
                continue
            for node in walk_body(summary.node):
                if not isinstance(node, ast.Call):
                    continue
                if _canonical(summary.mod, node) != ("os", "replace"):
                    continue
                if not reaches(summary.qname, fsync_memo,
                               has_own_fsync):
                    yield self.diag(
                        summary.mod, node.lineno,
                        f"os.replace in {summary.node.name} commits a "
                        f"temp file that is never fsynced (transitively)"
                        f" — power loss can surface an empty/partial "
                        f"file; use utils/durable.replace_atomic")
                elif not reaches(summary.qname, durable_memo,
                                 has_own_durable):
                    yield self.diag(
                        summary.mod, node.lineno,
                        f"os.replace in {summary.node.name} fsyncs the "
                        f"file but never the directory — the rename "
                        f"itself is revocable by power loss; use "
                        f"utils/durable.replace_atomic (or fsync_dir)")

    @staticmethod
    def _calls_durable(summary) -> bool:
        for node in walk_body(summary.node):
            if isinstance(node, ast.Call) and \
                    _is_durable_call(summary.mod, node):
                return True
        return False
