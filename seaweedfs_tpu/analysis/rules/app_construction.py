"""Serving-surface construction guards: explicit body caps and no
unmetered HTTP surface. Ported from tests/test_async_guard.py's
overload-plane checks."""

from __future__ import annotations

import ast

from ..engine import Rule, register

# every file allowed to construct a web.Application; each must meter it
# through the overload admission middleware (fastpath listeners hook
# admission explicitly — they bypass aiohttp middleware entirely)
SERVING_SURFACES = (
    "seaweedfs_tpu/server/master.py",
    "seaweedfs_tpu/server/volume_server.py",
    "seaweedfs_tpu/server/filer_server.py",
    "seaweedfs_tpu/server/webdav_server.py",
    "seaweedfs_tpu/s3/s3_server.py",
    "seaweedfs_tpu/messaging/broker.py",
)


def _application_calls(tree: ast.Module):
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "Application":
            yield node


@register
class AppClientMaxSize(Rule):
    name = "app-client-max-size"
    rationale = ("aiohttp's silent 1 MiB default body cap bites exactly "
                 "once per forgotten surface; every Application() must "
                 "state its client_max_size")
    scope = ("seaweedfs_tpu/",)
    fixture = "app = web.Application(middlewares=[trace])\n"
    clean_fixture = ("app = web.Application(client_max_size=1,\n"
                     "    middlewares=[overload.admission_middleware(c)])\n")

    def check_module(self, mod):
        for call in _application_calls(mod.tree):
            if not any(kw.arg == "client_max_size"
                       for kw in call.keywords):
                yield self.diag(
                    mod, call.lineno,
                    "web.Application() without an explicit "
                    "client_max_size (aiohttp's silent 1 MiB default "
                    "caps non-streamed bodies)")


@register
class AppAdmissionMiddleware(Rule):
    name = "app-admission-middleware"
    rationale = ("an unguarded serving surface accepts unbounded load; "
                 "the surface list itself is completeness-checked so a "
                 "new Application() can't dodge the guard")
    scope = ("seaweedfs_tpu/",)
    # fixture pretends to live OUTSIDE the surface list -> flagged as an
    # unlisted surface
    fixture_relpath = "seaweedfs_tpu/server/_fixture.py"
    fixture = "app = web.Application(middlewares=[trace])\n"
    clean_fixture = "def helper():\n    return 1\n"  # no HTTP surface

    def check_project(self, mods):
        by_path = {m.relpath: m for m in mods}
        for mod in mods:
            if mod.relpath in SERVING_SURFACES:
                continue
            for call in _application_calls(mod.tree):
                yield self.diag(
                    mod, call.lineno,
                    "constructs a web.Application but is not listed in "
                    "SERVING_SURFACES (analysis/rules/app_construction"
                    ".py) — an unmetered HTTP surface")
        for rel in SERVING_SURFACES:
            mod = by_path.get(rel)
            if mod is None:
                continue  # not part of this run's path set
            calls = list(_application_calls(mod.tree))
            if not calls:
                yield self.diag(
                    mod, 1,
                    "listed in SERVING_SURFACES but constructs no "
                    "web.Application — stale surface list")
                continue
            for call in calls:
                mw = next((kw.value for kw in call.keywords
                           if kw.arg == "middlewares"), None)
                if mw is None or "admission_middleware" not in ast.dump(mw):
                    yield self.diag(
                        mod, call.lineno,
                        "web.Application() does not install "
                        "overload.admission_middleware — an unguarded "
                        "serving surface accepts unbounded load")
