"""Cross-file registry consistency: fault points and metric families.

Both registries fail silently when they drift — an unregistered fault
point quietly no-ops a chaos drill, and a metric name reused with a
different label set (or kind) splits one logical family into colliding
exposition groups."""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from ..astutil import attr_path, const_str
from ..engine import Rule, register

_FAULTS_MODULE = "seaweedfs_tpu/faults/__init__.py"
_FIRE_CALLS = ("fire", "fire_async", "corrupt", "set_fault")


def _known_points(faults_mod=None) -> frozenset:
    """The declared point set of the tree being ANALYZED: parsed from
    its faults module's KNOWN_POINTS literal when that file is in the
    run (so --root on a branch checkout judges against the branch's
    declarations), falling back to the running package's set for
    single-module fixture runs."""
    if faults_mod is not None:
        for node in ast.walk(faults_mod.tree):
            if not (isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "KNOWN_POINTS"
                    for t in node.targets)):
                continue
            call = node.value
            if isinstance(call, ast.Call) and call.args and \
                    isinstance(call.args[0], (ast.Set, ast.List,
                                              ast.Tuple)):
                points = [const_str(e) for e in call.args[0].elts]
                if all(p is not None for p in points):
                    return frozenset(points)
    from ...faults import KNOWN_POINTS
    return KNOWN_POINTS


def _fire_sites(mod) -> List[Tuple[str, int, str]]:
    """(point, lineno, call) for every faults.fire/fire_async/corrupt/
    set_fault with a literal point name in the module."""
    out = []
    for node in mod.walk():
        if not isinstance(node, ast.Call):
            continue
        path = attr_path(node.func)
        if not path or path[-1] not in _FIRE_CALLS:
            continue
        # require the faults module as receiver (faults.fire) or a
        # bare from-import (fire_async) — but NOT arbitrary .corrupt()
        if len(path) > 1 and path[-2] != "faults":
            continue
        if len(path) == 1 and path[0] == "corrupt":
            continue  # bare corrupt() is too generic to claim
        point = const_str(node.args[0]) if node.args else None
        if point is not None and not point.endswith("*"):
            out.append((point, node.lineno, path[-1]))
    return out


@register
class FaultPointRegistry(Rule):
    name = "fault-point-registry"
    rationale = ("faults.KNOWN_POINTS and the fire()/fire_async() call "
                 "sites must agree: an undeclared point is a typo that "
                 "no-ops a chaos drill, a declared point nothing fires "
                 "is dead chaos surface")
    scope = ("seaweedfs_tpu/",)
    fixture = (
        "from . import faults\n"
        "async def write(self):\n"
        "    await faults.fire_async('volume.wrlte')\n"  # typo
        "async def geo_apply(self):\n"
        "    await faults.fire_async('geo.aply')\n"      # typo
        "def geo_stream(self):\n"
        "    faults.fire('geo.straem')\n"                # typo
        "async def ring_hop(self):\n"
        "    await faults.fire_async('ring.proxi')\n"    # typo
        "async def balance_pass(self):\n"
        "    await faults.fire_async('master.balance.pln')\n"  # typo
        "def sim_beat(self):\n"
        "    faults.fire('sim.heartbeet')\n"             # typo
    )
    clean_fixture = (
        "from . import faults\n"
        "async def write(self):\n"
        "    await faults.fire_async('volume.write')\n"
        "async def geo_apply(self):\n"
        "    await faults.fire_async('geo.apply')\n"
        "def geo_stream(self):\n"
        "    faults.fire('geo.stream')\n"
        "async def ring_hop(self):\n"
        "    await faults.fire_async('ring.proxy')\n"
        "async def ring_handoff(self):\n"
        "    await faults.fire_async('ring.handoff')\n"
        "def log_apply(self):\n"
        "    faults.fire('master.log.apply')\n"
        "async def balance_pass(self):\n"
        "    await faults.fire_async('master.balance.plan')\n"
        "async def balance_move(self):\n"
        "    await faults.fire_async('master.balance.move')\n"
        "def sim_beat(self):\n"
        "    faults.fire('sim.heartbeat')\n"
    )

    def check_project(self, mods):
        faults_mod = next((m for m in mods
                           if m.relpath == _FAULTS_MODULE), None)
        known = _known_points(faults_mod)
        fired = {}
        for mod in mods:
            for point, lineno, call in _fire_sites(mod):
                fired.setdefault(point, []).append((mod, lineno, call))
        for point, sites in sorted(fired.items()):
            if point in known:
                continue
            for mod, lineno, call in sites:
                yield self.diag(
                    mod, lineno,
                    f"{call}({point!r}) names an undeclared fault "
                    f"point — typo, or add it to faults.KNOWN_POINTS "
                    f"so drills can arm it with confidence")
        # coverage direction only when the whole plane was analyzed
        servers_in_run = any(
            m.relpath.startswith("seaweedfs_tpu/server/") for m in mods)
        if faults_mod is None or not servers_in_run:
            return
        decl_line = 1
        for node in ast.walk(faults_mod.tree):
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "KNOWN_POINTS"
                    for t in node.targets):
                decl_line = node.lineno
        for point in sorted(known - set(fired)):
            yield self.diag(
                faults_mod, decl_line,
                f"declared fault point {point!r} is never fired "
                f"anywhere in the package — dead chaos surface that "
                f"drills believe in but nothing honors")


@register
class MetricLabelRegistry(Rule):
    name = "metric-label-registry"
    rationale = ("one metric name must mean one family: call sites "
                 "that disagree on label keys split the family, and "
                 "two names whose rendered samples collide (gauge "
                 "'x_count' vs histogram 'x') corrupt the exposition")
    scope = ("seaweedfs_tpu/",)
    fixture = (
        "def f(self):\n"
        "    self.metrics.count('reqs', labels={'cls': 'fg'})\n"
        "def g(self):\n"
        "    self.metrics.count('reqs')\n"   # same family, no labels
        "def h(self):\n"
        "    self.metrics.gauge('lat_count', 3)\n"
        "    self.metrics.observe('lat', 0.1)\n"  # renders lat_count too
    )
    clean_fixture = (
        "def f(self):\n"
        "    self.metrics.count('reqs', labels={'cls': 'fg'})\n"
        "def g(self):\n"
        "    self.metrics.count('reqs', labels={'cls': 'bg'})\n"
        "def h(self):\n"
        "    self.metrics.count('read')\n"   # renders read_total:
        "    with self.metrics.timed('read'):\n"  # no collision with
        "        pass\n"                          # read_bucket/sum/count
    )

    _KINDS = {"count": "counter", "gauge": "gauge",
              "observe": "histogram", "timed": "histogram"}

    @staticmethod
    def _rendered(name: str, kind: str) -> frozenset:
        """Sample names utils/metrics.py emits for a family — counters
        get _total, histograms explode to _bucket/_sum/_count."""
        if kind == "counter":
            return frozenset({f"{name}_total"})
        if kind == "histogram":
            return frozenset({f"{name}_bucket", f"{name}_sum",
                              f"{name}_count"})
        return frozenset({name})

    def _sites(self, mod):
        for node in mod.walk():
            if not isinstance(node, ast.Call) or \
                    not isinstance(node.func, ast.Attribute) or \
                    node.func.attr not in self._KINDS:
                continue
            recv = node.func.value
            recv_path = attr_path(recv)
            is_registry = (recv_path and recv_path[-1] == "metrics") or (
                isinstance(recv, ast.Call) and
                isinstance(recv.func, (ast.Name, ast.Attribute)) and
                (attr_path(recv.func) or ("",))[-1] == "shared")
            if not is_registry:
                continue
            name = const_str(node.args[0]) if node.args else None
            if name is None:
                continue
            labels = next((kw.value for kw in node.keywords
                           if kw.arg == "labels"), None)
            if labels is None:
                keyset: frozenset = frozenset()
            elif isinstance(labels, ast.Dict) and all(
                    const_str(k) is not None for k in labels.keys):
                keyset = frozenset(const_str(k) for k in labels.keys)
            else:
                continue  # dynamic labels: can't judge statically
            yield (name, self._KINDS[node.func.attr], keyset,
                   node.lineno)

    def check_project(self, mods):
        families: Dict[tuple, Dict[frozenset, list]] = {}
        for mod in mods:
            for name, kind, keyset, lineno in self._sites(mod):
                families.setdefault((name, kind), {}).setdefault(
                    keyset, []).append((mod, lineno))

        # 1) label-keyset drift within one (name, kind) family
        for (name, kind), variants in sorted(families.items()):
            if len(variants) == 1:
                continue
            ranked = sorted(variants.items(),
                            key=lambda kv: (-len(kv[1]), sorted(kv[0])))
            canon_keys = ranked[0][0]
            for keys, sites in ranked[1:]:
                for mod, lineno in sites:
                    yield self.diag(
                        mod, lineno,
                        f"metric {name!r} recorded with label keys "
                        f"{sorted(keys)} but the rest of the family "
                        f"uses {sorted(canon_keys)} — mixed label sets "
                        f"split one family into colliding exposition "
                        f"groups")

        # 2) rendered-sample collisions across different families
        # (counter 'x' renders x_total so it coexists with histogram
        # 'x'; gauge 'x_count' vs histogram 'x' does NOT)
        rendered: Dict[str, tuple] = {}
        for (name, kind) in sorted(families):
            for sample in sorted(self._rendered(name, kind)):
                prev = rendered.get(sample)
                if prev is not None and prev[:2] != (name, kind):
                    mod, lineno = next(
                        (m, ln) for v in families[(name, kind)].values()
                        for m, ln in v)
                    yield self.diag(
                        mod, lineno,
                        f"metric {name!r} ({kind}) renders sample "
                        f"{sample!r}, colliding with metric "
                        f"{prev[0]!r} ({prev[1]}) — the exposition "
                        f"merges two meanings under one sample name")
                else:
                    rendered[sample] = (name, kind)
