"""Fire-and-forget tasks: an asyncio task (or executor future) whose
reference is dropped is collectable mid-flight, its exception vanishes,
and shutdown can never cancel it."""

from __future__ import annotations

import ast

from ..astutil import FUNC_DEFS, walk_body
from ..engine import Rule, register

_SPAWNERS = ("create_task", "ensure_future", "run_in_executor")


def _spawner(call: ast.Call) -> str:
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr in _SPAWNERS:
        return f.attr
    if isinstance(f, ast.Name) and f.id in ("create_task",
                                            "ensure_future"):
        return f.id
    return ""


@register
class TaskLeak(Rule):
    name = "task-leak"
    rationale = ("a create_task/ensure_future/run_in_executor result "
                 "that nobody holds is GC-collectable mid-flight and "
                 "swallows its exception; keep a reference (and an "
                 "error path) or await it")
    scope = ("seaweedfs_tpu/",)
    fixture = (
        "async def bad(self):\n"
        "    asyncio.create_task(self._worker())\n"
        "async def bad2(self, loop):\n"
        "    t = loop.create_task(self._worker())\n"
    )
    clean_fixture = (
        "async def good(self):\n"
        "    self._task = asyncio.create_task(self._worker())\n"
        "async def good2(self):\n"
        "    t = asyncio.create_task(self._worker())\n"
        "    self._tasks.add(t)\n"
        "    t.add_done_callback(self._tasks.discard)\n"
        "async def good3(self, loop):\n"
        "    await loop.run_in_executor(None, self._sync)\n"
        "async def good4(self):\n"
        "    self._tasks.append(asyncio.create_task(self._worker()))\n"
    )

    def check_module(self, mod):
        for fn in mod.walk():
            if not isinstance(fn, FUNC_DEFS):
                continue
            yield from self._check_scope(mod, fn)

    def _check_scope(self, mod, fn):
        # names loaded anywhere in the function (incl. nested defs:
        # closures legitimately capture task handles)
        loads = {n.id for n in ast.walk(fn)
                 if isinstance(n, ast.Name)
                 and isinstance(n.ctx, ast.Load)}
        for node in walk_body(fn):
            if isinstance(node, ast.Expr) and \
                    isinstance(node.value, ast.Call):
                kind = _spawner(node.value)
                if kind:
                    yield self.diag(
                        mod, node.lineno,
                        f"{kind}(...) result discarded — the task is "
                        f"GC-collectable mid-flight and its exception "
                        f"vanishes; hold a reference and add an error "
                        f"callback (or await it)")
            elif isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                kind = _spawner(node.value)
                if kind and node.targets[0].id not in loads:
                    yield self.diag(
                        mod, node.lineno,
                        f"{kind}(...) assigned to "
                        f"'{node.targets[0].id}' which is never used — "
                        f"a write-only reference still loses the "
                        f"exception and cancellation path")
