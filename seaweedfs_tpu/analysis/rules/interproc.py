"""Inter-procedural rules: the weedlint v2 layer.

Every rule here is written against the call-graph/effect-summary plane
(:mod:`..callgraph`) instead of a single function's AST — the whole
point is that one level of indirection must not launder a blocking
call, a held lock, a dropped deadline budget, or an escaping handle.

Laundering via executor stays structural: a helper handed to
``run_in_executor`` is an *argument*, not a call expression, so it
never produces a call edge — only code that actually runs on the
loop/thread at hand is on a chain.
"""

from __future__ import annotations

import ast
from typing import Dict

from .. import callgraph
from ..astutil import walk_body
from ..engine import Rule, register
from .resources import classify_local_ownership, collect_finally_nodes


@register
class BlockingCallTransitive(Rule):
    name = "blocking-call-transitive"
    rationale = ("a coroutine that reaches os.fsync/time.sleep/"
                 "subprocess through ANY chain of ordinary calls stalls "
                 "its event loop exactly like a direct call — wrapping "
                 "the blocker in a helper must not launder it (only "
                 "run_in_executor does)")
    scope = ("seaweedfs_tpu/",)
    fixture = (
        "import os\n"
        "def _persist(fd):\n"
        "    os.fsync(fd)\n"
        "def _sync_all(fds):\n"
        "    for fd in fds:\n"
        "        _persist(fd)\n"
        "async def bad(self, fd):\n"
        "    _persist(fd)\n"           # depth 2: v1 cannot see this
        "async def bad2(self, fds):\n"
        "    _sync_all(fds)\n"         # depth 3
    )
    clean_fixture = (
        "import os\n"
        "def _persist(fd):\n"
        "    os.fsync(fd)\n"
        "async def good(self, loop, fd):\n"
        "    await loop.run_in_executor(None, _persist, fd)\n"
        "async def good2(self, loop, fd):\n"
        "    def _job():\n"
        "        _persist(fd)\n"
        "    await loop.run_in_executor(None, _job)\n"
        "def sync_path(fd):\n"
        "    _persist(fd)\n"           # sync callers may block freely
        # the no-loop fallback idiom: the RuntimeError handler of a
        # loop probe only ever runs when NO loop exists to stall
        "def schedule(self, fd):\n"
        "    import asyncio\n"
        "    try:\n"
        "        asyncio.ensure_future(self._flush())\n"
        "    except RuntimeError:\n"
        "        _persist(fd)\n"
        "async def caller(self, fd):\n"
        "    self.schedule(fd)\n"
    )

    def check_project(self, mods):
        graph = callgraph.get(mods)
        for summary in graph.functions.values():
            if not summary.is_async:
                continue
            for site in summary.calls:
                if site.off_loop:
                    continue
                for callee in site.callees:
                    chain = graph.blocking_chain(callee)
                    if chain is None:
                        continue
                    # depth-1 (a blocking primitive called directly in
                    # the coroutine) is async-blocking-call's finding;
                    # re-reporting it here would double every baseline
                    # fingerprint
                    yield self.diag(
                        summary.mod, site.lineno,
                        f"async def {summary.node.name} reaches "
                        f"{chain[-1][2]} on the event loop through "
                        f"{graph.render_chain(chain)} — move the "
                        f"blocking step into run_in_executor (no call "
                        f"chain launders it)")
                    break   # one finding per call site


@register
class LockHeldAwaitTransitive(Rule):
    name = "lock-held-await-transitive"
    rationale = ("holding a thread mutex across a call chain that "
                 "blocks (or across a generator's yield consumed under "
                 "awaits) parks every thread and coroutine sharing the "
                 "lock — the lock-held-await rule for effects one or "
                 "more calls away")
    scope = ("seaweedfs_tpu/",)
    fixture = (
        "import os\n"
        "def _persist(fd):\n"
        "    os.fsync(fd)\n"
        "async def bad(self, fd):\n"
        "    with self._lock:\n"
        "        _persist(fd)\n"       # mutex held across a disk flush
        "def _locked_items(self):\n"
        "    with self._lock:\n"
        "        yield from self._items\n"
        "async def bad2(self):\n"
        "    for x in _locked_items(self):\n"
        "        await self.process(x)\n"   # lock parked across awaits
    )
    clean_fixture = (
        "import os\n"
        "def _persist(fd):\n"
        "    os.fsync(fd)\n"
        "async def good(self, fd):\n"
        "    with self._lock:\n"
        "        state = dict(self._cache)\n"
        "    _persist_via_executor = None\n"
        "def _items(self):\n"
        "    with self._lock:\n"
        "        snapshot = list(self._items)\n"
        "    yield from snapshot\n"
        "async def good2(self):\n"
        "    for x in _items(self):\n"
        "        await self.process(x)\n"
    )

    def check_project(self, mods):
        graph = callgraph.get(mods)
        for summary in graph.functions.values():
            if not summary.is_async:
                continue
            # (a) a sync call made while holding a lock, whose chain
            #     blocks — the direct-await case is lock-held-await's
            for site in summary.calls:
                if not site.held_locks:
                    continue
                for callee in site.callees:
                    chain = graph.blocking_chain(callee)
                    if chain is None:
                        continue
                    yield self.diag(
                        summary.mod, site.lineno,
                        f"async def {summary.node.name} holds "
                        f"{site.held_locks[0]} across "
                        f"{graph.render_chain(chain)} reaching "
                        f"{chain[-1][2]} — the mutex is parked for the "
                        f"full blocking call; copy state out, release, "
                        f"then do the slow work")
                    break
            # (b) iterating a generator that yields while holding a
            #     lock, with awaits in the loop body: the generator
            #     parks its lock across every suspension of the
            #     consumer
            yield from self._check_locked_generators(graph, summary)

    def _check_locked_generators(self, graph, summary):
        for node in ast.walk(summary.node):
            if not isinstance(node, (ast.For, ast.AsyncFor)):
                continue
            it = node.iter
            if not isinstance(it, ast.Call):
                continue
            callees = graph.call_resolutions.get(id(it), ())
            for callee in callees:
                gen = graph.functions.get(callee)
                if gen is None or not gen.yields_holding:
                    continue
                has_await = any(
                    isinstance(n, (ast.Await, ast.AsyncFor,
                                   ast.AsyncWith))
                    for stmt in node.body for n in ast.walk(stmt))
                if not has_await:
                    continue
                yield self.diag(
                    summary.mod, node.lineno,
                    f"async def {summary.node.name} awaits inside a "
                    f"loop over {gen.qname.split(':', 1)[-1]}(), which "
                    f"yields while holding {gen.yields_holding[0]} — "
                    f"the generator parks the lock across every await "
                    f"of the consumer; snapshot under the lock, yield "
                    f"outside it")


# serving planes where a dropped deadline budget is a real bug: these
# modules run under the trace middleware's bound budget (or are called
# from code that does). shell/cli/integrations are interactive entry
# points that START budgets instead of inheriting them.
_DEADLINE_PLANES = (
    "seaweedfs_tpu/server/", "seaweedfs_tpu/filer/",
    "seaweedfs_tpu/storage/", "seaweedfs_tpu/replication/",
    "seaweedfs_tpu/messaging/", "seaweedfs_tpu/mount/",
    "seaweedfs_tpu/geo/", "seaweedfs_tpu/metaring/",
    "seaweedfs_tpu/notification/", "seaweedfs_tpu/cluster/",
    "seaweedfs_tpu/topology/", "seaweedfs_tpu/ec/",
    "seaweedfs_tpu/cache/", "seaweedfs_tpu/s3/",
)


@register
class DeadlinePropagation(Rule):
    name = "deadline-propagation"
    rationale = ("an outbound hop that neither forwards X-Seaweed-"
                 "Deadline (retry.inject_deadline) nor caps its socket "
                 "timeout by the remaining budget (retry.cap_timeout) "
                 "lets one slow peer spend time the caller no longer "
                 "has — the budget dies at that hop and every "
                 "downstream retry is wasted work")
    scope = ("seaweedfs_tpu/",)
    fixture = (
        "import urllib.request\n"
        "def _post(url, headers):\n"
        "    req = urllib.request.Request(url, headers=headers)\n"
        "    return urllib.request.urlopen(req, timeout=5)\n"
        "def bad_helper_caller(self, url):\n"
        "    return _post(url, {'X-Thing': '1'})\n"   # budget dropped here
        "def bad_direct(url):\n"
        "    return urllib.request.urlopen(url, timeout=5)\n"
    )
    clean_fixture = (
        "import urllib.request\n"
        "from ..utils import retry\n"
        "def _post(url, headers):\n"
        "    req = urllib.request.Request(\n"
        "        url, headers=retry.inject_deadline(dict(headers)))\n"
        "    return urllib.request.urlopen(req, timeout=5)\n"
        "def good_caller(self, url):\n"
        "    return _post(url, {'X-Thing': '1'})\n"
        "def good_external(url, timeout):\n"
        "    return urllib.request.urlopen(\n"
        "        url, timeout=retry.cap_timeout(timeout))\n"
    )

    def check_project(self, mods):
        graph = callgraph.get(mods)
        for summary in graph.functions.values():
            if not summary.mod.relpath.startswith(_DEADLINE_PLANES) or \
                    not summary.raw_outbound or summary.launders_deadline:
                continue
            if summary.headers_delegated:
                # the helper forwards caller-built headers: every
                # resolved caller that doesn't launder the budget owns
                # the finding (the one-level-of-indirection case)
                callers = graph.callers.get(summary.qname, ())
                flagged_any = False
                for caller_q, lineno in callers:
                    caller = graph.functions.get(caller_q)
                    if caller is None or caller.launders_deadline or \
                            not caller.mod.relpath.startswith(
                                _DEADLINE_PLANES):
                        continue
                    flagged_any = True
                    yield self.diag(
                        caller.mod, lineno,
                        f"{caller.node.name} sends headers through "
                        f"{summary.node.name} -> urlopen without the "
                        f"deadline budget — wrap them in retry."
                        f"inject_deadline(...) (or cap the timeout "
                        f"with retry.cap_timeout) so X-Seaweed-"
                        f"Deadline survives the hop")
                if flagged_any or callers:
                    continue
            for lineno in summary.raw_outbound:
                yield self.diag(
                    summary.mod, lineno,
                    f"{summary.node.name} makes a raw outbound "
                    f"request that drops the deadline budget — "
                    f"inject X-Seaweed-Deadline via retry."
                    f"inject_deadline(headers) for intra-cluster "
                    f"hops, or bound the socket with timeout="
                    f"retry.cap_timeout(...) for external endpoints")


@register
class ResourceLeakInterproc(Rule):
    name = "resource-leak-interproc"
    rationale = ("a function that returns a fresh file/mmap/socket/"
                 "session is a constructor: a caller that neither "
                 "closes, transfers, nor `with`s the result leaks it — "
                 "the resource-leak rule applied across the call edge "
                 "the v1 rule had to trust blindly ('ownership "
                 "transferred out')")
    scope = ("seaweedfs_tpu/",)
    fixture = (
        "def open_index(p):\n"
        "    return open(p, 'rb')\n"
        "def open_index_checked(p):\n"
        "    fh = open(p, 'rb')\n"
        "    return fh\n"
        "def bad(p):\n"
        "    fh = open_index(p)\n"
        "    data = fh.read()\n"       # raises -> fh leaks
        "    fh.close()\n"
        "    return data\n"
        "def bad2(p):\n"
        "    open_index_checked(p)\n"  # constructed and dropped
    )
    clean_fixture = (
        "def open_index(p):\n"
        "    return open(p, 'rb')\n"
        "def good(p):\n"
        "    with open_index(p) as fh:\n"
        "        return fh.read()\n"
        "def good2(p):\n"
        "    fh = open_index(p)\n"
        "    try:\n"
        "        return fh.read()\n"
        "    finally:\n"
        "        fh.close()\n"
        "def good3(p):\n"
        "    return open_index(p)\n"   # still a constructor: callers own
        "def good4(self, p):\n"
        "    self._fh = open_index(p)\n"   # lifecycle-managed elsewhere
    )

    def check_project(self, mods):
        graph = callgraph.get(mods)
        factories: Dict[str, str] = {}
        for qname in graph.functions:
            label = graph.resource_label(qname)
            if label:
                factories[qname] = label

        for summary in graph.functions.values():
            fn = summary.node
            finally_nodes = None
            for node in walk_body(fn):
                call, target = self._factory_site(node, graph, factories)
                if call is None:
                    continue
                label = factories[
                    graph.call_resolutions[id(call)][0]]
                short = (graph.call_resolutions[id(call)][0]
                         .split(":", 1)[-1])
                if target is None:
                    yield self.diag(
                        summary.mod, node.lineno,
                        f"{short}(...) returns a fresh {label} that is "
                        f"immediately dropped — the handle can never "
                        f"be closed")
                    continue
                if finally_nodes is None:
                    finally_nodes = collect_finally_nodes(fn)
                verdict = classify_local_ownership(fn, target,
                                                   finally_nodes)
                if verdict is None:
                    continue
                kind, close_line = verdict
                if kind == "unclosed":
                    yield self.diag(
                        summary.mod, node.lineno,
                        f"{short}(...) returns a fresh {label} "
                        f"assigned to '{target}' but never closed in "
                        f"this scope — use with, or close in a "
                        f"finally")
                else:
                    yield self.diag(
                        summary.mod, node.lineno,
                        f"{short}(...) returns a fresh {label} "
                        f"assigned to '{target}' closed only on the "
                        f"happy path — an exception before "
                        f"{target}.close() (line {close_line}) leaks "
                        f"it; use with, or move the close into a "
                        f"finally")

    @staticmethod
    def _factory_site(node, graph, factories):
        """(call, local_name|None) when this statement materializes a
        factory result: Expr-dropped (None target) or single-Name
        assignment. Returns (None, None) otherwise."""
        if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
            callees = graph.call_resolutions.get(id(node.value), ())
            if callees and callees[0] in factories:
                return node.value, None
        elif isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call) and \
                len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            callees = graph.call_resolutions.get(id(node.value), ())
            if callees and callees[0] in factories:
                return node.value, node.targets[0].id
        return None, None
