"""Shared AST plumbing for weedlint rules: dotted-name resolution,
import-alias maps, and body walks that respect nested-def boundaries
(the run_in_executor pattern makes "lexically inside this coroutine,
excluding nested defs" the scope almost every async rule wants)."""

from __future__ import annotations

import ast
from typing import Dict, Iterator, Optional, Tuple

FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)
NESTED_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def attr_path(node) -> Tuple[str, ...]:
    """Name/Attribute chain -> ('urllib', 'request', 'urlopen');
    () when the expression isn't a plain dotted name."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return ()


def dotted(node) -> str:
    return ".".join(attr_path(node))


def import_aliases(tree: ast.Module) -> Dict[str, str]:
    """alias -> canonical dotted prefix, covering ``import a.b as c``
    and ``from a import b [as c]`` (absolute imports only)."""
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module and \
                node.level == 0:
            for a in node.names:
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def resolve_call_path(node: ast.Call,
                      aliases: Dict[str, str]) -> Tuple[str, ...]:
    """The callee's canonical dotted path after alias expansion, e.g.
    ``ur.urlopen`` -> ('urllib', 'request', 'urlopen') under
    ``import urllib.request as ur``."""
    path = attr_path(node.func)
    if not path:
        return ()
    head = aliases.get(path[0])
    if head is not None:
        path = tuple(head.split(".")) + path[1:]
    return path


def walk_body(node, *, into_nested_defs: bool = False) -> Iterator[ast.AST]:
    """Walk every node lexically inside ``node``'s body. By default does
    NOT descend into nested function definitions or lambdas: a sync def
    nested in a coroutine is an executor body, off-loop by design."""
    stack = list(getattr(node, "body", []))
    for extra in ("orelse", "finalbody", "handlers"):
        stack.extend(getattr(node, extra, []))
    while stack:
        n = stack.pop()
        yield n
        if not into_nested_defs and isinstance(n, NESTED_SCOPES):
            continue
        stack.extend(ast.iter_child_nodes(n))


def enclosing_class_map(tree: ast.Module) -> Dict[ast.AST, str]:
    """function/With node -> name of the nearest enclosing ClassDef
    ('' at module level). Cheap parent walk, computed once per module."""
    out: Dict[ast.AST, str] = {}

    def visit(node, cls: str):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                visit(child, child.name)
            else:
                out[child] = cls
                visit(child, cls)

    visit(tree, "")
    return out


def const_str(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None
