"""weedlint CLI.

    python -m seaweedfs_tpu.analysis [options] PATH [PATH...]

Exit codes: 0 clean (no unsuppressed, un-baselined findings and no
stale baseline entries), 1 findings, 2 usage/internal error.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

from . import Baseline, registry, run


def _repo_root() -> str:
    """The directory containing the seaweedfs_tpu package: relpaths
    (and therefore baseline fingerprints) anchor here so invocation cwd
    doesn't matter."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m seaweedfs_tpu.analysis",
        description="weedlint: static analysis for the async storage "
                    "plane")
    ap.add_argument("paths", nargs="*", metavar="PATH",
                    help="files or directories to analyze")
    ap.add_argument("--baseline", metavar="FILE",
                    help="JSON baseline of grandfathered findings; new "
                         "findings and stale entries both fail")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite --baseline with the current finding "
                         "set (exits 0)")
    ap.add_argument("--rules", metavar="R1,R2",
                    help="comma-separated subset of rules to run")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--show-baselined", action="store_true",
                    help="also print findings matched by the baseline")
    ap.add_argument("--root", default="",
                    help="repo root for relative paths (default: the "
                         "package parent)")
    ap.add_argument("--jobs", type=int, default=1, metavar="N",
                    help="parse files on an N-process pool (findings "
                         "and fingerprints are identical to -j1)")
    ap.add_argument("--format", choices=("text", "github"),
                    default="text",
                    help="'github' emits ::error annotation lines for "
                         "CI in addition to the summary")
    args = ap.parse_args(argv)

    if args.list_rules:
        rules = registry()
        width = max(len(n) for n in rules)
        for name in sorted(rules):
            print(f"{name:<{width}}  {rules[name].rationale}")
        return 0

    if not args.paths:
        ap.error("no paths given (try: seaweedfs_tpu/ tests/)")
    if args.write_baseline and not args.baseline:
        ap.error("--write-baseline requires --baseline")

    root = os.path.abspath(args.root) if args.root else _repo_root()
    rule_names = ([r.strip() for r in args.rules.split(",") if r.strip()]
                  if args.rules else None)
    baseline = Baseline.load(args.baseline) if args.baseline else None

    t0 = time.perf_counter()
    try:
        report = run(root, args.paths, rule_names=rule_names,
                     baseline=baseline, jobs=max(1, args.jobs))
    except ValueError as e:
        print(f"weedlint: {e}", file=sys.stderr)
        return 2
    wall = time.perf_counter() - t0

    if report.files_checked == 0:
        # a typo'd path (or wrong cwd) must not read as a passing gate
        print(f"weedlint: no .py files found under "
              f"{' '.join(args.paths)} — nothing was linted",
              file=sys.stderr)
        return 2

    if args.write_baseline:
        findings = report.new + report.baselined
        broken = [d for d in findings if d.rule == "parse-error"]
        if broken:
            # a syntax-broken file can never be grandfathered
            for d in sorted(broken, key=lambda d: d.path):
                print(d.render(), file=sys.stderr)
            print("weedlint: refusing to write a baseline over "
                  f"{len(broken)} parse error(s)", file=sys.stderr)
            return 1
        merged = Baseline.from_findings(findings)
        # a partial rewrite (--rules subset, one directory) must only
        # replace entries it actually re-judged: everything outside
        # this run's rule/path scope is preserved verbatim, or a
        # routine subset run would silently erase the rest of the
        # grandfather list and fail the next full CI pass
        preserved = 0
        if baseline is not None:
            for fp, entry in baseline.entries.items():
                if fp in merged.entries:
                    continue
                if entry.get("rule") not in report.rules_run or \
                        not report.covers(entry.get("path", "")):
                    merged.entries[fp] = entry
                    preserved += 1
        merged.write(args.baseline)
        print(f"weedlint: wrote {len(merged.entries)} entries to "
              f"{args.baseline}"
              + (f" ({preserved} out-of-scope preserved)"
                 if preserved else ""))
        return 0

    if args.format == "github":
        # one workflow-command annotation per actionable line; GitHub
        # reads these off stdout and pins them to the diff view

        def esc(s: str) -> str:
            return (s.replace("%", "%25").replace("\r", "%0D")
                    .replace("\n", "%0A"))

        for d in sorted(report.new,
                        key=lambda d: (d.path, d.line, d.rule)):
            print(f"::error file={esc(d.path)},line={d.line},"
                  f"title=weedlint {esc(d.rule)}::{esc(d.message)}")
        for e in sorted(report.stale_baseline,
                        key=lambda e: (e["rule"], e["path"], e["line"])):
            print(f"::error file={esc(e['path'])},line={e['line']},"
                  f"title=weedlint stale-baseline::stale baseline "
                  f"entry {e['fp']} ([{esc(e['rule'])}]) no longer "
                  f"matches any finding — remove it")
    out = report.render(show_baselined=args.show_baselined)
    if out:
        print(out)
    status = "clean" if report.clean else (
        f"{len(report.new)} finding(s)"
        + (f", {len(report.stale_baseline)} stale baseline entr"
           f"{'y' if len(report.stale_baseline) == 1 else 'ies'}"
           if report.stale_baseline else ""))
    print(f"weedlint: {report.files_checked} files, "
          f"{len(report.suppressed)} suppressed, "
          f"{len(report.baselined)} baselined, {status} "
          f"({wall:.2f}s)")
    return 0 if report.clean else 1


if __name__ == "__main__":
    sys.exit(main())
