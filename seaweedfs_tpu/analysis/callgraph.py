"""weedlint v2: tree-wide call graph + per-function effect summaries.

PR 8's fifteen rules are single-function AST matchers — a helper that
calls ``os.fsync`` goes invisible the moment it's wrapped in one level
of indirection. This module gives rules the inter-procedural layer:

  * a qualified-name index of every function/method in the analyzed
    tree (``relpath:func``, ``relpath:Class.method``, nested defs as
    ``relpath:outer.<locals>.inner``);
  * call-edge resolution good enough for a cohesive package — local
    names, absolute AND relative imports, ``self.``/``cls.`` methods
    through resolvable base classes;
  * a :class:`FunctionSummary` of the effects rules care about: calls
    a blocking primitive, acquires/releases which locks, awaits,
    spawns tasks, makes raw outbound HTTP, launders the deadline
    budget, returns an open resource, yields while holding a lock;
  * memoized transitive closures over the summary graph (blocking
    chains, summarized lock acquisitions, resource-returning factories)
    so every rule pays for the graph once.

Resolution is deliberately conservative: an edge exists only when the
callee is a plain dotted name the index can pin to one definition.
Unresolvable receivers (``obj.method()`` on a value of unknown type)
produce no edge — inter-procedural rules must prefer silence over a
fabricated chain.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from .astutil import attr_path, const_str
from .engine import Module

__all__ = [
    "BLOCKING_PRIMITIVES", "RESOURCE_CONSTRUCTORS", "CallGraph",
    "CallSite", "FunctionSummary", "get",
]

# (module, attr) pairs that block the calling thread — shared with the
# async_hygiene rule so intra- and inter-procedural views can't drift
BLOCKING_PRIMITIVES = {
    ("os", "fsync"): "use run_in_executor",
    ("os", "fdatasync"): "use run_in_executor",
    ("time", "sleep"): "use asyncio.sleep (or run_in_executor)",
    ("subprocess", "run"): "use asyncio.create_subprocess_exec",
    ("subprocess", "check_output"): "use asyncio.create_subprocess_exec",
    ("subprocess", "check_call"): "use asyncio.create_subprocess_exec",
}

# close-needing constructors — shared with the resources rule
RESOURCE_CONSTRUCTORS = {
    ("open",): "open",
    ("os", "fdopen"): "os.fdopen",
    ("mmap", "mmap"): "mmap.mmap",
    ("socket", "socket"): "socket.socket",
    ("aiohttp", "ClientSession"): "aiohttp.ClientSession",
}

_LOCKISH = ("lock", "mutex")
_SPAWNERS = ("create_task", "ensure_future", "run_in_executor")
_DEADLINE_LAUNDERERS = ("inject_deadline", "cap_timeout")
_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)


def module_dotted(relpath: str) -> str:
    """'seaweedfs_tpu/ec/feed.py' -> 'seaweedfs_tpu.ec.feed';
    package __init__ maps to the package itself."""
    parts = relpath[:-3].split("/")  # drop .py
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


_LOOP_PROBES = ("ensure_future", "get_running_loop", "get_event_loop",
                "create_task", "run_coroutine_threadsafe")


def _probes_loop(try_node: ast.Try) -> bool:
    """Does this try's body attempt event-loop access? If so, its
    ``except RuntimeError`` handlers are the no-running-loop fallback
    and execute off-loop by construction."""
    for stmt in try_node.body:
        for n in ast.walk(stmt):
            if isinstance(n, ast.Call):
                path = attr_path(n.func)
                if path and path[-1] in _LOOP_PROBES:
                    return True
    return False


def _catches_runtime_error(handler: ast.excepthandler) -> bool:
    t = handler.type
    names = []
    if isinstance(t, (ast.Name, ast.Attribute)):
        names = [attr_path(t)[-1:]]
    elif isinstance(t, ast.Tuple):
        names = [attr_path(e)[-1:] for e in t.elts]
    return any(n == ("RuntimeError",) for n in names)


def _lock_name(expr) -> str:
    """Dotted name when the expression looks like a lock (same notion
    the locks rule uses: last segment mentions lock/mutex)."""
    path = attr_path(expr)
    if not path:
        return ""
    last = path[-1].lower()
    if any(s in last for s in _LOCKISH):
        return ".".join(path)
    return ""


@dataclass(frozen=True)
class CallSite:
    lineno: int
    name: str                       # display name of the callee expr
    callees: Tuple[str, ...]        # resolved qnames ((), when unknown)
    held_locks: Tuple[str, ...]     # lock ids lexically held here
    # inside an ``except RuntimeError:`` whose try body probed the
    # event loop (ensure_future/get_running_loop/...): that handler
    # only runs when NO loop is running, so blocking there cannot
    # stall one — the no-loop-fallback idiom must not taint chains
    off_loop: bool = False


@dataclass
class FunctionSummary:
    qname: str
    mod: Module
    node: ast.AST
    is_async: bool
    cls: str = ""
    params: Tuple[str, ...] = ()
    # --- direct effects, this function's own body only (nested defs
    # and lambdas are deferred execution: their own summaries carry
    # their own effects) ---
    blocking: List[Tuple[str, int]] = field(default_factory=list)
    acquires: List[Tuple[str, int]] = field(default_factory=list)
    has_await: bool = False
    spawns: List[int] = field(default_factory=list)
    yields_holding: Tuple[str, ...] = ()
    raw_outbound: List[int] = field(default_factory=list)
    launders_deadline: bool = False
    headers_delegated: bool = False   # raw outbound headers come from a param
    returns_resource: str = ""        # constructor label returned directly
    returns_calls: Tuple[str, ...] = ()  # qnames whose result is returned
    calls: List[CallSite] = field(default_factory=list)


class CallGraph:
    """Index + summaries + memoized transitive queries over one module
    set. Build once per run via :func:`get`."""

    def __init__(self, mods: Sequence[Module]):
        self.mods = list(mods)
        self.functions: Dict[str, FunctionSummary] = {}
        # python dotted module name -> Module
        self.modules: Dict[str, Module] = {}
        # (relpath, ClassName) -> {method -> qname}; plus base exprs
        self._class_methods: Dict[Tuple[str, str], Dict[str, str]] = {}
        self._class_bases: Dict[Tuple[str, str], List[Tuple[str, ...]]] = {}
        self._imports: Dict[str, Dict[str, str]] = {}   # relpath -> alias map
        # id(ast.Call) -> resolved callee qnames, so rules doing their
        # own walks can look resolutions up without re-deriving context
        self.call_resolutions: Dict[int, Tuple[str, ...]] = {}
        # reverse edges: callee qname -> [(caller qname, lineno)]
        self.callers: Dict[str, List[Tuple[str, int]]] = {}
        # memo tables
        self._blocking_chain_memo: Dict[str, Optional[Tuple]] = {}
        self._acq_memo: Dict[str, Dict[str, Tuple]] = {}
        self._resource_memo: Dict[str, str] = {}

        for mod in self.mods:
            self.modules[module_dotted(mod.relpath)] = mod
        for mod in self.mods:
            self._index_module(mod)
        for mod in self.mods:
            self._summarize_module(mod)
        for s in self.functions.values():
            for site in s.calls:
                for callee in site.callees:
                    self.callers.setdefault(callee, []).append(
                        (s.qname, site.lineno))

    # ------------------------------------------------------ indexing

    def _index_module(self, mod: Module) -> None:
        self._imports[mod.relpath] = self._module_imports(mod)

        def index_scope(parent, prefix: str, cls: str) -> None:
            for child in ast.iter_child_nodes(parent):
                if isinstance(child, ast.ClassDef):
                    key = (mod.relpath, child.name)
                    self._class_methods.setdefault(key, {})
                    self._class_bases[key] = [
                        attr_path(b) for b in child.bases if attr_path(b)]
                    index_scope(child, f"{child.name}.", child.name)
                elif isinstance(child, _FUNC_DEFS):
                    qname = f"{mod.relpath}:{prefix}{child.name}"
                    self.functions[qname] = FunctionSummary(
                        qname=qname, mod=mod, node=child,
                        is_async=isinstance(child, ast.AsyncFunctionDef),
                        cls=cls,
                        params=tuple(
                            a.arg for a in (child.args.posonlyargs
                                            + child.args.args
                                            + child.args.kwonlyargs)))
                    if cls and prefix == f"{cls}.":
                        self._class_methods[(mod.relpath, cls)][
                            child.name] = qname
                    # nested defs: indexable so local-name calls resolve
                    index_scope(child, f"{prefix}{child.name}.<locals>.",
                                cls)
                else:
                    index_scope(child, prefix, cls)

        index_scope(mod.tree, "", "")

    def _module_imports(self, mod: Module) -> Dict[str, str]:
        """alias -> canonical dotted target, including RELATIVE imports
        (astutil.import_aliases covers absolute only — most intra-
        package edges here ride ``from ..utils import retry``)."""
        pkg_parts = module_dotted(mod.relpath).split(".")
        is_pkg = mod.relpath.endswith("/__init__.py")
        aliases: Dict[str, str] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                if node.level == 0:
                    base = node.module or ""
                else:
                    # level 1 = this module's package, each extra level
                    # one package up
                    drop = node.level - (1 if is_pkg else 0)
                    kept = pkg_parts[:len(pkg_parts) - drop]
                    if not kept:
                        continue
                    base = ".".join(kept)
                    if node.module:
                        base = f"{base}.{node.module}"
                if not base:
                    continue
                for a in node.names:
                    aliases[a.asname or a.name] = f"{base}.{a.name}"
        return aliases

    # ---------------------------------------------------- resolution

    def _resolve_dotted(self, dotted: Tuple[str, ...]) -> Tuple[str, ...]:
        """Canonical dotted path -> qnames. Handles module.func,
        module.Class.method and package.module chains by longest-prefix
        module match."""
        for cut in range(len(dotted) - 1, 0, -1):
            mod_name = ".".join(dotted[:cut])
            mod = self.modules.get(mod_name)
            if mod is None:
                continue
            rest = dotted[cut:]
            if len(rest) == 1:
                q = f"{mod.relpath}:{rest[0]}"
                if q in self.functions:
                    return (q,)
                # re-export: the name may be an alias inside that module
                target = self._imports.get(mod.relpath, {}).get(rest[0])
                if target:
                    return self._resolve_dotted(tuple(target.split(".")))
            elif len(rest) == 2:
                q = self._method_qname(mod.relpath, rest[0], rest[1])
                if q:
                    return (q,)
            return ()
        return ()

    def _method_qname(self, relpath: str, cls: str, meth: str,
                      _seen=None) -> Optional[str]:
        """Class method lookup through resolvable base classes."""
        _seen = _seen or set()
        key = (relpath, cls)
        if key in _seen:
            return None
        _seen.add(key)
        methods = self._class_methods.get(key)
        if methods is None:
            return None
        if meth in methods:
            return methods[meth]
        imports = self._imports.get(relpath, {})
        for base in self._class_bases.get(key, ()):
            # base may be a local class or an imported one
            head = imports.get(base[0])
            dotted = (tuple(head.split(".")) + base[1:]) if head else base
            if len(dotted) == 1:
                q = self._method_qname(relpath, dotted[0], meth, _seen)
                if q:
                    return q
                continue
            for cut in range(len(dotted) - 1, 0, -1):
                m = self.modules.get(".".join(dotted[:cut]))
                if m is not None and len(dotted) - cut == 1:
                    q = self._method_qname(m.relpath, dotted[cut], meth,
                                           _seen)
                    if q:
                        return q
                    break
        return None

    def _resolve_call(self, mod: Module, call: ast.Call, cls: str,
                      local_defs: Dict[str, str]) -> Tuple[str, ...]:
        path = attr_path(call.func)
        if not path:
            return ()
        if len(path) == 1:
            name = path[0]
            if name in local_defs:
                return (local_defs[name],)
            q = f"{mod.relpath}:{name}"
            if q in self.functions:
                return (q,)
            target = self._imports.get(mod.relpath, {}).get(name)
            if target:
                return self._resolve_dotted(tuple(target.split(".")))
            return ()
        if path[0] in ("self", "cls") and cls:
            if len(path) == 2:
                q = self._method_qname(mod.relpath, cls, path[1])
                return (q,) if q else ()
            return ()
        head = self._imports.get(mod.relpath, {}).get(path[0])
        if head:
            return self._resolve_dotted(tuple(head.split(".")) + path[1:])
        if len(path) == 2 and (mod.relpath, path[0]) in \
                self._class_methods:
            # Class.method(...) on a class defined in this module
            q = self._method_qname(mod.relpath, path[0], path[1])
            return (q,) if q else ()
        # anything else (obj.method() on an unknown receiver) produces
        # no edge by design: silence over fabricated chains
        return ()

    # --------------------------------------------------- summarizing

    def _summarize_module(self, mod: Module) -> None:
        aliases = mod.aliases()

        classes: Dict[ast.AST, str] = {}

        def tag_classes(node, cls):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    tag_classes(child, child.name)
                else:
                    classes[child] = cls
                    tag_classes(child, cls)

        tag_classes(mod.tree, "")

        for qname, summary in list(self.functions.items()):
            if summary.mod is not mod:
                continue
            self._summarize_function(summary, aliases)

    def _summarize_function(self, s: FunctionSummary, aliases) -> None:
        mod, fn = s.mod, s.node
        # local (nested) defs visible by bare name inside this body
        local_defs = {
            child.name: f"{s.qname}.<locals>.{child.name}"
            for child in ast.iter_child_nodes(fn)
            if isinstance(child, _FUNC_DEFS)}
        local_defs = {k: v for k, v in local_defs.items()
                      if v in self.functions}
        # name -> resource label / factory callees, for the
        # assign-then-return shape
        assigned_resources: Dict[str, str] = {}
        assigned_calls: Dict[str, Tuple[str, ...]] = {}
        returns_calls: List[str] = []

        def canonical(call: ast.Call) -> Tuple[str, ...]:
            path = attr_path(call.func)
            if not path:
                return ()
            head = aliases.get(path[0])
            if head is not None:
                path = tuple(head.split(".")) + path[1:]
            return path

        def visit(node, held: List[str], off_loop: bool = False) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return   # deferred execution: own summary
            if isinstance(node, ast.Try) and _probes_loop(node):
                # no-loop fallback idiom: the RuntimeError handlers of
                # a try that attempted loop access only run off-loop
                for part in (node.body, node.orelse, node.finalbody):
                    for sub in part:
                        visit(sub, held, off_loop)
                for handler in node.handlers:
                    h_off = off_loop or _catches_runtime_error(handler)
                    for sub in handler.body:
                        visit(sub, held, h_off)
                    if handler.type is not None:
                        visit(handler.type, held, off_loop)
                return
            if isinstance(node, (ast.Await, ast.AsyncFor, ast.AsyncWith)):
                s.has_await = True
            if isinstance(node, (ast.Yield, ast.YieldFrom)) and held:
                s.yields_holding = tuple(
                    sorted(set(s.yields_holding) | set(held)))
            acquired: List[str] = []
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    name = _lock_name(item.context_expr)
                    if name:
                        lid = self._qualify_lock(mod, s.cls, name)
                        s.acquires.append((lid, node.lineno))
                        acquired.append(lid)
            if isinstance(node, ast.Call):
                self._summarize_call(s, node, held, canonical,
                                     local_defs, off_loop)
            if isinstance(node, ast.Return) and node.value is not None:
                self._summarize_return(s, node.value, canonical,
                                       assigned_resources,
                                       assigned_calls, returns_calls,
                                       local_defs)
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                tgt = node.targets[0].id
                label = RESOURCE_CONSTRUCTORS.get(
                    canonical(node.value), "")
                if label:
                    assigned_resources[tgt] = label
                else:
                    callees = self._resolve_call(mod, node.value, s.cls,
                                                 local_defs)
                    if callees:
                        assigned_calls[tgt] = callees
            for child in ast.iter_child_nodes(node):
                visit(child, held + acquired, off_loop)

        for stmt in fn.body:
            visit(stmt, [])
        s.returns_calls = tuple(dict.fromkeys(returns_calls))

    def _summarize_call(self, s: FunctionSummary, call: ast.Call,
                        held: List[str], canonical, local_defs,
                        off_loop: bool = False) -> None:
        mod = s.mod
        path = canonical(call)
        raw = attr_path(call.func)
        if path in BLOCKING_PRIMITIVES and not off_loop:
            s.blocking.append((".".join(path), call.lineno))
        if raw and raw[-1] in _SPAWNERS:
            s.spawns.append(call.lineno)
        if raw and raw[-1] in _DEADLINE_LAUNDERERS:
            s.launders_deadline = True
        if path == ("urllib", "request", "urlopen"):
            s.raw_outbound.append(call.lineno)
            if self._headers_from_param(s, call):
                s.headers_delegated = True
        callees = self._resolve_call(mod, call, s.cls, local_defs)
        if callees:
            self.call_resolutions[id(call)] = callees
        s.calls.append(CallSite(
            lineno=call.lineno,
            name=".".join(raw) if raw else "<expr>",
            callees=callees, held_locks=tuple(held),
            off_loop=off_loop))

    def _headers_from_param(self, s: FunctionSummary,
                            call: ast.Call) -> bool:
        """Does the raw outbound call's request plausibly carry headers
        handed in by the caller? True when a parameter whose name
        mentions 'headers' exists — responsibility for the deadline
        budget then sits with every caller."""
        return any("headers" in p for p in s.params)

    def _summarize_return(self, s, value, canonical, assigned_resources,
                          assigned_calls, returns_calls,
                          local_defs) -> None:
        if isinstance(value, ast.Call):
            label = RESOURCE_CONSTRUCTORS.get(canonical(value), "")
            if label:
                s.returns_resource = label
            else:
                for q in self._resolve_call(s.mod, value, s.cls,
                                            local_defs):
                    returns_calls.append(q)
        elif isinstance(value, ast.Name):
            if value.id in assigned_resources:
                s.returns_resource = assigned_resources[value.id]
            for q in assigned_calls.get(value.id, ()):
                returns_calls.append(q)

    @staticmethod
    def _qualify_lock(mod: Module, cls: str, name: str) -> str:
        """Same convention as the locks rule: module-prefixed, class-
        qualified for self attributes — A._lock and B._lock never merge
        across files."""
        if name.startswith("self."):
            owner = f"{mod.relpath}:{cls}" if cls else mod.relpath
            return f"{owner}.{name[5:]}"
        return f"{mod.relpath}:{name}"

    # ------------------------------------------- transitive closures

    def blocking_chain(self, qname: str,
                       _stack: Optional[set] = None) -> Optional[Tuple]:
        """Shortest-found chain of (qname, lineno, desc) steps from
        qname to a blocking primitive, or None.

        Cycle discipline: a node on the walk stack contributes nothing
        to THIS traversal, and a negative computed while any ancestor
        was on the stack is provisional — memoizing it would hide real
        chains from other roots (a->b->a with a->c->fsync must still
        find b's chain through a). Positives are always definitive
        (existence proven); negatives memoize only when untainted."""
        memo = self._blocking_chain_memo
        if qname in memo:
            return memo[qname]
        _stack = _stack if _stack is not None else set()
        if qname in _stack:
            return None          # cycle-truncated: caller marks taint
        s = self.functions.get(qname)
        if s is None:
            memo[qname] = None
            return None
        if s.blocking:
            what, lineno = s.blocking[0]
            memo[qname] = ((qname, lineno, f"{what}()"),)
            return memo[qname]
        _stack.add(qname)
        best: Optional[Tuple] = None
        tainted = False
        try:
            for site in s.calls:
                if site.off_loop:
                    continue
                for callee in site.callees:
                    if callee in _stack:
                        tainted = True
                        continue
                    sub = self.blocking_chain(callee, _stack)
                    if sub is None and callee not in memo:
                        tainted = True   # callee's negative was provisional
                    if sub is not None:
                        cand = ((qname, site.lineno, site.name),) + sub
                        if best is None or len(cand) < len(best):
                            best = cand
        finally:
            _stack.discard(qname)
        if best is not None or not tainted:
            memo[qname] = best
        return best

    def transitive_acquires(self, qname: str,
                            _stack=None) -> Dict[str, Tuple]:
        """lock id -> (site relpath, lineno, via) for every lock this
        function (or anything it calls) acquires. A set assembled while
        a cycle truncated part of the walk is provisional and NOT
        memoized (it may undercount for other roots)."""
        if qname in self._acq_memo:
            return self._acq_memo[qname]
        _stack = _stack if _stack is not None else set()
        if qname in _stack:
            return {}
        _stack.add(qname)
        s = self.functions.get(qname)
        out: Dict[str, Tuple] = {}
        tainted = False
        try:
            if s is not None:
                for lid, lineno in s.acquires:
                    out.setdefault(lid, (s.mod.relpath, lineno, qname))
                for site in s.calls:
                    for callee in site.callees:
                        if callee in _stack:
                            tainted = True
                            continue
                        for lid, info in self.transitive_acquires(
                                callee, _stack).items():
                            out.setdefault(lid, info)
                        if callee not in self._acq_memo:
                            tainted = True
        finally:
            _stack.discard(qname)
        if not tainted:
            self._acq_memo[qname] = out
        return out

    def resource_label(self, qname: str, _stack=None) -> str:
        """Constructor label when qname (transitively) returns a fresh
        close-needing resource — the interprocedural 'factory' set.
        Positives memoize always; a negative found through a cycle-
        truncated walk stays unmemoized."""
        if qname in self._resource_memo:
            return self._resource_memo[qname]
        _stack = _stack if _stack is not None else set()
        if qname in _stack:
            return ""
        _stack.add(qname)
        s = self.functions.get(qname)
        label = ""
        tainted = False
        try:
            if s is not None:
                label = s.returns_resource
                if not label:
                    for callee in s.returns_calls:
                        if callee in _stack:
                            tainted = True
                            continue
                        label = self.resource_label(callee, _stack)
                        if not label and \
                                callee not in self._resource_memo:
                            tainted = True
                        if label:
                            break
        finally:
            _stack.discard(qname)
        if label or not tainted:
            self._resource_memo[qname] = label
        return label

    def render_chain(self, chain: Iterable[Tuple]) -> str:
        steps = []
        for qname, lineno, name in chain:
            short = qname.split(":", 1)[-1]
            steps.append(f"{short} ({qname.split(':', 1)[0]}:{lineno})")
        return " -> ".join(steps)


# --------------------------------------------------------------- cache

_CACHE: List[Tuple[Tuple[int, ...], CallGraph]] = []


def get(mods: Sequence[Module]) -> CallGraph:
    """One CallGraph per module set per run. Keyed on module object
    identity (the engine holds them alive for the run's duration); a
    tiny LRU so interleaved fixture checks don't thrash."""
    key = tuple(id(m) for m in mods)
    for k, g in _CACHE:
        if k == key:
            return g
    g = CallGraph(mods)
    _CACHE.append((key, g))
    del _CACHE[:-4]
    return g
