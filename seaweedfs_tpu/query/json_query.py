"""S3-Select-lite: filter/project JSON documents stored in needles.

Capability parity with the reference's query engine
(weed/server/volume_grpc_query.go:13-69, weed/query/json/query_json.go:17):
stream needle payloads, apply a comparison filter on one dotted field, and
project a subset of fields, emitting NDJSON. The reference uses gjson path
syntax; here paths are dotted keys with list indices (a.b.0.c).
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Any, Iterable, Iterator, Optional

_OPS = {
    "=": lambda a, b: a == b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "contains": lambda a, b: isinstance(a, str) and str(b) in a,
}


def get_path(doc: Any, path: str) -> Optional[Any]:
    """Resolve a dotted path ('a.b.0.c') against parsed JSON."""
    cur = doc
    if not path:
        return cur
    for part in path.split("."):
        if isinstance(cur, dict):
            if part not in cur:
                return None
            cur = cur[part]
        elif isinstance(cur, list):
            try:
                cur = cur[int(part)]
            except (ValueError, IndexError):
                return None
        else:
            return None
    return cur


@dataclass
class QueryFilter:
    field: str
    op: str
    value: Any

    def matches(self, doc: Any) -> bool:
        got = get_path(doc, self.field)
        if got is None:
            return False
        want = self.value
        # numeric comparisons coerce like gjson does
        if isinstance(got, (int, float)) and isinstance(want, str):
            try:
                want = float(want)
            except ValueError:
                pass
        fn = _OPS.get(self.op)
        if fn is None:
            raise ValueError(f"unsupported op {self.op!r}")
        try:
            return bool(fn(got, want))
        except TypeError:
            return False


def project_doc(doc: Any, projections: Optional[list[str]]) -> Any:
    if not projections:
        return doc
    out = {}
    for p in projections:
        v = get_path(doc, p)
        if v is not None:
            out[p.split(".")[-1]] = v
    return out


def query_json_lines(payloads: Iterable[bytes],
                     flt: Optional[QueryFilter] = None,
                     projections: Optional[list[str]] = None,
                     ) -> Iterator[str]:
    """Filter+project a stream of JSON payloads; yields NDJSON lines.
    Payloads that aren't valid JSON are skipped (as the reference skips
    needles that fail to parse)."""
    for raw in payloads:
        try:
            doc = json.loads(raw)
        except (ValueError, UnicodeDecodeError):
            continue
        docs = doc if isinstance(doc, list) else [doc]
        for d in docs:
            if flt is not None and not flt.matches(d):
                continue
            yield json.dumps(project_doc(d, projections),
                             separators=(",", ":"))
