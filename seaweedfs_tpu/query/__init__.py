from .json_query import QueryFilter, project_doc, query_json_lines

__all__ = ["QueryFilter", "project_doc", "query_json_lines"]
