"""PeerInvalidator: cross-peer entry-cache invalidation.

The PR 2 entry cache made every filer's lookups read-through with
generation-guarded fills; in a ring, a peer may also cache entries it
PROXIED (reads of partitions it does not own), and those can go stale
when the owner mutates them — the owner's own ``_notify`` only sweeps
the owner's cache.

This watcher extends the generation mechanism across peers: it tails
every other ring member's ``/__meta__/subscribe`` stream (the same
stream filer.sync and the geo replicator ride) and sweeps the LOCAL
entry cache for every remote mutation — both the old and the new path,
and for directory events both parents' subtrees by prefix.  Each sweep
bumps the cache generation, so an in-flight read-through fill that
raced the remote mutation is discarded by ``put_if_fresh`` exactly like
a local one.

No store writes happen here: partitions are partitioned.  The stream is
cache-coherency traffic only, so a watcher outage degrades to TTL
staleness (the PR 2 bound), never to wrong durable state.
"""

from __future__ import annotations

import asyncio
import json
import logging

import aiohttp

from .. import overload
from ..filer.filer import MetaEvent
from ..lifecycle import jittered

log = logging.getLogger("metaring.invalidation")


class PeerInvalidator:
    def __init__(self, filer_server, peers_fn):
        """``peers_fn`` returns the CURRENT remote ring members (ring
        changes re-shape the watch set on the next reconnect)."""
        self.fs = filer_server
        self.peers_fn = peers_fn
        self.swept = 0
        self.events = 0
        self._tasks: dict[str, asyncio.Task] = {}
        # per-peer resume offset (memory-only: a restarted watcher
        # re-sweeping history is idempotent cache hygiene, not loss)
        self._since: dict[str, int] = {}

    def start(self) -> None:
        self.reconcile()

    def reconcile(self) -> None:
        """Start/stop per-peer watch tasks to match the current ring."""
        want = set(self.peers_fn())
        for peer in list(self._tasks):
            if peer not in want:
                self._tasks.pop(peer).cancel()
        for peer in want:
            if peer not in self._tasks or self._tasks[peer].done():
                self._tasks[peer] = asyncio.create_task(
                    self._watch_loop(peer))

    def stop(self) -> None:
        for t in self._tasks.values():
            t.cancel()
        self._tasks.clear()

    async def _watch_loop(self, peer: str) -> None:
        # coherency traffic is background: its reconnect probes shed
        # first at an overloaded peer
        overload.set_priority(overload.CLASS_BG)
        while True:
            try:
                async with self.fs._session.get(
                        f"http://{peer}/__meta__/subscribe",
                        params={"since": str(self._since.get(peer, 0)),
                                "prefix": "/"},
                        timeout=aiohttp.ClientTimeout(
                            total=None, sock_read=None)) as r:
                    # manual ndjson split: aiohttp's line iterator
                    # raises past ~128KB, and a many-chunk entry's
                    # event exceeds that — with since= advancing only
                    # on parsed lines, the oversized event would be
                    # redelivered on every reconnect (livelock)
                    from ..filer.netutil import iter_ndjson
                    async for line in iter_ndjson(r.content):
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            d = json.loads(line)
                            tsns = int(d.get("tsns", 0))
                        except (ValueError, KeyError):
                            continue
                        self._since[peer] = max(
                            self._since.get(peer, 0), tsns)
                        self.apply_raw(d)
            except asyncio.CancelledError:
                return
            except Exception as ex:
                log.debug("invalidation watch of %s: %s (retrying)",
                          peer, ex)
            await asyncio.sleep(jittered(1.0))

    def apply(self, event: MetaEvent) -> None:
        """Sweep for one parsed MetaEvent (tests, in-process use)."""
        self.apply_raw(event.to_dict())

    @staticmethod
    def _side(d: dict, key: str):
        """(path, is_directory) of one event side without building an
        Entry — full deserialization (double json per side) was
        measurable loop work at N peers x every mutation."""
        s = d.get(key)
        if not s:
            return None, False
        import stat as _stat
        mode = int((s.get("attr") or {}).get("mode", 0))
        return s.get("path", ""), _stat.S_ISDIR(mode)

    def apply_raw(self, d: dict) -> None:
        """Sweep the local entry cache for one remote mutation (wire
        dict form).  Both sides of a rename — old AND new parent
        directories — are covered (the regression the `_notify` audit
        fixed locally)."""
        self.events += 1
        if self.fs.filer.signature in (d.get("signatures") or ()):
            # an echo of a mutation THIS peer originated or applied
            # (the owner's signature rides every mirror): the local
            # _notify already swept — re-sweeping would only churn the
            # cache generation under our own write load
            return
        old_path, old_is_dir = self._side(d, "old")
        new_path, new_is_dir = self._side(d, "new")
        # a REMOTE directory delete/move must also drop the ring
        # parent-existence cache (file events don't touch it)
        dir_cache = getattr(self.fs, "_ring_dir_cache", None)
        if dir_cache is not None and old_path and old_is_dir \
                and new_path != old_path:
            dir_cache.pop(old_path)
            dir_cache.drop_prefix(old_path.rstrip("/") + "/")
        cache = self.fs.filer._entry_cache
        if cache is None:
            return
        paths = []
        prefixes = []
        for path, is_dir in ((old_path, old_is_dir),
                             (new_path, new_is_dir)):
            if not path:
                continue
            paths.append(path)
            if is_dir:
                prefixes.append(path.rstrip("/") + "/")
        if paths:
            cache.drop_paths(paths)
            self.swept += len(paths)
        for p in prefixes:
            cache.drop_prefix(p)

    def status(self) -> dict:
        return {"peers": sorted(self._tasks),
                "events": self.events, "swept": self.swept}
