"""DirectoryRing: virtual-node consistent hashing over filer peers.

The partition key is the PARENT directory of a path — one directory's
children always share an owner, so directory listings stay one-peer
operations and a path's create/overwrite/delete serialize on one store
(the same ordering argument the geo ApplierPool makes when it hashes
events by directory).

Hashing is md5-based and fully deterministic from (peer urls, vnode
count), so every process that knows the membership computes the same
ring — the master still serves /dir/ring as the authoritative view
(version-numbered, pushed over KeepConnected) because membership
CHANGES must be observed in one order by everyone.

``owners(dir, n)`` returns the owner plus n-1 distinct successors —
the replica set.  Writes land on the owner and mirror to successors, so
losing a peer loses no acked entry: the ring drops the dead peer, the
successor (which already holds the copies) becomes the owner, and the
background handoff re-establishes the replica count.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Optional


def _hash(key: str) -> int:
    return int.from_bytes(
        hashlib.md5(key.encode("utf-8")).digest()[:8], "big")


class DirectoryRing:
    def __init__(self, peers: Optional[list[str]] = None,
                 vnodes: int = 64, replicas: int = 2, version: int = 0):
        self.vnodes = max(1, int(vnodes))
        self.replicas = max(1, int(replicas))
        self.version = version
        self.peers: list[str] = []
        self._points: list[int] = []       # sorted vnode hashes
        self._owners: list[str] = []       # parallel peer urls
        for p in peers or []:
            self.add_peer(p, _bump=False)

    # --- membership ---

    def add_peer(self, peer: str, _bump: bool = True) -> bool:
        if peer in self.peers:
            return False
        self.peers.append(peer)
        self.peers.sort()
        for i in range(self.vnodes):
            h = _hash(f"{peer}#{i}")
            at = bisect.bisect_left(self._points, h)
            self._points.insert(at, h)
            self._owners.insert(at, peer)
        if _bump:
            self.version += 1
        return True

    def remove_peer(self, peer: str) -> bool:
        if peer not in self.peers:
            return False
        self.peers.remove(peer)
        keep = [(h, o) for h, o in zip(self._points, self._owners)
                if o != peer]
        self._points = [h for h, _ in keep]
        self._owners = [o for _, o in keep]
        self.version += 1
        return True

    # --- placement ---

    def owner(self, directory: str) -> Optional[str]:
        owners = self.owners(directory, 1)
        return owners[0] if owners else None

    def owners(self, directory: str, n: int = 0) -> list[str]:
        """Owner + distinct successors for a directory (replica set).
        n=0 means the configured replica count, capped at membership."""
        if not self._points:
            return []
        n = n or self.replicas
        n = min(n, len(self.peers))
        start = bisect.bisect(self._points, _hash(directory)) \
            % len(self._points)
        out: list[str] = []
        for i in range(len(self._points)):
            peer = self._owners[(start + i) % len(self._points)]
            if peer not in out:
                out.append(peer)
                if len(out) >= n:
                    break
        return out

    def is_replica(self, directory: str, peer: str) -> bool:
        return peer in self.owners(directory)

    # --- wire form (served at /dir/ring, pushed over /cluster/watch) ---

    def to_dict(self) -> dict:
        return {"version": self.version, "peers": list(self.peers),
                "vnodes": self.vnodes, "replicas": self.replicas}

    @classmethod
    def from_dict(cls, d: dict) -> "DirectoryRing":
        return cls(peers=list(d.get("peers", [])),
                   vnodes=int(d.get("vnodes", 64)),
                   replicas=int(d.get("replicas", 2)),
                   version=int(d.get("version", 0)))

    def partition_counts(self, sample_dirs: list[str]) -> dict[str, int]:
        """Owned-directory counts over a directory sample — the
        `filer.ring.status` balance view."""
        out = {p: 0 for p in self.peers}
        for d in sample_dirs:
            o = self.owner(d)
            if o is not None:
                out[o] += 1
        return out
