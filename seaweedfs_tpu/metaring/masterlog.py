"""MasterMetaLog: the master's replicated metadata state machine.

Before this plane the raft log carried only two CEILINGS — MaxVolumeId
and a needle-key high-water mark bumped once per 10k keys.  A freshly
elected leader jumped its sequencer past the last committed ceiling:
safe against duplicates, but it skipped up to a whole bound window of
fids and left every other piece of assignment state (which volumes
exist, under which collection/geometry) to be re-learned from
heartbeats.

This log makes the assignment plane itself replicated.  Commands:

  {"assign_batch": {"count": N}}       mint N consecutive needle keys;
                                       the APPLY computes the first key
                                       from the replicated next_key, so
                                       the leader reads its own result
                                       back through the state machine
  {"seq_floor": K}                     fold an externally observed key
                                       (heartbeat max_file_key) in as a
                                       floor — rare: only a cold start
                                       against pre-existing volumes
  {"volume_create": {...}}             volume registry entry (vid,
                                       collection, replication, ttl)
  {"volume_retire": {"vid": N}}        drop a registry entry
  {"geometry_stamp": {...}}            the RS(k,m) a collection's
                                       volumes seal into, as first used

Killing the leader mid-``/dir/assign?count=N`` can therefore never
re-issue or skip a fid: a batch that committed is in the log the new
leader replays (next_key resumes exactly after it), and a batch that
never committed consumed nothing.

The log rides the EXISTING raft plane (cluster/raft.py): commands apply
through the master's ``_raft_apply``, snapshots through
capture/restore, and the leader obtains per-command results via
``RaftNode.propose_apply``.
"""

from __future__ import annotations

from typing import Optional


class MasterMetaLog:
    """Applied state of the metadata log — owned by the master, mutated
    ONLY from raft apply (leader and follower take the same path)."""

    def __init__(self):
        self.next_key = 1                 # exact next needle key
        self.assign_batches = 0           # applied batches (status view)
        self.volumes: dict[int, dict] = {}   # vid -> registry record
        self.geometry: dict[str, str] = {}   # collection -> "k+m"

    # --- apply (one command, in raft log order) ---

    def apply(self, cmd: dict) -> Optional[int]:
        """Apply one replicated command; returns the first key of an
        assign batch (None for every other kind).  Must stay
        deterministic — every replica folds the same commands in the
        same order into the same state."""
        result = None
        if "assign_batch" in cmd:
            count = max(1, int(cmd["assign_batch"]["count"]))
            result = self.next_key
            self.next_key += count
            self.assign_batches += 1
        if "seq_floor" in cmd:
            floor = int(cmd["seq_floor"])
            if floor >= self.next_key:
                self.next_key = floor + 1
        if "volume_create" in cmd:
            rec = dict(cmd["volume_create"])
            vid = int(rec.pop("vid"))
            self.volumes[vid] = rec
        if "volume_retire" in cmd:
            vr = cmd["volume_retire"]
            vids = vr.get("vids", [vr["vid"]] if "vid" in vr else [])
            for v in vids:
                self.volumes.pop(int(v), None)
        if "geometry_stamp" in cmd:
            st = cmd["geometry_stamp"]
            self.geometry[st.get("collection", "")] = st["geometry"]
        return result

    # --- snapshot (raft log compaction / follower catch-up) ---

    def capture(self) -> dict:
        return {"next_key": self.next_key,
                "assign_batches": self.assign_batches,
                "volumes": {str(v): dict(r)
                            for v, r in self.volumes.items()},
                "geometry": dict(self.geometry)}

    def restore(self, state: dict) -> None:
        self.next_key = max(self.next_key,
                            int(state.get("next_key", 1)))
        self.assign_batches = max(self.assign_batches,
                                  int(state.get("assign_batches", 0)))
        # the snapshot is the AUTHORITATIVE registry view: replace, do
        # not merge — a lagging follower that applied volume_create
        # before falling behind must also forget rows the leader
        # retired before compacting, or replicas of the "deterministic"
        # state machine stop converging
        self.volumes = {int(v): dict(rec)
                        for v, rec in (state.get("volumes")
                                       or {}).items()}
        self.geometry = dict(state.get("geometry") or {})

    def status(self) -> dict:
        return {"next_key": self.next_key,
                "assign_batches": self.assign_batches,
                "volumes": len(self.volumes),
                "geometry_stamps": dict(self.geometry)}
