"""RingRouter: owner-routed namespace ops for a ring-member filer.

Every namespace op is keyed on the PARENT directory and executed on the
ring owner of that directory; non-owners proxy over the pooled
keep-alive HTTP client (cache/http_pool.py — trace id, deadline budget
and priority-class headers already ride every pooled request), marked
with the ring-hop header so the receiving peer classifies the hop as
system (it was admitted once already at the edge) and does NOT route it
again (loop prevention).

Writes applied on the owner are mirrored synchronously to the ring
successors with the replica header — that is the zero-loss story the
chaos suite proves: losing the owner loses no acked entry, because the
successor that already holds the copy becomes the owner when the ring
drops the dead peer.  Reads fall back down the replica list when the
owner is unreachable.

The pooled client is synchronous by design (it is the shared
intra-cluster client); the filer calls it through the default executor
exactly like its own store reads, so proxy hops never block the event
loop.
"""

from __future__ import annotations

import asyncio
import json
import logging
import urllib.parse
from typing import Optional

from .. import faults
from ..cache.http_pool import HttpPool, shared_pool
from ..filer.entry import Entry
from .ring import DirectoryRing

log = logging.getLogger("metaring.router")

# marks a hop that was already admitted (and routed) at the edge peer:
# the receiver executes locally and never re-routes — one hop maximum.
# ONE definition — the admission plane owns the wire constant.
from ..overload import RING_HOP_HEADER  # noqa: E402
# marks a replica mirror: apply locally even though this peer is not
# the owner, and do not mirror again
RING_REPLICA_HEADER = "X-Seaweed-Ring-Replica"


class RingProxyError(RuntimeError):
    """The owner (and every fallback replica) refused or was
    unreachable; carries the last HTTP status for the surface to map."""

    def __init__(self, message: str, status: int = 502,
                 body: Optional[dict] = None):
        super().__init__(message)
        self.status = status
        self.body = body or {}


class RingRouter:
    def __init__(self, ring: DirectoryRing, self_url: str,
                 pool: Optional[HttpPool] = None, metrics=None,
                 timeout: float = 30.0):
        self.ring = ring
        self.self_url = self_url
        self.pool = pool or shared_pool()
        self.metrics = metrics
        self.timeout = timeout
        self.proxied = 0
        self.mirrored = 0
        self.mirror_failures = 0

    # --- placement ---

    def owners(self, directory: str) -> list[str]:
        return self.ring.owners(directory)

    def is_owner(self, directory: str) -> bool:
        owners = self.ring.owners(directory)
        return not owners or owners[0] == self.self_url

    def is_replica(self, directory: str) -> bool:
        owners = self.ring.owners(directory)
        return not owners or self.self_url in owners

    def mirror_targets(self, directory: str) -> list[str]:
        return [p for p in self.ring.owners(directory)
                if p != self.self_url]

    # --- pooled request plumbing (executor-hosted) ---

    def _count(self, name: str) -> None:
        if self.metrics is not None:
            self.metrics.count(name)

    async def _request(self, peer: str, method: str, path: str,
                       params: Optional[dict] = None,
                       body: Optional[dict] = None,
                       replica: bool = False,
                       idempotent: bool = False):
        """One ring hop to `peer` via the pooled client, off-loop.
        ``idempotent`` lets upsert-shaped POSTs (create/update mirrors
        and proxies) ride pooled keep-alive sockets — dialing a fresh
        connection per mirrored create was the dominant ring-write
        cost; a stale-socket re-send just re-applies the upsert."""
        if await faults.fire_async("ring.proxy"):
            raise ConnectionResetError(f"injected ring.proxy drop "
                                       f"to {peer}")
        url = f"{peer}{path}"
        if params:
            url += "?" + urllib.parse.urlencode(params)
        headers = {RING_HOP_HEADER: "1"}
        if replica:
            headers[RING_REPLICA_HEADER] = "1"
        # trace id + priority class are contextvars, which do NOT cross
        # the executor hop below — capture them into the headers here
        # on the loop (HttpPool's own executor-side injects are no-ops
        # for keys already present), or a CLASS_BG caller's handoff
        # push would arrive untagged and dodge admission at the peer
        from .. import observe, overload
        observe.inject(headers)
        overload.inject(headers)
        data = None
        if body is not None:
            data = json.dumps(body).encode()
            headers["Content-Type"] = "application/json"
        self._count("ring_proxy_requests")
        return await asyncio.get_event_loop().run_in_executor(
            None, lambda: self.pool.request(method, url, body=data,
                                            headers=headers,
                                            timeout=self.timeout,
                                            idempotent=idempotent))

    async def call_owner(self, directory: str, method: str, path: str,
                         params: Optional[dict] = None,
                         body: Optional[dict] = None,
                         read_fallback: bool = False,
                         idempotent: bool = False) -> dict:
        """Execute one meta op on the directory's owner; with
        ``read_fallback`` walk down the replica list when the owner is
        unreachable (reads stay available through a peer kill)."""
        targets = [p for p in self.ring.owners(directory)
                   if p != self.self_url]
        if not targets:
            raise RingProxyError(f"no ring owner for {directory}")
        if not read_fallback:
            targets = targets[:1]
        last: Optional[Exception] = None
        for peer in targets:
            try:
                resp = await self._request(peer, method, path,
                                           params=params, body=body,
                                           idempotent=idempotent)
            except (ConnectionError, OSError, TimeoutError) as e:
                last = e
                continue
            self.proxied += 1
            try:
                out = resp.json()
            except ValueError:
                out = {}
            if resp.status >= 500:
                last = RingProxyError(
                    f"{peer}{path}: HTTP {resp.status}",
                    status=resp.status, body=out)
                continue
            if resp.status >= 400:
                raise RingProxyError(f"{peer}{path}: HTTP {resp.status}",
                                     status=resp.status, body=out)
            return out
        raise RingProxyError(f"ring owner unreachable for {directory}: "
                             f"{last}", body={"error": str(last)})

    async def mirror(self, directory: str, path: str,
                     body: dict, idempotent: bool = False) -> None:
        """Mirror one applied mutation to the ring successors,
        synchronously (the ack must imply the replica holds the copy) —
        but a down successor degrades to a warning, not a failed user
        op: the background handoff re-establishes the count when the
        ring membership catches up with reality."""
        targets = self.mirror_targets(directory)
        if not targets:
            return

        async def one(peer: str) -> None:
            try:
                resp = await self._request(peer, "POST", path,
                                           body=body, replica=True,
                                           idempotent=idempotent)
                if resp.status >= 400:
                    raise RingProxyError(f"HTTP {resp.status}",
                                         status=resp.status)
                self.mirrored += 1
                self._count("ring_mirrors")
            except Exception as e:
                self.mirror_failures += 1
                self._count("ring_mirror_failures")
                log.warning("ring mirror of %s to %s failed: %s",
                            directory, peer, e)

        await asyncio.gather(*[one(p) for p in targets])

    # --- typed meta ops (the /__meta__ wire face) ---

    async def find_entry(self, path: str) -> Optional[Entry]:
        directory = path.rstrip("/").rsplit("/", 1)[0] or "/"
        try:
            out = await self.call_owner(directory, "GET",
                                        "/__meta__/lookup",
                                        params={"path": path},
                                        read_fallback=True)
        except RingProxyError as e:
            if e.status == 404:
                return None
            raise
        return Entry.from_json(json.dumps(out))

    async def list_directory(self, dir_path: str, start: str = "",
                             include_start: bool = False,
                             limit: int = 1024,
                             prefix: str = "") -> list[Entry]:
        out = await self.call_owner(
            dir_path, "GET", "/__meta__/list",
            params={"dir": dir_path, "start": start,
                    "include_start": "true" if include_start else "false",
                    "limit": str(limit), "prefix": prefix},
            read_fallback=True)
        return [Entry.from_json(json.dumps(e))
                for e in out.get("entries", [])]

    async def create_entry(self, entry: Entry, o_excl: bool = False,
                           signatures: tuple = (),
                           free_old_chunks: bool = True) -> None:
        await self.call_owner(
            entry.parent, "POST", "/__meta__/create_entry",
            body={"entry": json.loads(entry.to_json()),
                  "o_excl": o_excl, "signatures": list(signatures),
                  "free_old_chunks": free_old_chunks},
            # an upsert re-sent over a stale pooled socket re-applies
            # harmlessly (o_excl creates excepted — those must not
            # double-send a conflict)
            idempotent=not o_excl)

    async def update_entry(self, entry: Entry,
                           signatures: tuple = ()) -> None:
        await self.call_owner(
            entry.parent, "POST", "/__meta__/update_entry",
            body={"entry": json.loads(entry.to_json()),
                  "signatures": list(signatures)},
            idempotent=True)

    async def delete_entry(self, path: str, recursive: bool = False,
                           free_chunks: bool = True,
                           signatures: tuple = ()) -> None:
        directory = path.rstrip("/").rsplit("/", 1)[0] or "/"
        await self.call_owner(
            directory, "POST", "/__meta__/delete",
            body={"path": path, "recursive": recursive,
                  "free_chunks": free_chunks,
                  "signatures": list(signatures)})

    def status(self) -> dict:
        return {"self": self.self_url, "ring": self.ring.to_dict(),
                "proxied": self.proxied, "mirrored": self.mirrored,
                "mirror_failures": self.mirror_failures}
