"""FanoutCoordinator: recursive namespace ops across ring partitions.

A recursive delete (or a cross-partition rename) touches directories
owned by several peers: every directory's children live on that
directory's owner, so the subtree walk itself must hop the ring.  The
coordinator fans the per-directory work across a small worker pool with
the one ordering that matters — operations for the SAME directory hash
to the same worker and run FIFO (exactly the geo ApplierPool's
discipline: one path's create/overwrite/delete can never land out of
order, cross-directory ordering is deliberately relaxed).

Structure ordering is enforced by the walk itself: children are
scheduled (and drained) before their parent directory entry is removed,
so a crash mid-delete leaves only complete subtrees missing — never an
orphaned child under a deleted parent.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Awaitable, Callable

from ..filer.entry import Entry

log = logging.getLogger("metaring.coordinator")


class FanoutCoordinator:
    """Per-directory-ordered async fanout over ring ops.

    ``ops`` is the FilerServer's ring-op facade: objects exposing
    ``ring_list / ring_delete / ring_create / ring_find`` coroutines
    that already handle owner routing + replica mirroring.
    """

    def __init__(self, ops, workers: int = 4):
        self.ops = ops
        self.workers = max(1, workers)

    # --- per-directory-ordered execution ---

    async def _run_grouped(self, jobs: list[tuple[str, Callable[[],
                                                  Awaitable[None]]]]
                           ) -> None:
        """Run (directory, thunk) jobs: same directory -> same lane,
        FIFO; distinct directories run concurrently across lanes."""
        lanes: list[list[Callable[[], Awaitable[None]]]] = [
            [] for _ in range(self.workers)]
        for directory, thunk in jobs:
            lanes[hash(directory) % self.workers].append(thunk)

        async def drain(lane) -> None:
            for thunk in lane:
                await thunk()

        results = await asyncio.gather(
            *[drain(lane) for lane in lanes if lane],
            return_exceptions=True)
        for r in results:
            if isinstance(r, BaseException):
                raise r

    # --- recursive delete across partitions ---

    async def delete_subtree(self, path: str, free_chunks: bool = True,
                             signatures: tuple = ()) -> int:
        """Delete a directory subtree whose directories may be owned by
        different peers.  Bottom-up: each directory's files are deleted
        on its owner (freeing chunks there), subdirectories recurse
        first, the directory entry itself goes last."""
        deleted = await self._delete_children(path, free_chunks,
                                              signatures)
        # the directory ENTRY lives on the parent's owner
        await self.ops.ring_delete(path, recursive=True,
                                   free_chunks=free_chunks,
                                   signatures=signatures)
        return deleted + 1

    async def _delete_children(self, dir_path: str, free_chunks: bool,
                               signatures: tuple) -> int:
        deleted = 0
        while True:
            # every processed page is deleted, so the NEXT page is
            # always the new first page — re-list from the start rather
            # than paginate past entries that no longer exist
            batch = await self.ops.ring_list(dir_path, limit=1024)
            if not batch:
                break
            jobs: list[tuple[str, Callable[[], Awaitable[None]]]] = []
            subdirs: list[str] = []
            for e in batch:
                if e.is_directory:
                    subdirs.append(e.full_path)
                else:
                    jobs.append((dir_path, self._delete_one(
                        e.full_path, free_chunks, signatures)))
            # subtrees drain fully before this page's files are counted
            # done — children before parents, always
            for sub in subdirs:
                deleted += await self.delete_subtree(
                    sub, free_chunks=free_chunks, signatures=signatures)
            await self._run_grouped(jobs)
            deleted += len(jobs)
            if len(batch) < 1024:
                break
        return deleted

    def _delete_one(self, path: str, free_chunks: bool,
                    signatures: tuple):
        async def run() -> None:
            try:
                await self.ops.ring_delete(path, recursive=False,
                                           free_chunks=free_chunks,
                                           signatures=signatures)
            except FileNotFoundError:
                pass  # a retried fanout page may have deleted it already
        return run

    # --- cross-partition rename ---

    async def rename(self, old_path: str, new_path: str) -> int:
        """Move old_path -> new_path across partitions: entries are
        re-created at their new owners (same chunk list — bytes never
        move), then the old side is removed metadata-only.  Create
        strictly precedes delete per entry, so a crash leaves a
        recoverable double-entry, never a lost one."""
        entry = await self.ops.ring_find(old_path)
        if entry is None:
            raise FileNotFoundError(old_path)
        moved = await self._move_entry(entry, new_path)
        return moved

    async def _move_entry(self, entry: Entry, new_path: str) -> int:
        moved = 1
        new_entry = Entry(full_path=new_path, attr=entry.attr,
                          chunks=entry.chunks, extended=entry.extended,
                          hard_link_id=entry.hard_link_id)
        await self.ops.ring_create(new_entry, free_old_chunks=False)
        if entry.is_directory:
            start = ""
            while True:
                batch = await self.ops.ring_list(entry.full_path,
                                                 start=start, limit=1024)
                if not batch:
                    break
                dirs = [e for e in batch if e.is_directory]
                files = [e for e in batch if not e.is_directory]
                jobs = [(entry.full_path,
                         self._move_file(e, f"{new_path}/{e.name}"))
                        for e in files]
                await self._run_grouped(jobs)
                moved += len(files)
                for e in dirs:
                    moved += await self._move_entry(
                        e, f"{new_path}/{e.name}")
                if len(batch) < 1024:
                    break
                start = batch[-1].name
        # old side: metadata only — the chunks now belong to the new path
        await self.ops.ring_delete(entry.full_path, recursive=False,
                                   free_chunks=False)
        return moved

    def _move_file(self, entry: Entry, new_path: str):
        async def run() -> None:
            new_entry = Entry(full_path=new_path, attr=entry.attr,
                              chunks=entry.chunks,
                              extended=entry.extended,
                              hard_link_id=entry.hard_link_id)
            await self.ops.ring_create(new_entry, free_old_chunks=False)
            try:
                await self.ops.ring_delete(entry.full_path,
                                           recursive=False,
                                           free_chunks=False)
            except FileNotFoundError:
                pass
        return run
