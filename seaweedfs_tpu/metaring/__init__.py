"""Metadata scale-out plane: partitioned filer ring + master metadata log.

PAPER.md §L4 gives the filer pluggable metadata stores precisely so the
namespace can outgrow one node; until this plane existed ours was still
one process in front of one store, and the master replicated only a
sequencer *ceiling*.  This package is the refactor ROADMAP item 3 names:
no single process bounds namespace size, assign throughput, or
availability.

Two halves:

* **DirectoryRing** (ring.py) — virtual-node consistent hashing keyed on
  the PARENT directory, so one directory's children (and therefore one
  path's create/overwrite/delete) always live on one owner peer, with a
  configurable replica count mirrored to ring successors.  The ring
  config is owned by the master (served at ``/dir/ring``, pushed over
  the existing KeepConnected ``/cluster/watch`` stream) so every filer
  and every client sees one consistent membership view.

* **Filer-side routing** (router.py / coordinator.py / invalidation.py /
  handoff.py) — every namespace op entering any peer is routed to its
  owner; non-owner peers proxy over the pooled keep-alive HTTP client
  (trace id, deadline and priority-class headers already ride it, and
  the hop classifies as system at the receiver — it was admitted once
  already).  Recursive ops (delete subtree, cross-partition rename) fan
  out under a coordinator with per-directory ordering exactly like the
  geo ApplierPool; the PR 2 entry-cache generations extend to
  cross-peer invalidation (owners broadcast their ``/__meta__`` deltas,
  peers sweep both parents by prefix); a ring change triggers a
  background partition handoff (walk + upsert, CLASS_BG, resumable
  low-watermark offsets exactly like the geo backfill).

The master half (masterlog.py) replaces the ceiling-only sequencer sync
with a compact replicated metadata log — assign batches, volume
create/retire, EC geometry stamps — applied through the existing raft
plane, so a freshly elected leader replays to the exact sequencer state
instead of jumping past a high-water mark.

Env knobs (all optional; the plane is off until peers are configured):

  WEED_FILER_RING_PEERS     comma-separated filer host:port members
  WEED_FILER_RING_VNODES    virtual nodes per peer (default 64)
  WEED_FILER_RING_REPLICAS  entry copies per partition (default 2)
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field


@dataclass
class RingConfig:
    """Parsed WEED_FILER_RING_* knobs (explicit args win over env)."""

    peers: list[str] = field(default_factory=list)
    vnodes: int = 64
    replicas: int = 2

    @property
    def enabled(self) -> bool:
        return len(self.peers) > 0

    @classmethod
    def from_env(cls, env=os.environ) -> "RingConfig":
        peers = [p.strip() for p in
                 env.get("WEED_FILER_RING_PEERS", "").split(",")
                 if p.strip()]

        def num(key: str, default: int) -> int:
            try:
                return int(env.get(key, "") or default)
            except ValueError:
                return default

        return cls(peers=peers,
                   vnodes=max(1, num("WEED_FILER_RING_VNODES", 64)),
                   replicas=max(1, num("WEED_FILER_RING_REPLICAS", 2)))


from .ring import DirectoryRing  # noqa: E402
from .masterlog import MasterMetaLog  # noqa: E402

__all__ = ["RingConfig", "DirectoryRing", "MasterMetaLog"]
