"""HandoffRunner: background partition migration on ring changes.

When a peer joins (or leaves) the ring, some directories' replica sets
change; their entries must move to (or be re-mirrored onto) the new
owners.  This runner walks the LOCAL store's directory enumeration
(``FilerStore.iter_directories`` — a root walk can't see subtrees whose
parents live on peers), pushes every entry of a no-longer-ours
directory to its current replica set as replica-apply upserts, then
drops the local copies metadata-only (the chunks moved with the entry
records; bytes on volume servers never move).

The discipline is the geo backfill's, exactly:

* CLASS_BG — every push sheds before foreground traffic at the
  receiving peer;
* resumable low-watermark offsets — directories are walked in sorted
  order and the last fully-moved directory is persisted in the store's
  KV face under ``ring_handoff/v<version>``; a restarted coordinator
  (or a crashed filer) resumes AFTER the watermark instead of
  re-pushing from scratch (re-pushing is idempotent upsert anyway — the
  watermark bounds the wasted work, not correctness);
* the ``ring.handoff`` fault point makes the mid-flight crash a
  one-line chaos drill instead of a monkeypatch.
"""

from __future__ import annotations

import asyncio
import json
import logging

from .. import faults, overload
from ..lifecycle import jittered
from .ring import DirectoryRing

log = logging.getLogger("metaring.handoff")


class HandoffRunner:
    def __init__(self, filer_server, router):
        self.fs = filer_server
        self.router = router
        self.moved_entries = 0
        self.moved_dirs = 0
        self.last_error = ""
        self.state = "idle"
        self._task = None

    # --- trigger (ring change / startup recovery) ---

    def trigger(self, ring: DirectoryRing,
                old_ring: DirectoryRing = None) -> None:
        """Start (or restart) a handoff pass for the given ring view.
        An already-running pass for an older view is cancelled — its
        watermark persists, but the new membership decides ownership."""
        if self._task is not None and not self._task.done():
            self._task.cancel()
        self._task = asyncio.create_task(self.run_once(ring, old_ring))

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    # --- one resumable pass ---

    def _offset_key(self, version: int) -> str:
        return f"ring_handoff/v{version}"

    async def run_once(self, ring: DirectoryRing,
                       old_ring: DirectoryRing = None) -> int:
        """Re-home every locally-held directory whose replica set
        changed: push its entries to the new members (a joiner that
        became owner gets the data even when this peer stays on as
        successor), and drop the local copies only when this peer left
        the set entirely.  Returns moved directory count."""
        overload.set_priority(overload.CLASS_BG)
        self.state = "running"
        self.last_error = ""
        store = self.fs.filer.store
        key = self._offset_key(ring.version)
        loop = asyncio.get_event_loop()
        raw = await loop.run_in_executor(None, store.kv_get, key)
        watermark = ""
        if raw:
            try:
                watermark = json.loads(raw.decode()).get("dir", "")
            except ValueError:
                watermark = ""
        moved_dirs = 0
        try:
            dirs = sorted(await loop.run_in_executor(
                None, lambda: list(store.iter_directories())))
            for d in dirs:
                if watermark and d <= watermark:
                    continue
                new_set = ring.owners(d)
                stray = self.router.self_url not in new_set
                if not stray:
                    # we remain a replica: skip only when the before
                    # view shows an unchanged membership (the diff is
                    # an optimization, never a correctness gate)
                    if old_ring is None \
                            or old_ring.owners(d) == new_set:
                        continue
                # a STRAY (locally held, not ours under the new ring)
                # always moves — even when the old-vs-new diff shows no
                # change for this partition: an earlier cancelled pass
                # (ring change during handoff, coordinator crash) may
                # have left it behind, and skipping it would strand the
                # data on a peer the ring never routes to again
                await self._move_directory(d, ring, drop=stray)
                moved_dirs += 1
                self.moved_dirs += 1
                # low-watermark: everything <= d is done for v<version>
                await loop.run_in_executor(
                    None, store.kv_put, key,
                    json.dumps({"dir": d}).encode())
                # jittered yield between directories: a fleet-wide ring
                # change must not stampede the new owner in lockstep
                await asyncio.sleep(jittered(0.01))
            self.state = "done"
        except asyncio.CancelledError:
            self.state = "cancelled"
            raise
        except Exception as e:
            self.state = "failed"
            self.last_error = str(e)
            log.warning("ring handoff (v%d) failed at %d dirs: %s",
                        ring.version, moved_dirs, e)
            raise
        return moved_dirs

    async def _move_directory(self, d: str, ring: DirectoryRing,
                              drop: bool = True) -> None:
        if await faults.fire_async("ring.handoff"):
            raise ConnectionResetError(
                f"injected ring.handoff drop at {d}")
        store = self.fs.filer.store
        loop = asyncio.get_event_loop()
        start = ""
        while True:
            batch = await loop.run_in_executor(
                None, lambda s=start: store.list_directory_entries(
                    d, s, False, 512))
            if not batch:
                break
            for e in batch:
                # replica-apply upsert on every CURRENT replica of the
                # directory — idempotent, so a resumed pass re-pushing
                # the watermark directory is harmless
                body = {"entry": json.loads(e.to_json()),
                        "o_excl": False, "signatures": [],
                        "free_old_chunks": False}
                for peer in ring.owners(d):
                    if peer == self.router.self_url:
                        continue
                    resp = await self.router._request(
                        peer, "POST", "/__meta__/create_entry",
                        body=body, replica=True, idempotent=True)
                    if resp.status >= 400:
                        raise RuntimeError(
                            f"handoff upsert {e.full_path} -> {peer}: "
                            f"HTTP {resp.status}")
                self.moved_entries += 1
            if len(batch) < 512:
                break
            start = batch[-1].name
        if drop:
            # local copies go metadata-only: the entries (and their
            # chunk references) now live with the new replica set —
            # freeing chunks here would tear bytes out from under the
            # moved entries
            await loop.run_in_executor(
                None, lambda: _drop_local_children(store, d))

    def status(self) -> dict:
        return {"state": self.state, "moved_dirs": self.moved_dirs,
                "moved_entries": self.moved_entries,
                "last_error": self.last_error}


def _drop_local_children(store, d: str) -> None:
    """Remove the local copies of one handed-off directory's children
    (entries only; never the subtree — deeper directories may still be
    owned here and are judged one by one by the walk)."""
    while True:
        batch = store.list_directory_entries(d, "", False, 512)
        if not batch:
            return
        for e in batch:
            store.delete_entry(e.full_path)
        if len(batch) < 512:
            return
