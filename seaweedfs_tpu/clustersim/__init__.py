"""clustersim: deterministic in-process 1000-node control-plane simulator.

The control-plane twin of crashsim (scripts/crashsim.sh): where crashsim
proves the DATA plane survives a kill at any barrier, clustersim proves
the CONTROL plane — the real ``Topology``, ``DirectoryRing``, balance
planner, ``PlannerState`` and repair placement rule, the exact objects
production masters run — converges, stays bounded, and never oscillates
at planet scale.  Nothing is mocked at the decision layer; only the
physical substrate (volume servers, wires, disks) is modeled:

* a **virtual clock** (clock.py) injected into ``Topology`` — zero
  wall-clock sleeps, so a 1000-node, 200-virtual-second run finishes in
  seconds and every liveness window (prune timeout, heat decay,
  cooldown) behaves exactly as in production;
* **seeded everything** — node layout, scripted kills/flaps/rack loss,
  heat skew, and the planner's tie-break all derive from one integer.
  Identical seed => identical event log (the run digest is the sha256
  of the canonical event log, and the CI gate runs every scenario twice
  to prove it);
* **scripted heartbeats** drive the real ``Topology.register_heartbeat``
  / ``merge_heat`` / ``prune_dead_nodes`` intake, each beat gated by the
  ``sim.heartbeat`` fault point so flap drills ride the same faults
  plane as every other chaos drill;
* a **slot pool** models the master's shared ``_repair_sem`` worker
  budget with repair-before-balance priority, so the repair-storm
  scenario proves a rack-loss rebuild drains without balance moves
  starving it.

Scenarios and their assertions (convergence in bounded ticks, zero
placement oscillation, ring-bounded movement under churn, repair-storm
drain) live in scenarios.py; ``python -m seaweedfs_tpu.clustersim``
(scripts/clustersim.sh) is the CI gate that sweeps seeds x scenarios
and exits 1 on any violation.
"""

from .clock import VirtualClock
from .sim import ClusterSim, SimNode

__all__ = ["VirtualClock", "ClusterSim", "SimNode"]
