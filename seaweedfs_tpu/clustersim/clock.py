"""The virtual clock every simulated component shares.

``Topology`` takes this as its injected ``clock`` callable, the balance
planner and ``PlannerState`` take ``now`` arguments — so the whole
control plane runs on simulated time.  Time only moves when the
simulator says so; there is no wall-clock anywhere in a run, which is
what makes a 1000-node, minutes-of-virtual-time scenario finish in
seconds and replay bit-identically from its seed.

The epoch is deliberately far from zero: production code compares
timestamps against ``last_seen``/``first_seen`` defaults and a
zero-epoch sim would sit inside decay half-lives of t=0.
"""

from __future__ import annotations

EPOCH = 1_700_000_000.0


class VirtualClock:
    __slots__ = ("_now",)

    def __init__(self, start: float = EPOCH):
        self._now = float(start)

    def now(self) -> float:
        return self._now

    def advance(self, dt: float) -> float:
        if dt < 0:
            raise ValueError("virtual time never rewinds")
        self._now += dt
        return self._now
