"""The scenario sweep the CI gate runs — each returns a report dict
with a ``violations`` list that MUST be empty.

* ``steady``  — uniform heat, no events: the balancer must plan ZERO
  moves (a balanced cluster is left alone) and end at a planner
  fixpoint.
* ``skew``    — a few nodes turn hot: rebalance must CONVERGE within a
  bounded number of ticks (fixpoint reached, hot nodes drained below
  half their peak), with zero placement oscillation — no volume moves
  twice inside the cooldown window and no A->B->A path ever.
* ``churn``   — node kills/flaps/revivals with NO heat skew: capacity
  churn alone must trigger zero balance moves, repair must heal every
  deficit, the data moved (repair bytes) must stay bounded by the
  churn fraction, and the DirectoryRing must exhibit its
  minimal-movement property at 1000 peers (a membership change moves
  ~1/N of directories, never a reshuffle).
* ``rackloss`` — a whole rack dies while heat skew is active: the
  repair storm must fully drain (every deficit healed in bounded
  ticks) and repair must NEVER be starved by balance — the shared
  slot pool always gives repair priority (no balance job starts while
  repair work is queued).

Every scenario is pure in its (name, seed, nodes) inputs — the runner
(__main__.py) executes each twice and asserts identical digests, which
is the determinism gate for the whole control plane.
"""

from __future__ import annotations

from .. import faults
from ..metaring.ring import DirectoryRing
from .sim import ClusterSim

SCENARIOS = ("steady", "skew", "churn", "rackloss")

# virtual-cluster shape knobs shared by every scenario
TICKS = {"steady": 50, "skew": 120, "churn": 130, "rackloss": 200}


def _oscillation_violations(sim: ClusterSim) -> list[str]:
    """No volume moves twice within the cooldown window; no A->B->A
    path ever (steady heat must never make a volume retrace)."""
    out = []
    by_vid: dict[int, list] = {}
    for tick, vid, src, dst, _ in sim.completed_moves:
        by_vid.setdefault(vid, []).append((tick, src, dst))
    window = sim.cfg.cooldown / sim.tick_seconds
    for vid, moves in sorted(by_vid.items()):
        for (t1, _, _), (t2, _, _) in zip(moves, moves[1:]):
            if t2 - t1 < window:
                out.append(f"volume {vid} moved twice within the "
                           f"cooldown window (ticks {t1} and {t2})")
        for i, (_, s1, d1) in enumerate(moves):
            for (_, s2, d2) in moves[i + 1:]:
                if s2 == d1 and d2 == s1:
                    out.append(f"volume {vid} ping-ponged "
                               f"{s1}->{d1}->{d2}")
    return out


def _base_report(sim: ClusterSim, name: str, seed: int) -> dict:
    return {
        "scenario": name, "seed": seed, "nodes": len(sim.nodes),
        "ticks": sim.tick_no, "digest": sim.digest(),
        "moves": len(sim.completed_moves),
        "repairs": len(sim.completed_repairs),
        "moved_bytes": sim.moved_bytes,
        "repaired_bytes": sim.repaired_bytes,
        "moved_bytes_ratio": round(sim.moved_bytes
                                   / max(sim.total_bytes, 1), 6),
        "deficits_left": sim.deficit_count(),
        "max_node_rate": round(sim.max_node_rate(), 4),
        "violations": [],
    }


def steady(seed: int, nodes: int) -> dict:
    sim = ClusterSim(nodes=nodes, seed=seed)
    for n in sim.nodes:
        for vid in n.volumes:
            n.rates[vid] = 0.2
    sim.run(TICKS["steady"])
    rep = _base_report(sim, "steady", seed)
    if sim.completed_moves:
        rep["violations"].append(
            f"{len(sim.completed_moves)} moves on a uniform cluster")
    if sim.final_plan():
        rep["violations"].append("planner not at fixpoint under "
                                 "uniform heat")
    return rep


def skew(seed: int, nodes: int) -> dict:
    sim = ClusterSim(nodes=nodes, seed=seed)
    skew_tick, hot_nodes, hot_rate = 5, 3, 2.0
    for i in range(hot_nodes):
        for vid in sorted(sim.node(i).volumes):
            sim.at(skew_tick, "heat", i, vid, hot_rate)
    sim.run(TICKS["skew"])
    rep = _base_report(sim, "skew", seed)
    rep["converge_tick"] = (max(t for t, *_ in sim.completed_moves)
                            if sim.completed_moves else 0)
    if not sim.completed_moves:
        rep["violations"].append("no moves despite heat skew")
    if sim.final_plan():
        rep["violations"].append("planner not at fixpoint by end of run")
    if rep["converge_tick"] - skew_tick > 80:
        rep["violations"].append(
            f"convergence took {rep['converge_tick'] - skew_tick} ticks "
            f"(bound 80)")
    # a drained hot node: the per-node peak was hot_rate * volumes-held;
    # nothing can go below one indivisible hot volume's rate
    if rep["max_node_rate"] > hot_rate * 2 + 0.01:
        rep["violations"].append(
            f"hot node not drained: max rate {rep['max_node_rate']}")
    rep["violations"].extend(_oscillation_violations(sim))
    return rep


def churn(seed: int, nodes: int) -> dict:
    sim = ClusterSim(nodes=nodes, seed=seed)
    # deterministic low-probability beat loss (flap noise) through the
    # faults plane: the same drill an operator arms on a live cluster
    sim.at(1, "fault", "sim.heartbeat", "drop", 0.01, None, seed)
    import random as _random
    rng = _random.Random(seed)
    victims = rng.sample(range(len(sim.nodes)), 3)
    sim.at(10, "kill", victims[0])                # permanent
    sim.at(15, "kill", victims[1])                # flap: back before
    sim.at(30, "revive", victims[1])              # the prune window
    sim.at(50, "kill", victims[2])                # permanent
    # the ring's minimal-movement property at the same scale: mirror
    # the membership changes into a DirectoryRing and count how many
    # sampled directories change owner — a consistent-hash ring moves
    # ~1/N per change, a naive rehash would move nearly all of them
    ring = DirectoryRing(peers=[n.id for n in sim.nodes], vnodes=16)
    sample = [f"bucket{i}/dir{i}" for i in range(400)]
    owners = {d: ring.owner(d) for d in sample}
    ring_moved = 0
    membership = [(10, "remove", victims[0]), (15, "remove", victims[1]),
                  (30, "add", victims[1]), (50, "remove", victims[2])]
    sim.run(TICKS["churn"])
    for _, op, idx in membership:
        peer = sim.node(idx).id
        if op == "remove":
            ring.remove_peer(peer)
        else:
            ring.add_peer(peer)
        for d in sample:
            new = ring.owner(d)
            if new != owners[d]:
                owners[d] = new
                ring_moved += 1
    rep = _base_report(sim, "churn", seed)
    rep["ring_moved_dirs"] = ring_moved
    rep["ring_sampled_dirs"] = len(sample)
    if sim.completed_moves:
        rep["violations"].append(
            f"{len(sim.completed_moves)} balance moves from capacity "
            f"churn alone (no heat skew)")
    if rep["deficits_left"]:
        rep["violations"].append(
            f"{rep['deficits_left']} deficits unrepaired after churn")
    # minimal movement: each membership change over N peers should
    # touch ~len(sample)/N directories — allow 4x for vnode variance
    # (plus a floor for small-N noise); a reshuffle would move hundreds
    bound = max(4.0 * len(membership) * len(sample) / len(sim.nodes), 20)
    rep["ring_moved_bound"] = round(bound, 1)
    if ring_moved > bound:
        rep["violations"].append(
            f"ring moved {ring_moved}/{len(sample)} dirs over 4 "
            f"membership changes — not minimal movement")
    # data movement bounded by the churn itself: only the dead nodes'
    # replicas get re-created, nothing else migrates
    dead_fraction = 2.0 / len(sim.nodes)
    ratio = (sim.moved_bytes + sim.repaired_bytes) / sim.total_bytes
    if ratio > dead_fraction * 3 + 1e-9:
        rep["violations"].append(
            f"moved-bytes ratio {ratio:.4f} exceeds 3x the dead-node "
            f"fraction {dead_fraction:.4f}")
    rep["churn_data_ratio"] = round(ratio, 6)
    return rep


def rackloss(seed: int, nodes: int) -> dict:
    sim = ClusterSim(nodes=nodes, seed=seed)
    # heat skew on two nodes OUTSIDE the doomed rack, so balance work
    # coexists with the repair storm — the starvation drill
    survivors = [i for i in range(len(sim.nodes))
                 if (sim.node(i).dc, sim.node(i).rack) != ("dc0", "r0")]
    for i in survivors[:2]:
        for vid in sorted(sim.node(i).volumes):
            sim.at(5, "heat", i, vid, 2.0)
    sim.at(10, "rack_loss", "dc0", "r0")
    sim.run(TICKS["rackloss"])
    rep = _base_report(sim, "rackloss", seed)
    if rep["deficits_left"]:
        rep["violations"].append(
            f"repair storm did not drain: {rep['deficits_left']} "
            f"deficits left after {sim.tick_no} ticks")
    if not sim.completed_repairs:
        rep["violations"].append("rack loss produced no repairs")
    rep["balance_start_while_repair_pending"] = \
        sim.balance_start_while_repair_pending
    if sim.balance_start_while_repair_pending:
        rep["violations"].append(
            "balance jobs started while repair work was queued "
            "(slot-priority inversion)")
    for ev in sim.events:
        if ev["e"] == "move_start" and ev.get("repair_pending", 0) > 0:
            rep["violations"].append(
                f"move_start at tick {ev['t']} with "
                f"{ev['repair_pending']} repairs pending")
    rep["violations"].extend(_oscillation_violations(sim))
    return rep


def run_scenario(name: str, seed: int, nodes: int = 1000) -> dict:
    """One scenario run with a clean faults plane either side (scripted
    ops may arm sim.heartbeat faults; they must not leak across runs —
    a leaked fault would also advance its RNG and break determinism)."""
    fn = {"steady": steady, "skew": skew, "churn": churn,
          "rackloss": rackloss}[name]
    faults.clear("sim.heartbeat")
    try:
        return fn(seed, nodes)
    finally:
        faults.clear("sim.heartbeat")
