"""ClusterSim: the scripted substrate under the real control plane.

What is REAL here (imported production code, not models):

* ``Topology`` — heartbeat intake, layouts, pruning, heat merge, all on
  the injected virtual clock;
* ``plan_moves`` / ``PlannerState`` — the balance planner and its
  two-pass/cooldown/veto oscillation guard, exactly as the live
  BalancerDaemon runs them;
* ``pick_replica_target`` — the repair placement rule the master's
  repair daemon executes;
* the ``sim.heartbeat`` fault point — flap drills arm the same faults
  plane as every other chaos drill.

What is MODELED: volume servers are ``SimNode`` records (volumes, heat
rates, aliveness), and the master's shared ``_repair_sem`` worker
budget is a slot pool with repair-before-balance priority.  Move/repair
jobs occupy a slot for a fixed number of ticks and mutate the SimNodes
on completion, so the NEXT heartbeats — through the real intake — show
the control plane the consequences of its own decisions.  That closed
loop is the whole point: convergence, oscillation and starvation are
emergent properties of the real planner code, not of the model.

Every externally visible action is appended to ``events``;
``digest()`` is the sha256 of the canonical JSON event log.  Identical
seed => identical digest, enforced by the CI gate running every
scenario twice.
"""

from __future__ import annotations

import hashlib
import json
from collections import deque
from typing import Optional

from .. import faults
from ..balance import BalanceConfig, PlannerState, plan_moves
from ..balance.planner import node_rates, pick_replica_target
from ..storage.superblock import ReplicaPlacement
from ..topology.topology import Topology
from .clock import VirtualClock

MB = 1 << 20


class SimNode:
    """A modeled volume server: what the real Topology hears from it."""

    __slots__ = ("id", "url", "dc", "rack", "max_volumes", "alive",
                 "volumes", "rates", "needs_full", "stagger")

    def __init__(self, node_id: str, dc: str, rack: str,
                 max_volumes: int, stagger: int):
        self.id = node_id
        self.url = node_id
        self.dc = dc
        self.rack = rack
        self.max_volumes = max_volumes
        self.alive = True
        # vid -> volume dict, exactly the heartbeat payload shape
        self.volumes: dict[int, dict] = {}
        # vid -> steady read rate (reads/s) this node serves
        self.rates: dict[int, float] = {}
        self.needs_full = True   # next beat must be a full registration
        self.stagger = stagger   # spreads periodic full beats over ticks


class ClusterSim:
    def __init__(self, nodes: int = 1000, seed: int = 0, *,
                 dcs: int = 2, racks_per_dc: int = 5,
                 volumes_per_node: int = 4, replication: str = "010",
                 volume_bytes: int = MB,
                 cfg: Optional[BalanceConfig] = None,
                 slots: int = 16, tick_seconds: float = 1.0,
                 pulse_seconds: float = 5.0,
                 balance_every: int = 5, repair_every: int = 5,
                 refresh_every: int = 5, job_ticks: int = 3):
        self.seed = seed
        self.tick_seconds = tick_seconds
        self.balance_every = balance_every
        self.repair_every = repair_every
        self.refresh_every = refresh_every
        self.job_ticks = job_ticks
        self.slots = slots
        self.volume_bytes = volume_bytes
        self.replication = replication
        self.clock = VirtualClock()
        self.topology = Topology(volume_size_limit=30 * MB,
                                 pulse_seconds=pulse_seconds,
                                 clock=self.clock.now)
        self.cfg = cfg or BalanceConfig(
            interval=tick_seconds * balance_every, cooldown=30.0,
            max_moves=8, min_rate=0.05)
        self.state = PlannerState(self.cfg)
        self.tick_no = 0
        self.balance_passes = 0
        self.events: list = []
        # scripted events: tick -> [(op, args...)]
        self.script: dict[int, list[tuple]] = {}
        # slot pool (the shared worker budget): repair drains first
        self.repair_queue: deque = deque()
        self.balance_queue: deque = deque()
        self.running: list[dict] = []
        self._repair_seen: dict[int, int] = {}   # vid -> consecutive passes
        self._repair_inflight: set[int] = set()
        self._balance_inflight: set[int] = set()
        self._pending_dst: dict[str, int] = {}   # node -> inflight adds
        # stats the scenarios assert on
        self.completed_moves: list[tuple] = []   # (tick, vid, src, dst, b)
        self.completed_repairs: list[tuple] = []  # (tick, vid, dst)
        self.moved_bytes = 0
        self.repaired_bytes = 0
        self.balance_start_while_repair_pending = 0

        # --- deterministic layout: nodes round-robin over DCs/racks,
        # volumes placed primary + rack-spread replicas ---
        self.nodes: list[SimNode] = []
        for i in range(nodes):
            dc = f"dc{i % dcs}"
            rack = f"r{(i // dcs) % racks_per_dc}"
            self.nodes.append(SimNode(
                f"{dc}.{rack}.n{i:04d}:8080", dc, rack,
                max_volumes=volumes_per_node * 4,
                stagger=i % refresh_every))
        copies = ReplicaPlacement.parse(replication).copy_count()
        total_volumes = nodes * volumes_per_node // copies
        self.total_bytes = total_volumes * copies * volume_bytes
        vid = 0
        for v in range(total_volumes):
            vid += 1
            holders = [self.nodes[v % nodes]]
            j = (v + 1) % nodes
            while len(holders) < copies:
                cand = self.nodes[j % nodes]
                if all((cand.dc, cand.rack) != (h.dc, h.rack)
                       for h in holders) and cand not in holders:
                    holders.append(cand)
                j += 1
            for h in holders:
                h.volumes[vid] = {"id": vid, "collection": "",
                                  "size": volume_bytes,
                                  "read_only": True,
                                  "replica_placement": replication,
                                  "ttl": ""}
        self._by_id = {n.id: n for n in self.nodes}

    # --- scripting ---

    def at(self, tick: int, op: str, *args) -> None:
        self.script.setdefault(tick, []).append((op, args))

    def node(self, idx: int) -> SimNode:
        return self.nodes[idx]

    def _apply_op(self, op: str, args: tuple) -> None:
        if op == "kill":
            n = self.nodes[args[0]]
            n.alive = False
            self._log("kill", node=n.id)
        elif op == "revive":
            n = self.nodes[args[0]]
            n.alive = True
            n.needs_full = True
            self._log("revive", node=n.id)
        elif op == "rack_loss":
            dc, rack = args
            for n in self.nodes:
                if n.alive and (n.dc, n.rack) == (dc, rack):
                    n.alive = False
            self._log("rack_loss", dc=dc, rack=rack)
        elif op == "heat":
            idx, vid, rate = args
            n = self.nodes[idx]
            if rate > 0.0 and vid in n.volumes:
                n.rates[vid] = float(rate)
            else:
                n.rates.pop(vid, None)
            self._log("heat", node=n.id, vid=vid, rate=round(rate, 6))
        elif op == "fault":
            point, action, p, count, fseed = args
            faults.set_fault(point, action, p=p, count=count, seed=fseed)
            self._log("fault_armed", point=point, action=action, p=p)
        else:
            raise ValueError(f"unknown scripted op {op!r}")

    # --- event log ---

    def _log(self, kind: str, **kw) -> None:
        self.events.append({"t": self.tick_no, "e": kind, **kw})

    def digest(self) -> str:
        blob = json.dumps(self.events, sort_keys=True,
                          separators=(",", ":")).encode()
        return hashlib.sha256(blob).hexdigest()

    # --- one tick ---

    def tick(self) -> None:
        self.tick_no += 1
        self.clock.advance(self.tick_seconds)
        now = self.clock.now()
        for op, args in self.script.get(self.tick_no, []):
            self._apply_op(op, args)
        # heartbeats through the REAL intake, gated by sim.heartbeat.
        # Full registration when the node's volume set changed (or on
        # its staggered refresh slot); otherwise the cheap beat path a
        # real idle node takes: liveness touch + changed-heat merge.
        for n in self.nodes:
            if not n.alive:
                continue
            if faults.fire("sim.heartbeat"):
                self._log("beat_lost", node=n.id)
                continue
            heat = [{"id": vid, "reads": int(rate * self.tick_seconds),
                     "writes": 0, "last_access": now, "read_rate": rate}
                    for vid, rate in sorted(n.rates.items())]
            dn = self.topology.nodes.get(n.id)
            if (n.needs_full or dn is None
                    or self.tick_no % self.refresh_every == n.stagger):
                ev = self.topology.register_heartbeat(
                    n.id, n.url, n.url, n.dc, n.rack, n.max_volumes,
                    {"volumes": [n.volumes[v] for v in sorted(n.volumes)],
                     "ec_shards": [], "heat": heat})
                n.needs_full = False
                if ev["new_vids"] or ev["deleted_vids"]:
                    self._log("loc_delta", node=n.id,
                              added=len(ev["new_vids"]),
                              removed=len(ev["deleted_vids"]))
            else:
                dn.last_seen = now
                if heat:
                    self.topology.merge_heat(n.url, heat)
        for ev in self.topology.prune_dead_nodes():
            self._log("pruned", node=ev["url"],
                      vids=len(ev["deleted_vids"]))
        if self.tick_no % self.repair_every == 0:
            self._repair_pass(now)
        if self.tick_no % self.balance_every == 0:
            self._balance_pass(now)
        self._drive_jobs()

    def run(self, ticks: int) -> None:
        for _ in range(ticks):
            self.tick()

    # --- repair planning: two-pass deficit confirmation, the repair-
    #     daemon discipline, placing through the REAL target rule ---

    def _repair_pass(self, now: float) -> None:
        deficits: dict[int, tuple] = {}
        for (coll, repl, ttl), layout in sorted(self.topology.layouts.items()):
            need = ReplicaPlacement.parse(repl).copy_count()
            for vid, locs in sorted(layout.locations.items()):
                if len(locs) < need and locs \
                        and vid not in self._repair_inflight:
                    deficits.setdefault(vid, (repl, locs))
        fresh: dict[int, int] = {}
        for vid, (repl, locs) in sorted(deficits.items()):
            count = self._repair_seen.get(vid, 0) + 1
            if count < 2:   # a deficit must be seen on consecutive passes
                fresh[vid] = count
                continue
            target = pick_replica_target(self.topology, repl, locs,
                                         pending=self._pending_dst)
            if target is None:
                self._log("repair_unplaceable", vid=vid)
                continue
            self._repair_inflight.add(vid)
            self._pending_dst[target.id] = \
                self._pending_dst.get(target.id, 0) + 1
            self.repair_queue.append({
                "kind": "repair", "vid": vid, "src": locs[0].id,
                "dst": target.id, "bytes": self.volume_bytes})
            self._log("repair_planned", vid=vid, src=locs[0].id,
                      dst=target.id)
        self._repair_seen = fresh

    # --- balance planning: the real planner + oscillation guard ---

    def _balance_pass(self, now: float) -> None:
        self.balance_passes += 1
        frozen = frozenset(self.state.frozen(now)
                           | self._balance_inflight)
        # seed FIXED at 0, mirroring the live daemon: the two-pass
        # confirmation needs consecutive passes to agree on (src, dst)
        plan = plan_moves(self.topology, self.cfg, now,
                          seed=0, frozen=frozen)
        confirmed = self.state.confirm(plan, now)
        for mv in confirmed:
            if mv.vid in self._balance_inflight:
                continue
            self._balance_inflight.add(mv.vid)
            self.balance_queue.append({
                "kind": "balance", "vid": mv.vid, "src": mv.src,
                "dst": mv.dst, "bytes": mv.bytes, "move": mv})
        if plan:
            self._log("balance_plan", proposed=len(plan),
                      confirmed=len(confirmed))

    # --- the shared worker-slot pool: repair drains before balance ---

    def _drive_jobs(self) -> None:
        for job in list(self.running):
            job["left"] -= 1
            if job["left"] <= 0:
                self.running.remove(job)
                self._complete(job)
        free = self.slots - len(self.running)
        while free > 0 and self.repair_queue:
            job = self.repair_queue.popleft()
            job["left"] = self.job_ticks
            self.running.append(job)
            self._log("repair_start", vid=job["vid"], dst=job["dst"])
            free -= 1
        while free > 0 and self.balance_queue:
            if self.repair_queue:
                # structurally unreachable (repair drained first) —
                # counted so the storm scenario can assert it stayed 0
                self.balance_start_while_repair_pending += 1
            job = self.balance_queue.popleft()
            job["left"] = self.job_ticks
            self.running.append(job)
            self._log("move_start", vid=job["vid"], src=job["src"],
                      dst=job["dst"],
                      repair_pending=len(self.repair_queue))
            free -= 1

    def _find(self, node_id: str) -> Optional[SimNode]:
        return self._by_id.get(node_id)

    def _complete(self, job: dict) -> None:
        vid = job["vid"]
        src = self._find(job["src"])
        dst = self._find(job["dst"])
        if job["kind"] == "repair":
            self._repair_inflight.discard(vid)
            self._pending_dst[job["dst"]] = max(
                self._pending_dst.get(job["dst"], 1) - 1, 0)
            if dst is None or not dst.alive \
                    or len(dst.volumes) >= dst.max_volumes:
                self._log("repair_failed", vid=vid, dst=job["dst"])
                return
            donor = src if src is not None and vid in src.volumes else None
            if donor is None:
                for n in self.nodes:
                    if n.alive and vid in n.volumes:
                        donor = n
                        break
            if donor is None:
                self._log("repair_failed", vid=vid, dst=job["dst"])
                return
            dst.volumes[vid] = dict(donor.volumes[vid])
            dst.needs_full = True
            self.completed_repairs.append((self.tick_no, vid, dst.id))
            self.repaired_bytes += job["bytes"]
            self._log("repair_done", vid=vid, dst=dst.id)
        else:
            self._balance_inflight.discard(vid)
            if (src is None or dst is None or not src.alive
                    or not dst.alive or vid not in src.volumes
                    or len(dst.volumes) >= dst.max_volumes):
                self._log("move_failed", vid=vid, src=job["src"],
                          dst=job["dst"])
                return
            # the move: volume AND its heat follow to the destination —
            # the next heartbeats (real intake) show the planner the
            # consequence of its own decision
            dst.volumes[vid] = src.volumes.pop(vid)
            rate = src.rates.pop(vid, 0.0)
            if rate > 0.0:
                dst.rates[vid] = rate
            src.needs_full = dst.needs_full = True
            self.state.record_done(job["move"], self.clock.now())
            self.completed_moves.append(
                (self.tick_no, vid, src.id, dst.id, job["bytes"]))
            self.moved_bytes += job["bytes"]
            self._log("move_done", vid=vid, src=src.id, dst=dst.id)

    # --- inspection helpers the scenarios assert with ---

    def max_node_rate(self) -> float:
        rates = node_rates(self.topology, self.clock.now())
        return max(rates.values()) if rates else 0.0

    def final_plan(self) -> list:
        """A fixpoint probe: what would the planner still move now?"""
        return plan_moves(self.topology, self.cfg, self.clock.now(),
                          seed=0, frozen=frozenset())

    def deficit_count(self) -> int:
        out = 0
        for (_, repl, _), layout in self.topology.layouts.items():
            need = ReplicaPlacement.parse(repl).copy_count()
            for vid, locs in layout.locations.items():
                if len(locs) < need:
                    out += 1
        return out
