"""CLI / CI gate: sweep seeds x scenarios, prove determinism, exit 1
on any violation.

    python -m seaweedfs_tpu.clustersim --seeds 2 --nodes 1000
    python -m seaweedfs_tpu.clustersim --scenarios skew --seed-base 7 --json

Every (scenario, seed) cell runs TWICE; differing digests are reported
as a determinism violation — the whole point of the virtual clock and
seeded RNG is that a failure report's seed is a complete reproduction
recipe (see README "Planet-scale control" for the replay runbook).
"""

from __future__ import annotations

import argparse
import json
import sys

from .scenarios import SCENARIOS, run_scenario


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m seaweedfs_tpu.clustersim",
        description="deterministic control-plane simulator sweep")
    ap.add_argument("--seeds", type=int, default=2,
                    help="seeds per scenario (default 2)")
    ap.add_argument("--seed-base", type=int, default=0,
                    help="first seed (replay a failed cell with "
                         "--seeds 1 --seed-base N)")
    ap.add_argument("--nodes", type=int, default=1000,
                    help="virtual nodes per run (default 1000)")
    ap.add_argument("--scenarios", default=",".join(SCENARIOS),
                    help=f"comma list of {','.join(SCENARIOS)}")
    ap.add_argument("--json", action="store_true",
                    help="emit the full report list as JSON")
    args = ap.parse_args(argv)

    names = [s for s in args.scenarios.split(",") if s]
    unknown = [s for s in names if s not in SCENARIOS]
    if unknown:
        ap.error(f"unknown scenario(s): {', '.join(unknown)}")

    reports, failed = [], 0
    for name in names:
        for seed in range(args.seed_base, args.seed_base + args.seeds):
            rep = run_scenario(name, seed, nodes=args.nodes)
            replay = run_scenario(name, seed, nodes=args.nodes)
            if replay["digest"] != rep["digest"]:
                rep["violations"].append(
                    f"NONDETERMINISTIC: seed {seed} produced digests "
                    f"{rep['digest'][:12]} and {replay['digest'][:12]}")
            reports.append(rep)
            status = "ok" if not rep["violations"] else "FAIL"
            if rep["violations"]:
                failed += 1
            print(f"[{status}] {name} seed={seed} nodes={rep['nodes']} "
                  f"ticks={rep['ticks']} moves={rep['moves']} "
                  f"repairs={rep['repairs']} "
                  f"digest={rep['digest'][:12]}", file=sys.stderr)
            for v in rep["violations"]:
                print(f"       violation: {v}", file=sys.stderr)
    if args.json:
        print(json.dumps(reports, indent=2, sort_keys=True))
    print(f"clustersim: {len(reports) - failed}/{len(reports)} cells "
          f"clean", file=sys.stderr)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
