"""Multi-chip EC fabric: the shard_map kernel surface (sharded.py) and
the production MeshCoder (mesh_coder.py). mesh_coder imports jax lazily;
sharded.py imports it at module load — servers that never encode should
import through mesh_coder only."""

from .mesh_coder import MeshCoder, coder, mesh_device_count, mesh_status

__all__ = ["MeshCoder", "coder", "mesh_device_count", "mesh_status"]
