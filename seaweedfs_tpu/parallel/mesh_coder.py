"""MeshCoder — the production ErasureCoder over a jax.sharding.Mesh.

`parallel/sharded.py` proved the kernel shape (MULTICHIP_r05: the encode
HLO is collective-free, linear weak scaling over an 8-device mesh); this
module is the production face: an `ErasureCoder` the streaming pipeline
(ec/pipeline.py), the store's `ec_generate`/`ec_rebuild`, and the
device-sink bench paths drive unchanged, with every [k, B] batch's B axis
sharded over the mesh so ONE governed host feed saturates N chips.

Sharding shape (the pipeline's batches are [k, B] — k shard rows of a
B-byte stripe batch):

- encode: columns are independent under RS (parity[:, j] depends only on
  data[:, j]), so the batch axis shards as P(None, "batch") and each chip
  runs the same GF kernel on its B/n column slice. No collectives — the
  property the MULTICHIP dryruns verify — so aggregate throughput is
  n * per-chip throughput on ICI-attached chips.
- rebuild: survivor rows land row-sharded P("batch", None) (the natural
  layout when shards stream in per-chip), are all_gather'd over ICI so
  every chip holds all k survivor rows, and each chip reconstructs the
  missing rows for its own column slice — the ICI analog of the
  reference's parallel shard fetch (weed/storage/store_ec.go:322-376).

Batch widths not divisible by the mesh size zero-pad to the next multiple
(GF parity of zero columns is zero, so padding never changes real bytes;
materialize slices the pad off). Output is byte-identical to the
single-chip JaxCoder and to striping.write_ec_files at every geometry —
tests/test_mesh_coder.py proves it at odd widths and RS(20,4).

Staging is per-chip: `stage_async` splits a host batch into per-device
column slices and device_puts each one separately (transfers overlap;
the pipeline's stager pool calls this from several threads), emitting an
`ec.stage.chip` span and per-chip byte/second counters into the shared
"ec" metrics registry next to the governor's gauges.

`WEED_EC_MESH_DEVICES` selects the mesh: unset/"0"/"1" means no mesh
(production paths keep the proven single-chip JaxCoder), "all" takes
every local device, N clamps to what the host has. A 1-device request
degenerates to a plain JaxCoder — `coder()` never returns a MeshCoder
wrapping one chip.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional, Sequence

import numpy as np

from .. import observe
from ..ec.coder import JaxCoder
from ..ops import gf256, rs_jax
from ..utils import metrics as metrics_mod
from ..utils.jax_compat import shard_map_compat


def mesh_device_count() -> int:
    """Devices WEED_EC_MESH_DEVICES asks for: 0 = mesh disabled (the
    default — virtual CPU test meshes must not silently reroute every
    production encode), "all" = every local device, N clamps to the
    host. Values <= 1 read as disabled: a 1-chip mesh IS the JaxCoder
    path."""
    raw = os.environ.get("WEED_EC_MESH_DEVICES", "").strip().lower()
    if not raw or raw in ("0", "1", "no", "false"):
        return 0
    import jax
    have = len(jax.devices())
    if raw == "all":
        return have if have > 1 else 0
    try:
        n = int(raw)
    except ValueError:
        return 0
    n = min(n, have)
    return n if n > 1 else 0


def coder(data_shards: int, parity_shards: int,
          n_devices: Optional[int] = None,
          method: Optional[str] = None):
    """The mesh-or-single factory: a MeshCoder over n_devices (default:
    WEED_EC_MESH_DEVICES, then all local devices) when that resolves to
    more than one chip, else the proven single-chip backend for
    `method` (JaxCoder, or PallasCoder for method="pallas").

    method=None defers to WEED_EC_FORMULATION (rs_jax.formulation_env),
    falling back to "bitplane" — so the operator's pin reaches the mesh
    path exactly like the single-chip one."""
    if n_devices is None:
        if os.environ.get("WEED_EC_MESH_DEVICES", "").strip():
            n_devices = mesh_device_count() or 1
        else:
            import jax
            n_devices = len(jax.devices())
    if n_devices <= 1:
        if method == "pallas":
            from ..ec.coder import PallasCoder
            return PallasCoder(data_shards, parity_shards)
        return JaxCoder(data_shards, parity_shards, method=method)
    return MeshCoder(data_shards, parity_shards, n_devices=n_devices,
                     method=method)


class _MeshHandle:
    """In-flight sharded result + the valid (pre-padding) width."""

    __slots__ = ("arr", "width")

    def __init__(self, arr, width: int):
        self.arr = arr
        self.width = width

    def copy_to_host_async(self) -> None:
        start = getattr(self.arr, "copy_to_host_async", None)
        if start is not None:
            start()


class MeshCoder(JaxCoder):
    """ErasureCoder over a jax.sharding.Mesh (axis "batch" = the stripe
    batch's column axis). See the module docstring for the sharding
    shape; everything the JaxCoder exposes (digest windows, staged
    sinks, reconstruct) works here, mesh-sharded where it counts."""

    _VALID_METHODS = frozenset(rs_jax.FORMULATIONS) | {"pallas"}

    def __init__(self, data_shards: int, parity_shards: int,
                 n_devices: Optional[int] = None,
                 method: Optional[str] = None):
        method = method or rs_jax.formulation_env() or "bitplane"
        if method not in self._VALID_METHODS:
            raise ValueError(f"unknown mesh coder method {method!r}")
        # always pass the resolved method down: a mesh coder's sharded
        # executables are built for one formulation, so it stays pinned
        # (retune_formulation is a no-op here)
        super().__init__(data_shards, parity_shards, method=method)
        import jax
        from jax.sharding import Mesh
        devs = jax.devices()
        n = n_devices or len(devs)
        if n < 2:
            raise ValueError("MeshCoder needs >= 2 devices; use JaxCoder "
                             "(or parallel.mesh_coder.coder) for one chip")
        if len(devs) < n:
            raise ValueError(f"need {n} devices, have {len(devs)}")
        self.mesh = Mesh(np.array(devs[:n]), ("batch",))
        self.mesh_devices = n
        self._devices = list(devs[:n])
        self._enc_sharded = None
        self._rec_sharded: dict = {}
        self._lock = threading.Lock()
        metrics_mod.shared("ec").gauge("feed_mesh_devices", n)

    # --- staging: per-chip sub-batches ---

    def _pad_cols(self, arr: np.ndarray) -> np.ndarray:
        pad = (-arr.shape[-1]) % self.mesh_devices
        if pad:
            width = [(0, 0)] * (arr.ndim - 1) + [(0, pad)]
            arr = np.pad(arr, width)
        return arr

    def _col_sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec as P
        return NamedSharding(self.mesh, P(None, "batch"))

    def _stage_cols(self, arr: np.ndarray):
        """device_put one per-chip column slice per device and assemble
        the sharded array — transfers overlap (device_put is async), and
        each chip's H2D is visible as its own ec.stage.chip span plus
        feed_chip_staged_bytes / feed_chip_stage_seconds counters."""
        import jax
        n = self.mesh_devices
        cols = arr.shape[1] // n
        ctx = observe.ensure_ctx("ec")
        reg = metrics_mod.shared("ec")
        shards = []
        for i, dev in enumerate(self._devices):
            start_us = int(time.time() * 1e6)
            t0 = time.perf_counter()
            piece = np.ascontiguousarray(arr[:, i * cols:(i + 1) * cols])
            shards.append(jax.device_put(piece, dev))
            dur = time.perf_counter() - t0
            observe.record_span("ec.stage.chip", ctx, start_us,
                                int(dur * 1e6),
                                tags={"chip": i, "bytes": piece.nbytes})
            reg.count("feed_chip_staged_bytes", value=piece.nbytes,
                      labels={"chip": str(i)})
            reg.count("feed_chip_stage_seconds", value=round(dur, 6),
                      labels={"chip": str(i)})
        return jax.make_array_from_single_device_arrays(
            arr.shape, self._col_sharding(), shards)

    def stage_async(self, data: np.ndarray):
        arr = self._pad_cols(np.asarray(data, dtype=np.uint8))
        return self._stage_cols(arr)

    # --- encode: shard_map over the batch axis, collective-free ---

    def _apply_matrix_fn(self, matrix: np.ndarray):
        """The per-chip GF kernel for this coder's method — pallas keeps
        the hand-tiled TPU kernel inside the shard_map step (the demo's
        _apply_fn shape), bitplane/lut ride the rs_jax formulations."""
        if self.method == "pallas":
            from ..ops import rs_pallas
            return rs_pallas.gf_apply_pallas(matrix)
        if self.method == "bitplane":
            return rs_jax.gf_apply_bitplane(matrix)
        if self.method == "xorsched":
            # pure elementwise per-chip (pack -> XOR schedule -> unpack,
            # no cross-column ops), so shard_map stays collective-free —
            # tests assert it on the compiled HLO
            return rs_jax.gf_apply_xorsched(matrix)
        return rs_jax.gf_apply_lut(matrix)

    # inherited digest windows route through these two hooks, so the
    # mesh's pallas/lut choice holds there too instead of silently
    # remapping to another formulation
    def _encode_fn(self):
        if self.method == "pallas":
            return self._apply_matrix_fn(
                gf256.parity_matrix(self.k, self.m))
        return super()._encode_fn()

    def _rec_apply(self, present, missing):
        if self.method == "pallas":
            return self._apply_matrix_fn(gf256.reconstruction_matrix(
                self.k, self.m, tuple(present), tuple(missing)))
        return super()._rec_apply(present, missing)

    def _enc_fn(self):
        with self._lock:
            if self._enc_sharded is None:
                import jax
                from jax.sharding import PartitionSpec as P
                apply_fn = self._apply_matrix_fn(
                    gf256.parity_matrix(self.k, self.m))
                step = shard_map_compat(apply_fn, self.mesh,
                                        P(None, "batch"),
                                        P(None, "batch"))
                self._enc_sharded = jax.jit(step)
            return self._enc_sharded

    def encode_async(self, data: np.ndarray):
        width = int(data.shape[1])
        arr = self._pad_cols(np.asarray(data, dtype=np.uint8))
        return _MeshHandle(self._enc_fn()(self._stage_cols(arr)), width)

    def encode(self, data: np.ndarray) -> np.ndarray:
        return self.materialize(self.encode_async(data))

    def materialize(self, handle) -> np.ndarray:
        if isinstance(handle, _MeshHandle):
            out = np.asarray(handle.arr)
            return out[..., :handle.width]
        return super().materialize(handle)

    def encode_hlo_text(self, width: Optional[int] = None) -> str:
        """Compiled HLO of the sharded encode at `width` (default: one
        tile per chip) — what the multichip bench and tests inspect for
        the collective-free property."""
        import jax
        import jax.numpy as jnp
        w = width or 1024 * self.mesh_devices
        sds = jax.ShapeDtypeStruct((self.k, w), jnp.uint8)
        return self._enc_fn().lower(sds).compile().as_text()

    def encode_is_collective_free(self,
                                  width: Optional[int] = None) -> bool:
        text = self.encode_hlo_text(width).lower()
        return not any(tok in text for tok in
                       ("all-reduce", "all-gather", "collective-permute",
                        "all-to-all"))

    # --- rebuild: row-sharded survivors, all_gather over ICI ---

    def _rec_fn(self, present: tuple, missing: tuple):
        key = (present, missing)
        with self._lock:
            fn = self._rec_sharded.get(key)
            if fn is None:
                import jax
                import jax.numpy as jnp
                from jax.sharding import PartitionSpec as P
                rec = gf256.reconstruction_matrix(self.k, self.m, present,
                                                  missing)
                apply_fn = self._apply_matrix_fn(rec)
                n_dev = self.mesh_devices
                k = self.k

                def step(survivors):  # [k_pad/n, B] rows on each chip
                    full = jax.lax.all_gather(survivors, "batch", axis=0,
                                              tiled=True)[:k]
                    cols = full.shape[1] // n_dev
                    idx = jax.lax.axis_index("batch")
                    local = jax.lax.dynamic_slice(
                        full, (0, idx * cols), (k, cols))
                    return apply_fn(local)

                fn = jax.jit(shard_map_compat(
                    step, self.mesh, P("batch", None), P(None, "batch")))
                self._rec_sharded[key] = fn
            return fn

    def _stage_rows(self, arr: np.ndarray):
        """Row-shard [k_pad, B] survivors over the mesh (pad rows to a
        mesh multiple; the all_gather drops the pad)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        n = self.mesh_devices
        pad = (-arr.shape[0]) % n
        if pad:
            arr = np.pad(arr, ((0, pad), (0, 0)))
        rows = arr.shape[0] // n
        shards = [jax.device_put(
            np.ascontiguousarray(arr[i * rows:(i + 1) * rows]), dev)
            for i, dev in enumerate(self._devices)]
        return jax.make_array_from_single_device_arrays(
            arr.shape, NamedSharding(self.mesh, P("batch", None)), shards)

    def rec_apply_async(self, present, missing):
        present, missing = tuple(present), tuple(missing)
        fn = self._rec_fn(present, missing)

        def run(survivors: np.ndarray):
            width = int(survivors.shape[1])
            arr = self._pad_cols(np.asarray(survivors, dtype=np.uint8))
            return _MeshHandle(fn(self._stage_rows(arr)), width)

        return run

    # --- window sinks ---
    # The inherited JaxCoder window executables work unchanged: staged
    # batches arrive column-sharded from stage_async and GSPMD partitions
    # the dynamic-matrix digest program along the batch axis (the final
    # [m] digest sum is the only cross-chip reduction, 4*m bytes). AOT
    # warming is a tunneled-link optimization whose unsharded abstract
    # shapes would compile a single-device program the sharded call
    # could not reuse — on a mesh the compile happens at first dispatch.

    def _dyn_window_builder(self):
        # mesh staging is per-chip BYTE column slices (the packed
        # bit-plane transpose would couple stripe columns across the
        # 32-bit word, fighting the column sharding), so xorsched windows
        # ride the byte-domain dyn program here; the sharded encode
        # kernel itself (_apply_matrix_fn) still runs the XOR schedule
        if self.method in ("bitplane", "xorsched"):
            return self._dyn_window_fn
        return None

    def warm_encode_digest_window(self, n_batches: int,
                                  shape: tuple) -> None:
        return None

    def warm_rec_digest_window(self, present, missing, n_batches: int,
                               shape: tuple) -> None:
        return None


def mesh_status() -> dict:
    """Per-process mesh/EC-feed status for /admin/ec/mesh_status and the
    ec.mesh.status shell command: the configured mesh, the devices jax
    actually sees (enumerated only when the operator opted into a mesh
    or one is already live — a status probe on a mesh-less server must
    not pay jax backend init), and the per-chip staging + governor
    state from the shared "ec" registry."""
    reg = metrics_mod.shared("ec")
    feed = reg.snapshot(prefix="feed_")
    chips: dict[str, dict] = {}
    for key, value in sorted(feed.items()):
        if key.startswith("feed_chip_") and '{chip="' in key:
            name, _, rest = key.partition("{")
            chip = rest.split('"')[1]
            field = name[len("feed_chip_"):]
            chips.setdefault(chip, {})[field] = value
    out = {
        "requested": os.environ.get("WEED_EC_MESH_DEVICES", ""),
        "mesh_devices": int(feed.get("feed_mesh_devices", 0) or 0),
        "chips": chips,
        "feed": {k: v for k, v in feed.items()
                 if not k.startswith("feed_chip_")},
    }
    if out["mesh_devices"] > 0 or out["requested"].strip():
        import jax
        out["devices"] = [{"id": d.id, "platform": d.platform}
                          for d in jax.devices()]
        out["backend"] = jax.default_backend()
    else:
        out["devices"] = None  # no mesh configured: skip backend init
    return out
