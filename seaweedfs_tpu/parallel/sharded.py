"""Multi-chip EC kernels: pjit/shard_map over a device mesh.

The reference scales encode/rebuild by spreading work across volume servers
over gRPC (weed/shell/command_ec_encode.go:160-263, parallel shard fetch in
weed/storage/store_ec.go:322-376). The TPU-native equivalent keeps that
inter-node fabric, and *inside* a host scales across chips with a
jax.sharding.Mesh:

- axis "batch": stripe-row batches are data-parallel — each chip encodes its
  slice of the row batch with the fused Pallas kernel. No collectives on the
  encode path (the code is systematic), so throughput scales linearly over
  ICI-attached chips.
- rebuild: surviving shards live sharded across chips (axis "shard"); the
  reconstruction is an all_gather of the k needed survivor rows over ICI
  followed by the same GF matmul kernel — the ICI analog of the reference's
  parallel goroutine fetch from 10 peer nodes.

Everything is jit-compiled once per (geometry, mesh) and uses static shapes.
This module is the [B, k, n]-batched kernel surface (and the shape the
MULTICHIP dryruns measure); the production EC plane drives the same
shard_map machinery through parallel/mesh_coder.py's MeshCoder, which
implements the ErasureCoder interface over the pipeline's [k, B] batches.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import gf256, rs_jax, rs_pallas
from ..utils.jax_compat import shard_map_compat


def make_mesh(n_devices: int | None = None,
              axis_name: str = "batch") -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    if len(devs) < n:
        raise ValueError(f"need {n} devices, have {len(devs)}")
    return Mesh(np.array(devs[:n]), (axis_name,))


def _apply_fn(matrix: np.ndarray, use_pallas: bool):
    if use_pallas:
        return rs_pallas.gf_apply_pallas(matrix)
    return rs_jax.gf_apply_bitplane(matrix)


# The compiled-fn caches key on the Mesh itself (hashable by device ids +
# axis names), so the lru_cache IS the registry: bounded at maxsize
# entries, evicted LRU, nothing module-global pinning every mesh ever
# built. (The previous _MESHES dict grew monotonically and kept evicted
# entries' meshes alive forever.)

@functools.lru_cache(maxsize=32)
def _sharded_encode_fn(k: int, m: int, mesh: Mesh, use_pallas: bool):
    pm = gf256.parity_matrix(k, m)
    apply_fn = _apply_fn(pm, use_pallas)

    def step(data):  # [b_local, k, n] uint8 per device
        b, kk, n = data.shape
        # fold the local batch into the stripe width: one wide kernel call
        flat = jnp.transpose(data, (1, 0, 2)).reshape(kk, b * n)
        parity = apply_fn(flat)
        return jnp.transpose(parity.reshape(-1, b, n), (1, 0, 2))

    shard_step = shard_map_compat(step, mesh, P("batch", None, None),
                                  P("batch", None, None))
    return jax.jit(shard_step)


def sharded_encode(mesh: Mesh, data, parity_shards: int = 4,
                   use_pallas: bool | None = None):
    """data [B, k, n] uint8 (B divisible by mesh size) -> parity [B, m, n].

    B is sharded over the mesh "batch" axis; each chip runs the fused kernel
    on its local rows.
    """
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    b, k, n = data.shape
    assert b % mesh.devices.size == 0, (b, mesh.devices.size)
    fn = _sharded_encode_fn(k, parity_shards, mesh, use_pallas)
    spec = NamedSharding(mesh, P("batch", None, None))
    data = jax.device_put(data, spec)
    return fn(data)


@functools.lru_cache(maxsize=32)
def _sharded_rebuild_fn(k: int, m: int, present: tuple[int, ...],
                        missing: tuple[int, ...], mesh: Mesh,
                        use_pallas: bool):
    """Survivor shards sharded over chips; all_gather + GF matmul rebuild."""
    rec = gf256.reconstruction_matrix(k, m, present, missing)
    apply_fn = _apply_fn(rec, use_pallas)
    n_dev = mesh.devices.size

    def step(survivors):  # [k_padded, n] rows sharded over "batch"
        # ICI collective: every chip needs all k survivor rows
        full = jax.lax.all_gather(survivors, "batch", axis=0, tiled=True)
        full = full[:k]  # drop mesh-size padding rows
        # each chip rebuilds a slice of the column space
        n = full.shape[1]
        cols = n // n_dev
        idx = jax.lax.axis_index("batch")
        local = jax.lax.dynamic_slice(full, (0, idx * cols), (k, cols))
        return apply_fn(local)

    shard_step = shard_map_compat(step, mesh, P("batch", None),
                                  P(None, "batch"))
    return jax.jit(shard_step)


def sharded_rebuild(mesh: Mesh, shards: list, k: int, m: int,
                    use_pallas: bool | None = None):
    """Rebuild missing shards with survivors distributed across the mesh.

    shards: length k+m list with None for missing. Survivor rows are laid out
    sharded over the "batch" axis (pad to mesh size), all-gathered over ICI,
    and each chip computes the missing rows for its column slice.
    """
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    present = tuple(i for i, s in enumerate(shards) if s is not None)
    missing = tuple(i for i, s in enumerate(shards) if s is None)
    if len(present) < k:
        raise ValueError("too few shards")
    basis = present[:k]
    survivors = np.stack([np.asarray(shards[i], dtype=np.uint8)
                          for i in basis])
    n_dev = mesh.devices.size
    n = survivors.shape[1]
    pad_rows = (-survivors.shape[0]) % n_dev
    pad_cols = (-n) % n_dev  # each chip rebuilds an equal column slice
    if pad_rows or pad_cols:
        survivors = np.pad(survivors, ((0, pad_rows), (0, pad_cols)))
    fn = _sharded_rebuild_fn(k, m, basis, missing, mesh, use_pallas)
    spec = NamedSharding(mesh, P("batch", None))
    out = fn(jax.device_put(jnp.asarray(survivors), spec))
    result = list(shards)
    rebuilt = np.asarray(out)[:, :n]
    for row, tgt in enumerate(missing):
        result[tgt] = rebuilt[row]
    return result
