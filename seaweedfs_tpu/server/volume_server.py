"""Volume server: HTTP data path + admin API + master heartbeat loop.

Mirrors the reference volume server surface
(weed/server/volume_server_handlers_read.go / _write.go for the data path;
weed/server/volume_grpc_*.go for admin — here as JSON-over-HTTP):

  data:   GET/HEAD/POST/DELETE /<vid>,<fid>
  admin:  POST /admin/assign_volume       (AllocateVolume)
          POST /admin/vacuum              (VacuumVolume*)
          POST /admin/volume/delete
          POST /admin/volume/readonly
          POST /admin/ec/generate         (VolumeEcShardsGenerate)
          POST /admin/ec/mount            (VolumeEcShardsMount)
          POST /admin/ec/unmount          (VolumeEcShardsUnmount)
          POST /admin/ec/rebuild          (VolumeEcShardsRebuild)
          POST /admin/ec/copy             (VolumeEcShardsCopy — pull model)
          POST /admin/ec/delete_shards    (VolumeEcShardsDelete)
          POST /admin/ec/blob_delete      (VolumeEcBlobDelete)
          POST /admin/ec/to_volume        (VolumeEcShardsToVolume)
          GET  /admin/ec/shard_read?volume=&shard=&offset=&size=
          GET  /status, /metrics, /healthz

Replicated writes fan out with type=replicate exactly like the reference
(weed/topology/store_replicate.go:21-161): the first server writes locally
then POSTs the same body to every replica; all must ack.
"""

from __future__ import annotations

import asyncio
import email.parser
import functools
import logging
import os
import socket
import time
import uuid
from typing import Optional

import aiohttp
from aiohttp import web

from .. import faults, observe, overload
from ..lifecycle.heat import HeatTracker
from ..storage.file_id import FileId
from ..utils import compression, fast_multipart
from ..utils import retry as _retry
from ..storage.needle import (FLAG_IS_COMPRESSED,
                              FLAG_HAS_LAST_MODIFIED, FLAG_HAS_MIME,
                              FLAG_HAS_NAME, FLAG_HAS_TTL, CrcError,
                              Needle)
from ..storage import types as t
from ..storage.store import Store, safe_collection
from ..storage.volume import (NeedleDeleted, NeedleExpired, NeedleNotFound,
                              VolumeReadOnly)
from ..security.guard import Guard, token_from_request
from ..utils import metrics as metrics_mod

log = logging.getLogger("volume")


def _resize_image(data: bytes, mime: str, width: int, height: int,
                  mode: str) -> bytes:
    """Resize an image payload (weed/images/resizing.go): 'fit' keeps the
    aspect ratio inside the box, 'fill' crops to exactly fill it."""
    import io

    from PIL import Image

    img = Image.open(io.BytesIO(data))
    fmt = img.format or mime.split("/")[-1].upper()
    w = width or img.width
    h = height or img.height
    if mode == "fill":
        from PIL import ImageOps
        img = ImageOps.fit(img, (w, h))
    else:
        img.thumbnail((w, h))
    out = io.BytesIO()
    img.save(out, format=fmt)
    return out.getvalue()


class WriteBatcher:
    """Per-volume async write coalescing — the server half of the
    reference's batching worker (volume_read_write.go:297-327): up to 128
    requests or 4MB land in one executor call and one engine flush, so
    concurrent small writes stop paying a thread-pool hop each.
    """

    MAX_BATCH = 128
    MAX_BYTES = 4 * 1024 * 1024
    INLINE_BYTES = 256 * 1024  # below this a batch writes on the loop
    IDLE_SECONDS = 30.0  # worker exits after this long with no writes

    def __init__(self, store: Store, group_commit_us: Optional[int] = None):
        self.store = store
        self._queues: dict[int, asyncio.Queue] = {}
        self._workers: dict[int, asyncio.Task] = {}
        # group commit: hold the batch open for this many µs so
        # concurrent small writes coalesce into ONE gathered writev +
        # ONE fsync barrier; acks release only after the barrier
        # (storage/volume.py _write_needles_group). 0 = off (default):
        # the proven drain-what's-queued path with no added latency.
        if group_commit_us is None:
            try:
                group_commit_us = int(os.environ.get(
                    "WEED_VOLUME_GROUP_COMMIT_US", "0") or 0)
            except ValueError:
                group_commit_us = 0
        self.group_commit_us = max(0, group_commit_us)

    async def write(self, vid: int, needle) -> tuple[int, int, bool]:
        # (measured: an uncontended inline shortcut here is neutral at
        # c=16 — the queue is rarely empty under load and the probe cost
        # is paid on every write — so the single queue path stays)
        q = self._queues.get(vid)
        if q is None:
            q = self._queues[vid] = asyncio.Queue()
            self._workers[vid] = asyncio.create_task(self._worker(vid, q))
        fut = asyncio.get_event_loop().create_future()
        q.put_nowait((needle, fut))
        result = await fut
        if isinstance(result, Exception):
            raise result
        return result

    async def _worker(self, vid: int, q: asyncio.Queue) -> None:
        loop = asyncio.get_event_loop()
        while True:
            try:
                needle, fut = await asyncio.wait_for(
                    q.get(), timeout=self.IDLE_SECONDS)
            except asyncio.TimeoutError:
                # submit's critical section (dict get → put_nowait) has no
                # awaits, so checking emptiness here and deleting is safe:
                # anything enqueued after the timeout fired makes q
                # non-empty and we keep running
                if q.empty():
                    self._queues.pop(vid, None)
                    self._workers.pop(vid, None)
                    return
                continue
            batch = [(needle, fut)]
            size = len(needle.data)
            while (len(batch) < self.MAX_BATCH and size < self.MAX_BYTES
                   and not q.empty()):
                n2, f2 = q.get_nowait()
                batch.append((n2, f2))
                size += len(n2.data)
            if self.group_commit_us > 0:
                # hold the commit window open: anything arriving before
                # the deadline rides this group's single fsync
                deadline = loop.time() + self.group_commit_us / 1e6
                while (len(batch) < self.MAX_BATCH
                       and size < self.MAX_BYTES):
                    remaining = deadline - loop.time()
                    if remaining <= 0:
                        break
                    try:
                        n2, f2 = await asyncio.wait_for(q.get(), remaining)
                    except asyncio.TimeoutError:
                        break
                    batch.append((n2, f2))
                    size += len(n2.data)
                    while (len(batch) < self.MAX_BATCH
                           and size < self.MAX_BYTES and not q.empty()):
                        n3, f3 = q.get_nowait()
                        batch.append((n3, f3))
                        size += len(n3.data)
            v = self.store.find_volume(vid)
            if v is None:
                # volume deleted/unmounted (or bogus vid): fail the batch
                # and retire this worker instead of idling forever
                err = KeyError(f"volume {vid} not found")
                for _, f in batch:
                    if not f.done():
                        f.set_exception(err)
                if q.empty():
                    self._queues.pop(vid, None)
                    self._workers.pop(vid, None)
                    return
                continue
            try:
                ns = [n for n, _ in batch]
                results = None
                if self.group_commit_us > 0:
                    # the group path always takes the executor: it ends
                    # in an fsync barrier (never loop-inline), and the
                    # acks below release only after that barrier
                    results = await loop.run_in_executor(
                        None, functools.partial(
                            v.write_needles_batch, ns, group_commit=True))
                elif size <= self.INLINE_BYTES:
                    # small batches: buffered page-cache appends finish in
                    # microseconds, while the executor handoff costs two GIL
                    # convoys (~ms on few-core hosts). The nowait variant
                    # declines (None) when the volume lock is contended
                    # (vacuum) or the backend isn't local disk, so the loop
                    # is never blocked on slow IO.
                    results = v.write_needles_batch_nowait(ns)
                if results is None:
                    results = await loop.run_in_executor(
                        None, v.write_needles_batch, ns)
            except Exception as e:
                results = [e] * len(batch)
            for (_, f), res in zip(batch, results):
                if f.done():
                    continue
                # engine errors come back in-place; surface per-request
                f.set_result(res)

    def stop(self) -> None:
        for t in self._workers.values():
            t.cancel()


class VolumeServer:
    def __init__(self, store: Store, master_url: str, url: str,
                 public_url: str = "", data_center: str = "", rack: str = "",
                 pulse_seconds: float = 5.0, read_redirect: bool = False,
                 guard: Optional[Guard] = None,
                 use_grpc_heartbeat: bool = False,
                 master_grpc_target: str = "",
                 grpc_port: int = 0,
                 tls=None,
                 scrub_interval_seconds: Optional[float] = None,
                 internal_token: Optional[str] = None,
                 shard_ctx=None):
        self.use_grpc_heartbeat = use_grpc_heartbeat
        # explicit gRPC endpoint override; default follows the
        # HTTP-port+10000 convention (grpc_client_server.go)
        self.master_grpc_target = master_grpc_target
        self.store = store
        # master_url may be a comma-separated HA list; heartbeats follow the
        # raft leader hint and rotate on failure
        # (weed/server/volume_grpc_client_to_master.go:50-86)
        self.masters = [m.strip() for m in master_url.split(",") if m.strip()]
        self.master_url = self.masters[0]
        self.url = url
        self.public_url = public_url or url
        self.data_center = data_center
        self.rack = rack
        self.pulse_seconds = pulse_seconds
        self.read_redirect = read_redirect
        self.guard = guard or Guard()
        self.volume_size_limit = 30 * 1024 * 1024 * 1024
        self.metrics = metrics_mod.Registry("volume")
        self._hb_task: Optional[asyncio.Task] = None
        self._session: Optional[aiohttp.ClientSession] = None
        self._batcher: Optional[WriteBatcher] = None
        self.grpc_port = grpc_port
        self.tls = tls
        self._grpc_server = None
        self._replica_cache: dict[int, tuple[list[str], float]] = {}
        self._shard_loc_cache: dict[int, tuple[dict, float]] = {}
        self._peer_grpc_channels: dict[str, object] = {}
        self._peer_grpc_dead: dict[str, float] = {}
        self._repair_neg: dict[str, float] = {}
        self._repair_inflight = 0
        # EC scrubber: low-priority digest verify of local shards
        # (WEED_EC_SCRUB_INTERVAL seconds; 0 disables)
        if scrub_interval_seconds is None:
            try:
                scrub_interval_seconds = float(
                    os.environ.get("WEED_EC_SCRUB_INTERVAL", "3600"))
            except ValueError:
                scrub_interval_seconds = 3600.0
        self.scrub_interval_seconds = scrub_interval_seconds
        self._scrub_task: Optional[asyncio.Task] = None
        # per-volume access heat (lifecycle plane): O(1) sampling on the
        # read/write paths — both this app's handlers and the fastpath
        # listener's inline shapes — drained as deltas into heartbeats.
        # WEED_LIFECYCLE_HEAT_HALFLIFE shrinks the EWMA window so tests
        # (and aggressive un-EC policies) see rate changes quickly.
        try:
            halflife = float(
                os.environ.get("WEED_LIFECYCLE_HEAT_HALFLIFE", "0") or 0)
        except ValueError:
            halflife = 0.0
        self.heat = HeatTracker(halflife=halflife) if halflife > 0 \
            else HeatTracker()
        # per-process secret marking requests proxied from the fastpath
        # listener (server/fastpath.py): they arrive from 127.0.0.1 but
        # were already whitelist-checked against the REAL peer IP.  In a
        # shard fleet the token is minted pre-fork and shared, so any
        # shard's fastpath can proxy cross-shard to the owner's loopback
        # app and still be treated as pre-admitted.
        if internal_token:
            self._internal_token = internal_token
        else:
            import secrets as _secrets
            self._internal_token = _secrets.token_hex(16)
        self._fast_srv = None
        # share-nothing shard fleet handle (server/sharded.py); None in
        # the single-process path
        self.shard_ctx = shard_ctx
        self._stripe_task: Optional[asyncio.Task] = None
        # overload plane: repair/scrub/vacuum traffic (tagged bg by its
        # originators) sheds before the user data plane
        self.admission = overload.AdmissionController(
            "volume", metrics=self.metrics,
            system_paths=overload.VOLUME_SYSTEM_PATHS)
        self.app = self._build_app()
        # the EC read path fetches missing shards from peers through this
        store._remote_shard_reader = self._make_shard_reader

    def shard_route(self, vid: int) -> Optional[int]:
        """Loopback port of the sibling shard owning ``vid``, or None to
        handle locally.  Local store ALWAYS wins (legacy volumes all
        live in shard 0's base dir — the modulo map must never shadow
        them); EC volumes stay local too (the EC read path does its own
        peer fetches).  Called per-request from the fastpath dispatch,
        so the checks are dict probes, not IO."""
        ctx = self.shard_ctx
        if ctx is None or ctx.shards <= 1:
            return None
        if self.store.find_volume(vid) is not None \
                or self.store.find_ec_volume(vid) is not None:
            return None
        return ctx.lookup_volume_port(vid)

    def _build_app(self) -> web.Application:
        @web.middleware
        async def guard_mw(request: web.Request, handler):
            # IP whitelist wraps every route except liveness, admin surface
            # included (Guard.WhiteList, weed/security/guard.go:53); the
            # per-fid JWT check on the data path happens in data_handler.
            # Requests proxied from the fastpath listener carry the
            # per-process token: they were already checked against the
            # real peer IP (this listener only sees 127.0.0.1 for them).
            if request.path != "/healthz":
                if (request.headers.get("X-Swfs-Internal")
                        != self._internal_token
                        and not self.guard.check_whitelist(
                            request.remote or "")):
                    return web.json_response({"error": "ip not allowed"},
                                             status=403)
            return await handler(request)

        # tracing outermost: denied requests still record a span; the
        # whitelist guard BEFORE admission — an off-whitelist flood
        # must burn a cheap 403, not drain admission tokens and queue
        # slots (shedding whitelisted traffic and locking out bg
        # repair with zero real overload); requests proxied from the
        # fastpath were admitted there already (internal token)
        app = web.Application(
            client_max_size=256 * 1024 * 1024,
            middlewares=[observe.trace_middleware("volume", self.url),
                         guard_mw,
                         overload.admission_middleware(
                             self.admission,
                             internal_token=lambda: self._internal_token)])
        app.router.add_post("/admin/assign_volume", self.admin_assign_volume)
        app.router.add_post("/admin/vacuum", self.admin_vacuum)
        app.router.add_get("/admin/vacuum/check", self.admin_vacuum_check)
        app.router.add_post("/admin/vacuum/compact",
                            self.admin_vacuum_compact)
        app.router.add_post("/admin/vacuum/commit", self.admin_vacuum_commit)
        app.router.add_post("/admin/vacuum/cleanup",
                            self.admin_vacuum_cleanup)
        app.router.add_post("/admin/volume/delete", self.admin_volume_delete)
        app.router.add_post("/admin/volume/readonly", self.admin_readonly)
        app.router.add_post("/admin/volume/mount", self.admin_volume_mount)
        app.router.add_post("/admin/volume/unmount",
                            self.admin_volume_unmount)
        app.router.add_post("/admin/volume/configure_replication",
                            self.admin_volume_configure)
        app.router.add_get("/admin/volume/needle_ids", self.admin_needle_ids)
        app.router.add_get("/admin/needle_raw", self.admin_needle_raw)
        app.router.add_post("/admin/tier/upload", self.admin_tier_upload)
        app.router.add_post("/admin/tier/download", self.admin_tier_download)
        app.router.add_post("/admin/ec/generate", self.admin_ec_generate)
        app.router.add_post("/admin/ec/fused", self.admin_ec_fused)
        app.router.add_post("/admin/ec/mount", self.admin_ec_mount)
        app.router.add_post("/admin/ec/unmount", self.admin_ec_unmount)
        app.router.add_post("/admin/ec/rebuild", self.admin_ec_rebuild)
        app.router.add_post("/admin/ec/copy", self.admin_ec_copy)
        app.router.add_post("/admin/ec/delete_shards",
                            self.admin_ec_delete_shards)
        app.router.add_post("/admin/ec/blob_delete", self.admin_ec_blob_delete)
        app.router.add_post("/admin/ec/to_volume", self.admin_ec_to_volume)
        app.router.add_get("/admin/ec/shard_read", self.admin_ec_shard_read)
        app.router.add_post("/admin/ec/scrub", self.admin_ec_scrub)
        app.router.add_get("/admin/ec/mesh_status",
                           self.admin_ec_mesh_status)
        _faults_handler = faults.admin_handler()
        app.router.add_get("/admin/faults", _faults_handler)
        app.router.add_post("/admin/faults", _faults_handler)
        app.router.add_get("/admin/file_copy", self.admin_file_copy)
        app.router.add_get("/admin/tail", self.admin_tail)
        app.router.add_post("/admin/volume/copy", self.admin_volume_copy)
        app.router.add_post("/admin/batch_delete", self.admin_batch_delete)
        app.router.add_post("/admin/query", self.admin_query)
        app.router.add_get("/status", self.status)
        app.router.add_get("/metrics", self.metrics_handler)
        app.router.add_get("/healthz",
                           overload.healthz_handler(self.admission,
                                                    shard_ctx=self.shard_ctx))
        from ..observe import profiler, wideevents
        app.router.add_get("/debug/profile", profiler.profile_handler())
        app.router.add_get("/debug/trace", observe.trace_handler())
        overload.reserve_ops(app, "/debug/pprof", profiler.pprof_handler())
        overload.reserve_ops(app, "/debug/events",
                             wideevents.events_handler())
        app.router.add_get("/ui", self.status_ui)
        app.router.add_route("*", "/{fid:[^{}]*}", self.data_handler)
        app.on_startup.append(self._on_startup)
        app.on_cleanup.append(self._on_cleanup)
        return app

    async def _on_startup(self, app) -> None:
        from ..observe import profiler
        profiler.ensure_started()
        self._session = aiohttp.ClientSession(
            # connect/inactivity bounds with no total cap: replicate
            # fan-out and heartbeats must never hang on a dead peer,
            # while multi-GB volume/shard copies stream as long as bytes
            # keep flowing
            timeout=aiohttp.ClientTimeout(total=None, sock_connect=10,
                                          sock_read=60),
            trace_configs=[observe.client_trace_config()])
        await self.admission.start()
        self._batcher = WriteBatcher(self.store)
        self._hb_task = asyncio.create_task(self._heartbeat_loop())
        if self.scrub_interval_seconds > 0:
            self._scrub_task = asyncio.create_task(self._scrub_loop())
        if self.grpc_port:
            from .volume_grpc import serve_volume_grpc
            host = self.url.rsplit(":", 1)[0]
            self._grpc_server = await serve_volume_grpc(
                self, host, self.grpc_port, tls=self.tls)

    async def _on_cleanup(self, app) -> None:
        self.admission.stop()
        if getattr(self, "_fast_srv", None) is not None:
            self._fast_srv.close()
            await self._fast_srv.wait_closed()
            self._fast_srv = None
        for ch in self._peer_grpc_channels.values():
            try:
                ch.close()
            except Exception:
                pass
        self._peer_grpc_channels.clear()
        if self._grpc_server is not None:
            await self._grpc_server.stop(grace=0.5)
        if self._hb_task:
            self._hb_task.cancel()
        if self._scrub_task:
            self._scrub_task.cancel()
        if self._stripe_task:
            self._stripe_task.cancel()
        if self._batcher is not None:
            self._batcher.stop()
        if self._session:
            await self._session.close()
        self.store.close()

    # --- heartbeat (weed/server/volume_grpc_client_to_master.go:50-222) ---
    async def _heartbeat_loop(self) -> None:
        while True:
            try:
                await self._periodic_maintenance()
                if self.use_grpc_heartbeat:
                    # the bidi stream carries beats until it breaks; the
                    # HTTP beat below is the fallback for that round
                    await self._grpc_heartbeat_stream()
                await self.send_heartbeat()
            except Exception as e:
                log.warning("heartbeat to %s failed: %s", self.master_url, e)
                self._rotate_master()
            await asyncio.sleep(self.pulse_seconds)

    async def _periodic_maintenance(self) -> None:
        expired = await asyncio.get_event_loop().run_in_executor(
            None, self.store.delete_expired_volumes)
        if expired:
            log.info("deleted expired TTL volumes %s", expired)
        # min-free-space watchdog: volumes on a filling disk seal
        # themselves readonly before the disk is full (disk_location.go:304)
        was_low = self.store.low_disk_space
        low = await asyncio.get_event_loop().run_in_executor(
            None, self.store.check_free_space)
        if low != was_low:
            log.warning("low disk space: %s", low)

    def _hb_payload(self, include_heat: bool = True) -> dict:
        payload = self.store.heartbeat()
        payload.update({
            "node_id": self.url,
            "url": self.url,
            "public_url": self.public_url,
            "data_center": self.data_center,
            "rack": self.rack,
        })
        if include_heat:
            # changed-volumes-only deltas: an idle node's heartbeat
            # carries no heat entries at all (payload stays O(changed));
            # draining also prunes tracker state for departed volumes
            held = ({v["id"] for v in payload["volumes"]}
                    | {s["id"] for s in payload["ec_shards"]})
            deltas = self.heat.deltas(known_vids=held)
            if deltas:
                payload["heat"] = deltas
        return payload

    async def _report_heat(self) -> None:
        """Deliver heat deltas over HTTP for nodes whose heartbeats
        ride the gRPC stream (no pb heat field). Failures requeue the
        drained window and never break the stream — heat is advisory,
        the heartbeat is not."""
        deltas = self.heat.deltas()
        if not deltas:
            return
        try:
            async with self._session.post(
                    f"http://{self.master_url}/vol/heat/report",
                    json={"node_id": self.url, "heat": deltas},
                    timeout=aiohttp.ClientTimeout(total=5)) as r:
                if r.status != 200:
                    raise RuntimeError(f"status {r.status}")
        except asyncio.CancelledError:
            self.heat.requeue(deltas)
            raise
        except Exception as e:
            self.heat.requeue(deltas)
            log.debug("heat report to %s failed: %s", self.master_url, e)

    async def _grpc_heartbeat_stream(self) -> None:
        """Hold the bidi gRPC heartbeat stream
        (volume_grpc_client_to_master.go:50-222): full-state beats up
        every pulse, volume-size-limit + leader hints down. Returns when
        the stream breaks; the caller falls back to HTTP and retries."""
        import grpc

        from ..pb.rpc import MasterStub, grpc_address
        from .master_grpc import heartbeat_to_pb

        target = self.master_grpc_target or grpc_address(self.master_url)
        stop = asyncio.Event()

        async def beats():
            while not stop.is_set():
                await self._periodic_maintenance()
                # the pb schema has no heat field: don't drain deltas
                # into a beat that can't carry them — side-channel them
                # to /vol/heat/report right after, so gRPC-heartbeat
                # clusters still feed the lifecycle heat view
                yield heartbeat_to_pb(self._hb_payload(include_heat=False))
                await self._report_heat()
                try:
                    await asyncio.wait_for(stop.wait(), self.pulse_seconds)
                except asyncio.TimeoutError:
                    pass

        from ..pb.rpc import aio_dial
        async with aio_dial(target) as channel:
            call = MasterStub(channel).Heartbeat(beats())
            try:
                async for resp in call:
                    self.volume_size_limit = (resp.volume_size_limit
                                              or self.volume_size_limit)
                    leader = resp.leader
                    if leader and leader not in ("self", self.master_url):
                        log.info("grpc heartbeat: following leader %s",
                                 leader)
                        self.master_url = leader
                        # the explicit target (tests) only described the
                        # old master; the new leader is reached via the
                        # port convention
                        self.master_grpc_target = ""
                        return  # redial the leader's gRPC port
            finally:
                stop.set()

    def _rotate_master(self) -> None:
        if len(self.masters) > 1:
            i = self.masters.index(self.master_url) \
                if self.master_url in self.masters else 0
            self.master_url = self.masters[(i + 1) % len(self.masters)]

    def _update_volume_gauges(self, payload: dict) -> None:
        """Per-collection volume gauges (the reference's labeled
        volumeServer gauges, weed/stats/metrics.go + store.go:40)."""
        by_col: dict[str, list[int]] = {}
        for v in payload.get("volumes", []):
            agg = by_col.setdefault(v.get("collection", "") or "default",
                                    [0, 0])
            agg[0] += 1
            agg[1] += v.get("size", 0)
        for col, (n, size) in by_col.items():
            self.metrics.gauge("volumes", n, labels={"collection": col,
                                                     "type": "normal"})
            self.metrics.gauge("volume_bytes", size,
                               labels={"collection": col})
        for s in payload.get("ec_shards", []):
            self.metrics.gauge(
                "ec_shards", len(s.get("shard_ids", [])),
                labels={"collection": s.get("collection", "") or "default",
                        "volume": str(s.get("id"))})
        # EC read-coalescing totals: how many cold interval reads led a
        # flight vs rode one (singleflight in ec/ec_volume.py)
        leaders = shared = 0
        for loc in self.store.locations:
            for ev in loc.ec_volumes.values():
                st = ev.read_flight.stats()
                leaders += st["leaders"]
                shared += st["shared"]
        self.metrics.gauge("ec_read_flight_leaders", leaders)
        self.metrics.gauge("ec_read_flight_shared", shared)

    async def send_heartbeat(self) -> None:
        ctx = self.shard_ctx
        if ctx is not None and ctx.shards > 1 and ctx.index != 0:
            # non-zero shards publish their volume list through the
            # shared segment (stripe tick blob); shard 0 unions it into
            # the single master heartbeat.  Heat stays queued in the
            # tracker (advisory — see _report_heat's contract).
            self._update_volume_gauges(self._hb_payload(include_heat=False))
            return
        payload = self._hb_payload()
        self._update_volume_gauges(payload)
        if ctx is not None and ctx.shards > 1:
            payload = ctx.merged_heartbeat(payload)
        try:
            await self._send_heartbeat(payload)
        except BaseException:
            # the heat deltas were drained into this payload; a failed
            # delivery must not lose the window's access records (a
            # lost last_access makes an active volume look idle to the
            # warm rule one window early)
            if payload.get("heat"):
                self.heat.requeue(payload["heat"])
            raise

    async def _send_heartbeat(self, payload: dict) -> None:
        async with self._session.post(
                f"http://{self.master_url}/heartbeat", json=payload,
                timeout=aiohttp.ClientTimeout(total=10)) as r:
            body = await r.json()
            self.volume_size_limit = body.get("volume_size_limit",
                                              self.volume_size_limit)
            # follow the raft leader so deltas land on the node that owns
            # the topology (volume_grpc_client_to_master.go:60-86)
            leader = body.get("leader", "")
            if leader and leader != self.master_url and leader != "self":
                log.info("heartbeat: following master leader %s", leader)
                self.master_url = leader

    # --- data path ---
    async def data_handler(self, request: web.Request) -> web.Response:
        fid_str = request.match_info["fid"].lstrip("/")
        if not fid_str or "," not in fid_str:
            return web.json_response({"error": "missing file id"}, status=400)
        try:
            fid = FileId.parse(fid_str)
        except ValueError as e:
            return web.json_response({"error": str(e)}, status=400)
        token = token_from_request(request.headers, request.query)
        canonical = str(fid)
        if request.method in ("GET", "HEAD"):
            err = self.guard.verify_read(token, canonical)
            if err:
                return web.json_response({"error": err}, status=401)
            return await self._read(request, fid)
        if request.method in ("POST", "PUT"):
            err = self.guard.verify_write(token, canonical)
            if err:
                return web.json_response({"error": err}, status=401)
            return await self._write(request, fid)
        if request.method == "DELETE":
            err = self.guard.verify_write(token, canonical)
            if err:
                return web.json_response({"error": err}, status=401)
            return await self._delete(request, fid)
        return web.json_response({"error": "method not allowed"}, status=405)

    async def _read(self, request: web.Request, fid: FileId) -> web.Response:
        """GetOrHeadHandler (volume_server_handlers_read.go:28-272)."""
        self.metrics.count("read")
        try:
            if await faults.fire_async("volume.read"):
                # injected drop: the needle "isn't here" — clients fall
                # back to replicas / degraded EC paths
                return web.json_response({"error": "injected drop"},
                                         status=404)
        except faults.FaultError as e:
            return web.json_response({"error": str(e)}, status=500)
        with self.metrics.timed("read"), \
                observe.span("volume.read", tags={"fid": str(fid)}):
            try:
                # small needles (the request-rate-bound workload) read
                # inline: a page-cache pread is microseconds while the
                # executor handoff costs two GIL convoys. The nowait
                # variant declines (None) for big needles, contended locks
                # (vacuum), or non-local backends (tiered volumes) so the
                # loop never blocks on real IO.
                vol = self.store.find_volume(fid.volume_id)
                n = (vol.read_needle_nowait(fid.key, fid.cookie)
                     if vol is not None else None)
                if n is None:
                    n = await asyncio.get_event_loop().run_in_executor(
                        None, lambda: self.store.read_needle(
                            fid.volume_id, fid.key, fid.cookie))
            except NeedleExpired:
                # TTL expiry is not data loss: never repair it back
                return web.json_response({"error": "not found"}, status=404)
            except (NeedleNotFound, KeyError) as miss:
                if (self.read_redirect
                        and self.store.find_volume(fid.volume_id) is None
                        and self.store.find_ec_volume(fid.volume_id) is None):
                    url = await self._lookup_replica(fid.volume_id)
                    if url:
                        raise web.HTTPMovedPermanently(
                            f"http://{url}/{fid}")
                # read repair: a replica of a volume we host may still have
                # the needle (lost local write / corruption); fetch it,
                # rewrite locally, and serve (the repair hook at
                # weed/topology/store_replicate.go:163-194). Guarded by a
                # negative cache + concurrency cap so scans of bogus fids
                # cannot amplify into replica storms.
                if (isinstance(miss, NeedleNotFound)
                        and self.store.find_volume(fid.volume_id)
                        is not None
                        and self._repair_permitted(str(fid))):
                    repaired = await self._read_repair(fid)
                    if repaired is not None:
                        n = repaired
                    else:
                        return web.json_response({"error": "not found"},
                                                 status=404)
                else:
                    return web.json_response({"error": "not found"},
                                             status=404)
            except NeedleDeleted:
                return web.json_response({"error": "deleted"}, status=404)
            except CrcError as rot:
                # on-disk corruption (bit-rot / torn write) on a volume
                # we host: repair from a healthy replica and serve the
                # good copy instead of surfacing the rot to the client.
                # The repair re-appends the intact needle locally (the
                # corrupt bytes become vacuumable garbage) and the event
                # is reported for the scrubber/operators via metric+log.
                self.metrics.count("read_crc_repair")
                log.error("volume %d: CRC mismatch on needle %s (%s); "
                          "attempting read-repair from replicas",
                          fid.volume_id, fid, rot)
                repaired = None
                if self._repair_permitted(str(fid)):
                    repaired = await self._read_repair(fid)
                if repaired is None:
                    return web.json_response(
                        {"error": "data corruption"}, status=500)
                n = repaired
        # lifecycle heat: one dict update per served read (EC reads —
        # the warm tier's un-EC signal — land here too)
        self.heat.record_read(fid.volume_id)
        etag = f'"{n.etag()}"'
        if request.headers.get("If-None-Match") == etag:
            return web.Response(status=304)
        headers = {"ETag": etag, "Accept-Ranges": "bytes"}
        if n.has(FLAG_HAS_LAST_MODIFIED):
            headers["X-Last-Modified"] = str(n.last_modified)
        mime = (n.mime.decode("utf-8", "replace")
                if n.has(FLAG_HAS_MIME) else "application/octet-stream")
        if n.has(FLAG_HAS_NAME) and n.name:
            headers["Content-Disposition"] = (
                f'inline; filename="{n.name.decode("utf-8", "replace")}"')
        body = n.data
        if n.is_compressed:
            # serve gzip verbatim only to clients that accept it; otherwise
            # decompress server-side (volume_server_handlers_read.go:170-200)
            if "gzip" in request.headers.get("Accept-Encoding", ""):
                headers["Content-Encoding"] = "gzip"
            else:
                body = compression.decompress(body)
        # image resize on read (?width=&height=&mode=fit|fill,
        # volume_server_handlers_read.go:240-272 via images.Resized);
        # skipped when the body is being served gzip-encoded. Detection by
        # mime or stored filename extension (the reference keys on ext).
        is_image = mime.startswith("image/") or (
            n.has(FLAG_HAS_NAME) and n.name
            and n.name.lower().endswith((b".jpg", b".jpeg", b".png",
                                         b".gif", b".webp")))
        if (is_image
                and "Content-Encoding" not in headers
                and (request.query.get("width")
                     or request.query.get("height"))):
            try:
                body = _resize_image(
                    body, mime,
                    int(request.query.get("width", 0)),
                    int(request.query.get("height", 0)),
                    request.query.get("mode", "fit"))
            except Exception as e:
                log.warning("image resize failed: %s", e)
        # range support
        rng = request.headers.get("Range")
        if rng and rng.startswith("bytes=") and \
                "Content-Encoding" not in headers:
            try:
                start_s, _, end_s = rng[6:].partition("-")
                if not start_s:
                    # suffix range: last N bytes (RFC 7233)
                    suffix = int(end_s)
                    if suffix <= 0:
                        raise ValueError
                    start = max(0, len(body) - suffix)
                    end = len(body) - 1
                else:
                    start = int(start_s)
                    end = int(end_s) if end_s else len(body) - 1
                end = min(end, len(body) - 1)
                if start > end:
                    raise ValueError
                headers["Content-Range"] = (
                    f"bytes {start}-{end}/{len(body)}")
                body = body[start:end + 1]
                status = 206
            except ValueError:
                return web.Response(status=416)
        else:
            status = 200
        if request.method == "HEAD":
            headers["Content-Length"] = str(len(body))
            return web.Response(status=status, headers=headers,
                                content_type=mime)
        return web.Response(status=status, body=body, headers=headers,
                            content_type=mime)

    _REPAIR_NEG_TTL = 10.0
    _REPAIR_MAX_INFLIGHT = 8

    def _repair_permitted(self, fid_str: str) -> bool:
        now = time.monotonic()
        if len(self._repair_neg) > 4096:
            self._repair_neg = {k: v for k, v in self._repair_neg.items()
                                if now - v < self._REPAIR_NEG_TTL}
        seen = self._repair_neg.get(fid_str)
        if seen is not None and now - seen < self._REPAIR_NEG_TTL:
            return False
        if self._repair_inflight >= self._REPAIR_MAX_INFLIGHT:
            return False
        return True

    async def _read_repair(self, fid: FileId):
        """Fetch a locally-missing needle from a replica, re-append it
        locally, and return it (None when no replica has it)."""
        from ..storage.needle import Needle as NeedleCls
        self._repair_inflight += 1
        try:
            with observe.span("volume.read_repair",
                              tags={"fid": str(fid)}):
                return await self._read_repair_inner(fid, NeedleCls)
        finally:
            self._repair_inflight -= 1

    async def _read_repair_inner(self, fid: FileId, NeedleCls):

        from ..utils.retry import BreakerOpen, shared_breaker
        breaker = shared_breaker()
        auth = (self.guard.sign_write(str(fid))
                if self.guard.signing_key else "")
        for url in await self._replica_urls(fid.volume_id):
            # unified failure discipline: a replica that keeps refusing
            # dials is skipped fast instead of paying a connect timeout
            # per missing needle
            try:
                breaker.check(url)
            except BreakerOpen:
                continue
            try:
                headers = ({"Authorization": f"BEARER {auth}"}
                           if auth else {})
                async with self._session.get(
                        f"http://{url}/admin/needle_raw",
                        params={"fid": str(fid)}, headers=headers,
                        timeout=aiohttp.ClientTimeout(total=10)) as r:
                    if r.status != 200:
                        breaker.record_success(url)  # host is alive
                        continue
                    raw = await r.read()
                breaker.record_success(url)
                v = self.store.find_volume(fid.volume_id)
                if v is None:
                    return None
                n = NeedleCls.from_bytes(raw, v.version)
                await asyncio.get_event_loop().run_in_executor(
                    None, lambda: v.write_needle(
                        n, preserve_append_at_ns=True))
                log.info("read-repaired needle %s from %s", fid, url)
                self.metrics.count("read_repair")
                return n
            except Exception as e:
                if isinstance(e, (aiohttp.ClientConnectionError, OSError,
                                  asyncio.TimeoutError)):
                    breaker.record_failure(url)
                log.warning("read repair of %s from %s failed: %s",
                            fid, url, e)
        self._repair_neg[str(fid)] = time.monotonic()
        return None

    async def admin_needle_raw(self, request: web.Request) -> web.Response:
        """Raw needle record bytes for peer read-repair. With a signing
        key configured the peer must present a write or read JWT for the
        fid — this endpoint returns needle content, so it enforces the
        same token regime as the data path."""
        try:
            fid = FileId.parse(request.query["fid"])
            token = token_from_request(request.headers, request.query)
            canonical = str(fid)
            # With any key configured, at least one configured regime must
            # affirmatively validate the token. verify_* returns None both
            # on success AND when its own key is unconfigured, so an
            # "all regimes failed" check would silently pass whenever one
            # key is absent.
            if self.guard.signing_key or self.guard.read_signing_key:
                ok = (self.guard.signing_key and
                      not self.guard.verify_write(token, canonical)) or \
                     (self.guard.read_signing_key and
                      not self.guard.verify_read(token, canonical))
                if not ok:
                    return web.json_response({"error": "unauthorized"},
                                             status=401)
            v = self.store.find_volume(fid.volume_id)
            if v is None:
                return web.json_response({"error": "no volume"}, status=404)
            n = await asyncio.get_event_loop().run_in_executor(
                None, lambda: v.read_needle(fid.key, cookie=fid.cookie))
            return web.Response(body=n.to_bytes(v.version),
                                content_type="application/octet-stream")
        except (NeedleNotFound, NeedleDeleted, KeyError, ValueError) as e:
            return web.json_response({"error": str(e)}, status=404)

    async def _lookup_replica(self, vid: int) -> Optional[str]:
        try:
            async with self._session.get(
                    f"http://{self.master_url}/dir/lookup",
                    params={"volumeId": str(vid)}) as r:
                if r.status != 200:
                    return None
                body = await r.json()
                locs = body.get("locations", [])
                return locs[0]["url"] if locs else None
        except Exception:
            return None

    async def _write(self, request: web.Request, fid: FileId) -> web.Response:
        """PostHandler + ReplicatedWrite (volume_server_handlers_write.go:19,
        weed/topology/store_replicate.go:21-161)."""
        self.metrics.count("write")
        try:
            if await faults.fire_async("volume.write"):
                return web.json_response({"error": "injected drop"},
                                         status=503)
        except faults.FaultError as e:
            return web.json_response({"error": str(e)}, status=500)
        n = Needle(cookie=fid.cookie, id=fid.key)
        # raw header compare, NOT request.content_type: that property (and
        # request.multipart()) routes through email.parser — ~40% of write
        # CPU at 1KB payloads. Single-part uploads (the overwhelming case)
        # parse with fast_multipart; anything irregular falls back.
        raw_ct = request.headers.get("Content-Type", "")
        filename, ctype = "", ""
        already_gzipped = False
        if raw_ct[:10].lower().startswith("multipart/"):  # MIME types are case-insensitive
            body = await request.read()
            part = fast_multipart.parse_single_part(body, raw_ct)
            if part is None:
                # irregular shape (multi-part, escaped quoting, base64
                # parts): full mime parse of the buffered body
                msg = email.parser.BytesParser().parsebytes(
                    b"Content-Type: " + raw_ct.encode("utf-8", "replace")
                    + b"\r\n\r\n" + body)
                subs = msg.get_payload()
                if not msg.is_multipart() or not subs:
                    return web.json_response(
                        {"error": "empty multipart body"}, status=400)
                first = subs[0]
                part = fast_multipart.Part(
                    first.get_payload(decode=True) or b"",
                    first.get_filename() or "",
                    first.get("Content-Type", ""),
                    first.get("Content-Encoding", ""))
            n.data = part.data
            filename = part.filename
            if filename:
                n.set_flag(FLAG_HAS_NAME)
                n.name = filename.encode()[:255]
            ctype = part.content_type
            if ctype and ctype != "application/octet-stream":
                n.set_flag(FLAG_HAS_MIME)
                n.mime = ctype.encode()[:255]
            already_gzipped = part.content_encoding == "gzip"
        else:
            n.data = await request.read()
            already_gzipped = request.headers.get(
                "Content-Encoding", "") == "gzip"
        # write-path compression (needle_parse_upload.go via
        # util/compression.go): client-gzipped payloads keep the flag;
        # compressable content gets gzipped when it actually shrinks.
        # ?compress=false opts out (e.g. filer-ciphered chunks).
        # The Content-Encoding header alone is NOT trusted: aiohttp
        # auto-inflates gzip request bodies on the raw path, so the flag is
        # only set when the bytes really are a gzip stream.
        if already_gzipped and compression.is_gzipped(n.data):
            n.set_flag(FLAG_IS_COMPRESSED)
        elif request.query.get("compress") != "false":
            ext = os.path.splitext(filename)[1] if filename else ""
            payload, compressed = compression.maybe_compress(
                n.data, ext, ctype)
            if compressed:
                n.data = payload
                n.set_flag(FLAG_IS_COMPRESSED)
        if len(n.data) > 32 * 1024 * 1024:
            return web.json_response({"error": "entry too large"}, status=413)
        ttl_s = request.query.get("ttl", "")
        if ttl_s:
            n.set_flag(FLAG_HAS_TTL)
            n.ttl = t.TTL.parse(ttl_s)
        n.set_flag(FLAG_HAS_LAST_MODIFIED)
        n.last_modified = int(time.time())

        with self.metrics.timed("write"), \
                observe.span("volume.write", tags={"fid": str(fid)}):
            try:
                _, size, unchanged = await self._batcher.write(
                    fid.volume_id, n)
            except KeyError:
                return web.json_response({"error": "volume not found"},
                                         status=404)
            except VolumeReadOnly as e:
                return web.json_response({"error": str(e)}, status=409)
            except ValueError as e:
                return web.json_response({"error": str(e)}, status=409)
        self.heat.record_write(fid.volume_id)

        if request.query.get("type") != "replicate":
            with observe.span("volume.replicate", tags={"fid": str(fid)}):
                ok = await self._replicate(request, fid, n)
            if not ok:
                return web.json_response(
                    {"error": "replication failed"}, status=500)
        return web.json_response({"name": (n.name or b"").decode("utf-8",
                                                                 "replace"),
                                  "size": len(n.data),
                                  "eTag": n.etag(),
                                  "unchanged": unchanged}, status=201)

    async def _replicate(self, request: web.Request, fid: FileId,
                         n: Needle) -> bool:
        try:
            if await faults.fire_async("volume.replicate"):
                # injected drop: fan-out silently skipped — exactly the
                # lost-replica divergence read-repair must later heal
                return True
        except faults.FaultError:
            return False
        replicas = await self._replica_urls(fid.volume_id)
        if not replicas:
            return True


        def body_for_replica() -> tuple[bytes, str]:
            # raw multipart so name/mime survive on the replica and its
            # needle bytes match the primary's; already-compressed payloads
            # carry Content-Encoding so the replica sets the compressed
            # flag instead of re-compressing/mis-flagging
            boundary = uuid.uuid4().hex
            name = (n.name.decode("utf-8", "replace")
                    if n.has(FLAG_HAS_NAME) else "file")
            ctype = (n.mime.decode("utf-8", "replace")
                     if n.has(FLAG_HAS_MIME) else "application/octet-stream")
            head = (f"--{boundary}\r\n"
                    f'Content-Disposition: form-data; name="file"; '
                    f'filename="{name}"\r\n'
                    f"Content-Type: {ctype}\r\n")
            if n.is_compressed:
                head += "Content-Encoding: gzip\r\n"
            body = head.encode() + b"\r\n" + n.data + \
                f"\r\n--{boundary}--\r\n".encode()
            return body, boundary

        # forward the caller's write jwt (header or query form) so the peer's
        # guard admits the replicated write (weed/topology/store_replicate.go
        # fans the original request out, jwt included)
        fwd = {k: v for k, v in request.query.items() if k == "ttl"}
        token = token_from_request(request.headers, request.query)
        if token:
            fwd["jwt"] = token
        payload, boundary = body_for_replica()
        results = await asyncio.gather(
            *[self._session.post(
                f"http://{url}/{fid}",
                params={"type": "replicate", **fwd},
                data=payload,
                headers={"Content-Type":
                         f"multipart/form-data; boundary={boundary}"})
              for url in replicas], return_exceptions=True)
        ok = True
        for url, res in zip(replicas, results):
            if isinstance(res, Exception):
                log.warning("replicate %s to %s failed: %s", fid, url, res)
                ok = False
            else:
                if res.status >= 300:
                    ok = False
                res.release()
        return ok

    async def _replica_urls(self, vid: int) -> list[str]:
        # short-TTL cache: the replicated-write fan-out otherwise pays a
        # master lookup per request (getWritableRemoteReplications caches
        # the same way, weed/topology/store_replicate.go:163)
        cached = self._replica_cache.get(vid)
        if cached and time.monotonic() - cached[1] < 10.0:
            return cached[0]
        try:
            async with self._session.get(
                    f"http://{self.master_url}/dir/lookup",
                    params={"volumeId": str(vid)}) as r:
                if r.status != 200:
                    return []
                body = await r.json()
                urls = [loc["url"] for loc in body.get("locations", [])
                        if loc["url"] != self.url]
                self._replica_cache[vid] = (urls, time.monotonic())
                return urls
        except Exception:
            return []

    async def _delete(self, request: web.Request, fid: FileId) -> web.Response:
        self.metrics.count("delete")
        ev = self.store.find_ec_volume(fid.volume_id)
        if ev is not None and self.store.find_volume(fid.volume_id) is None:
            # EC delete: local tombstone + propagate to all shard holders
            try:
                self.store.ec_blob_delete(fid.volume_id, fid.key)
            except KeyError:
                return web.json_response({"error": "not found"}, status=404)
            self.heat.record_write(fid.volume_id)
            if request.query.get("type") != "replicate":
                await self._propagate_ec_delete(fid)
            return web.json_response({"size": 0})
        n = Needle(cookie=fid.cookie, id=fid.key)
        try:
            size = await asyncio.get_event_loop().run_in_executor(
                None, lambda: self.store.delete_needle(fid.volume_id, n))
        except KeyError:
            return web.json_response({"error": "volume not found"},
                                     status=404)
        self.heat.record_write(fid.volume_id)
        if request.query.get("type") != "replicate":
            replicas = await self._replica_urls(fid.volume_id)
            for url in replicas:
                try:
                    fwd = {}
                    token = token_from_request(request.headers, request.query)
                    if token:
                        fwd["jwt"] = token
                    async with self._session.delete(
                            f"http://{url}/{fid}",
                            params={"type": "replicate", **fwd}) as r:
                        pass
                except Exception as e:
                    log.warning("delete replicate to %s: %s", url, e)
        return web.json_response({"size": size})

    async def _propagate_ec_delete(self, fid: FileId) -> None:
        try:
            async with self._session.get(
                    f"http://{self.master_url}/col/lookup/ec",
                    params={"volumeId": str(fid.volume_id)}) as r:
                if r.status != 200:
                    return
                shards = (await r.json()).get("shards", {})
        except Exception:
            return
        urls = {u for us in shards.values() for u in us if u != self.url}
        for url in urls:
            try:
                async with self._session.delete(
                        f"http://{url}/{fid}",
                        params={"type": "replicate"}) as r:
                    pass
            except Exception as e:
                log.warning("ec delete propagate to %s: %s", url, e)

    # --- admin ---
    async def admin_assign_volume(self, request: web.Request) -> web.Response:
        body = await request.json()
        ctx = self.shard_ctx
        if ctx is not None and ctx.shards > 1:
            # new volumes land on their modulo owner so the fleet's
            # capacity actually spreads; forward if that's not me
            owner = ctx.owner(int(body["volume_id"]))
            if owner != ctx.index:
                m = ctx.read_meta(owner)
                if m["alive"] and m["internal_port"]:
                    try:
                        async with self._session.post(
                                f"http://127.0.0.1:{m['internal_port']}"
                                "/admin/assign_volume", json=body,
                                headers={"X-Swfs-Internal":
                                         self._internal_token},
                                timeout=aiohttp.ClientTimeout(
                                    total=15)) as r:
                            return web.json_response(await r.json(),
                                                     status=r.status)
                    except Exception as e:
                        log.warning("assign forward to shard %d failed:"
                                    " %s; allocating locally", owner, e)
                # owner dead/unpublished: allocate locally — capacity
                # beats placement purity, and routing follows the
                # published volume lists anyway
        try:
            self.store.add_volume(
                int(body["volume_id"]), body.get("collection", ""),
                body.get("replication", "000"), body.get("ttl", ""))
        except (ValueError, RuntimeError) as e:
            return web.json_response({"error": str(e)}, status=409)
        try:
            await self.send_heartbeat()
        except Exception as e:
            # the allocation itself succeeded; the periodic heartbeat will
            # report it shortly
            log.warning("post-allocate heartbeat failed: %s", e)
        return web.json_response({"ok": True})

    async def admin_vacuum(self, request: web.Request) -> web.Response:
        body = await request.json()
        vid = int(body["volume_id"])
        v = self.store.find_volume(vid)
        if v is None:
            return web.json_response({"error": "volume not found"},
                                     status=404)
        garbage = v.garbage_level()
        await asyncio.get_event_loop().run_in_executor(None, v.compact)
        return web.json_response({"ok": True, "garbage_level": garbage})

    async def admin_vacuum_check(self, request: web.Request) -> web.Response:
        """VacuumVolumeCheck (weed/server/volume_grpc_vacuum.go): report the
        garbage ratio so the master can decide whether to compact."""
        try:
            garbage = self.store.vacuum_check(
                int(request.query["volume_id"]))
        except KeyError:
            return web.json_response({"error": "volume not found"},
                                     status=404)
        return web.json_response({"garbage_level": garbage})

    async def admin_vacuum_compact(self,
                                   request: web.Request) -> web.Response:
        body = await request.json()
        vid = int(body["volume_id"])
        rate = int(body.get("compaction_byte_per_second", 0))
        try:
            await asyncio.get_event_loop().run_in_executor(
                None, lambda: self.store.vacuum_compact(vid, rate))
        except KeyError:
            return web.json_response({"error": "volume not found"},
                                     status=404)
        except RuntimeError as e:
            return web.json_response({"error": str(e)}, status=409)
        return web.json_response({"ok": True})

    async def admin_vacuum_commit(self,
                                  request: web.Request) -> web.Response:
        body = await request.json()
        vid = int(body["volume_id"])
        try:
            await asyncio.get_event_loop().run_in_executor(
                None, lambda: self.store.vacuum_commit(vid))
        except KeyError:
            return web.json_response({"error": "volume not found"},
                                     status=404)
        except RuntimeError as e:
            return web.json_response({"error": str(e)}, status=409)
        return web.json_response({"ok": True})

    async def admin_vacuum_cleanup(self,
                                   request: web.Request) -> web.Response:
        body = await request.json()
        try:
            self.store.vacuum_cleanup(int(body["volume_id"]))
        except KeyError:
            return web.json_response({"error": "volume not found"},
                                     status=404)
        return web.json_response({"ok": True})

    async def admin_volume_delete(self, request: web.Request) -> web.Response:
        body = await request.json()
        ok = self.store.delete_volume(int(body["volume_id"]))
        await self.send_heartbeat()
        return web.json_response({"ok": ok})

    async def admin_readonly(self, request: web.Request) -> web.Response:
        body = await request.json()
        ok = self.store.mark_readonly(int(body["volume_id"]),
                                      body.get("read_only", True))
        return web.json_response({"ok": ok})

    async def admin_volume_mount(self, request: web.Request) -> web.Response:
        """VolumeMount (weed/server/volume_grpc_admin.go)."""
        body = await request.json()
        try:
            v = await asyncio.get_event_loop().run_in_executor(
                None, lambda: self.store.mount_volume(
                    int(body["volume_id"]), body.get("collection", "")))
        except (KeyError, ValueError) as e:
            return web.json_response({"error": str(e)}, status=409)
        await self.send_heartbeat()
        return web.json_response({"ok": True,
                                  "file_count": v.file_count()})

    async def admin_volume_unmount(self,
                                   request: web.Request) -> web.Response:
        """VolumeUnmount: stop serving, keep files."""
        body = await request.json()
        ok = self.store.unmount_volume(int(body["volume_id"]))
        await self.send_heartbeat()
        return web.json_response({"ok": ok})

    async def admin_volume_configure(self,
                                     request: web.Request) -> web.Response:
        """VolumeConfigure: rewrite superblock replication in place."""
        body = await request.json()
        try:
            self.store.configure_replication(int(body["volume_id"]),
                                             body["replication"])
        except (KeyError, ValueError) as e:
            return web.json_response({"error": str(e)}, status=400)
        await self.send_heartbeat()
        return web.json_response({"ok": True})

    async def admin_needle_ids(self, request: web.Request) -> web.Response:
        """Live needle inventory for fsck (command_volume_fsck.go collects
        the same per-volume id set)."""
        try:
            vid = int(request.query["volume_id"])
            entries = await asyncio.get_event_loop().run_in_executor(
                None, lambda: self.store.needle_ids(vid))
        except KeyError as e:
            return web.json_response({"error": str(e)}, status=404)
        return web.json_response({"volume_id": vid,
                                  "needles": [[k, s] for k, s in entries]})

    async def admin_tier_upload(self, request: web.Request) -> web.Response:
        """Move a sealed volume's .dat to an object-store tier
        (VolumeTierMoveDatToRemote, volume_grpc_tier_upload.go:14)."""
        body = await request.json()
        try:
            info = await asyncio.get_event_loop().run_in_executor(
                None, lambda: self.store.tier_upload(
                    int(body["volume_id"]), body["backend"],
                    keep_local=body.get("keep_local", False)))
        except (KeyError, ValueError) as e:
            return web.json_response({"error": str(e)}, status=400)
        except Exception as e:
            # upload failure (unreachable store etc.): volume already
            # un-sealed by the store's rollback
            return web.json_response({"error": str(e)}, status=502)
        await self.send_heartbeat()
        return web.json_response({"ok": True, "info": info})

    async def admin_tier_download(self,
                                  request: web.Request) -> web.Response:
        """Bring a tiered .dat back local (VolumeTierMoveDatFromRemote)."""
        body = await request.json()
        try:
            out = await asyncio.get_event_loop().run_in_executor(
                None, lambda: self.store.tier_download(
                    int(body["volume_id"])))
        except (KeyError, ValueError) as e:
            return web.json_response({"error": str(e)}, status=400)
        await self.send_heartbeat()
        return web.json_response({"ok": True, **out})

    async def admin_ec_generate(self, request: web.Request) -> web.Response:
        """One volume (volume_id) or a WINDOW (volume_ids): the batched
        form streams every volume through one governed executable
        back-to-back (store.ec_generate_many), which is how the
        lifecycle daemon's encode queue amortizes compiles + program
        loads across a whole batch of sealed volumes. ``"fused": true``
        (or the /admin/ec/fused route) runs the one-pass warm-down
        instead: compaction + gzip + encode + digests fused
        (store.ec_fused_generate), so the shard set holds the COMPACTED
        volume and no separate vacuum precedes the encode."""
        body = await request.json()
        return await self._ec_generate_impl(
            body, fused=bool(body.get("fused", False)))

    async def admin_ec_fused(self, request: web.Request) -> web.Response:
        """The one-pass warm-down route (always fused)."""
        return await self._ec_generate_impl(await request.json(),
                                            fused=True)

    async def _ec_generate_impl(self, body: dict,
                                fused: bool) -> web.Response:
        vids = ([int(v) for v in body["volume_ids"]]
                if "volume_ids" in body else [int(body["volume_id"])])
        if not vids:
            return web.json_response({"error": "empty volume_ids"},
                                     status=400)
        gen_one = (self.store.ec_fused_generate if fused
                   else self.store.ec_generate)
        gen_many = (self.store.ec_fused_generate_many if fused
                    else self.store.ec_generate_many)
        tctx = observe.capture()
        try:
            if len(vids) == 1:
                shards = await asyncio.get_event_loop().run_in_executor(
                    None, lambda: observe.run_with(
                        tctx, gen_one, vids[0]))
                per_volume = {str(vids[0]): shards}
            else:
                per_volume_raw = await asyncio.get_event_loop() \
                    .run_in_executor(
                        None, lambda: observe.run_with(
                            tctx, gen_many, vids))
                per_volume = {str(k): v for k, v in per_volume_raw.items()}
                shards = per_volume.get(str(vids[0]), [])
        except KeyError as e:
            return web.json_response({"error": str(e)}, status=404)
        return web.json_response({"ok": True, "shards": shards,
                                  "fused": fused, "volumes": per_volume})

    async def admin_ec_mount(self, request: web.Request) -> web.Response:
        body = await request.json()
        try:
            mounted = self.store.ec_mount(
                int(body["volume_id"]), body.get("collection", ""),
                [int(s) for s in body["shard_ids"]])
        except (KeyError, FileNotFoundError) as e:
            return web.json_response({"error": str(e)}, status=404)
        await self.send_heartbeat()
        return web.json_response({"ok": True, "mounted": mounted})

    async def admin_ec_unmount(self, request: web.Request) -> web.Response:
        body = await request.json()
        removed = self.store.ec_unmount(int(body["volume_id"]),
                                        [int(s) for s in body["shard_ids"]])
        await self.send_heartbeat()
        return web.json_response({"ok": True, "unmounted": removed})

    async def admin_ec_rebuild(self, request: web.Request) -> web.Response:
        body = await request.json()
        tctx = observe.capture()
        try:
            rebuilt = await asyncio.get_event_loop().run_in_executor(
                None, lambda: observe.run_with(
                    tctx, self.store.ec_rebuild,
                    int(body["volume_id"]), body.get("collection", "")))
        except (KeyError, ValueError) as e:
            return web.json_response({"error": str(e)}, status=409)
        return web.json_response({"ok": True, "rebuilt": rebuilt})

    async def admin_ec_copy(self, request: web.Request) -> web.Response:
        """Pull shard files from a source server (VolumeEcShardsCopy,
        volume_grpc_erasure_coding.go:104 — pull model like the reference)."""
        body = await request.json()
        vid = int(body["volume_id"])
        collection = body.get("collection", "")
        if not safe_collection(collection):
            return web.json_response({"error": "bad collection"},
                                     status=400)
        shard_ids = [int(s) for s in body["shard_ids"]]
        source = body["source"]
        copy_ecx = body.get("copy_ecx_file", False)
        from .. import ec as ec_mod
        loc = self.store.locations[0]
        prefix = f"{collection}_" if collection else ""
        base = os.path.join(loc.directory, f"{prefix}{vid}")
        try:
            exts = [ec_mod.to_ext(sid) for sid in shard_ids]
            if copy_ecx:
                exts += [".ecx", ".ecj", ".ecm"]
            for ext in exts:
                async with self._session.get(
                        f"http://{source}/admin/file_copy",
                        params={"volume_id": str(vid),
                                "collection": collection,
                                "ext": ext}) as r:
                    if r.status == 404 and ext in (".ecj", ".ecm"):
                        continue  # delete journal / layout marker optional
                    if r.status != 200:
                        return web.json_response(
                            {"error": f"copy {ext} from {source}: "
                             f"{r.status}"}, status=502)
                    with open(base + ext, "wb") as f:
                        async for chunk in r.content.iter_chunked(1 << 20):
                            f.write(chunk)
        except aiohttp.ClientError as e:
            return web.json_response({"error": str(e)}, status=502)
        return web.json_response({"ok": True})

    async def admin_ec_delete_shards(self, request: web.Request
                                     ) -> web.Response:
        body = await request.json()
        self.store.ec_delete_shards(int(body["volume_id"]),
                                    body.get("collection", ""),
                                    [int(s) for s in body["shard_ids"]])
        await self.send_heartbeat()
        return web.json_response({"ok": True})

    async def admin_ec_blob_delete(self, request: web.Request) -> web.Response:
        body = await request.json()
        try:
            self.store.ec_blob_delete(int(body["volume_id"]),
                                      int(body["needle_id"]))
        except KeyError as e:
            return web.json_response({"error": str(e)}, status=404)
        return web.json_response({"ok": True})

    async def admin_ec_to_volume(self, request: web.Request) -> web.Response:
        body = await request.json()
        try:
            await asyncio.get_event_loop().run_in_executor(
                None, lambda: self.store.ec_to_volume(
                    int(body["volume_id"]), body.get("collection", "")))
        except (KeyError, FileNotFoundError) as e:
            return web.json_response({"error": str(e)}, status=404)
        await self.send_heartbeat()
        return web.json_response({"ok": True})

    async def admin_ec_shard_read(self, request: web.Request) -> web.Response:
        q = request.query
        try:
            if await faults.fire_async("ec.shard_read"):
                return web.json_response({"error": "injected drop"},
                                         status=404)
            data = self.store.ec_shard_read(
                int(q["volume"]), int(q["shard"]),
                int(q.get("offset", 0)), int(q["size"]))
        except faults.FaultError as e:
            return web.json_response({"error": str(e)}, status=500)
        except KeyError as e:
            return web.json_response({"error": str(e)}, status=404)
        return web.Response(body=faults.corrupt("ec.shard_read", data),
                            content_type="application/octet-stream")

    # shard-location freshness tiers (store_ec.go:221-262): a missing
    # shard re-polls the master after 11s, a known one after 7m; a total
    # read miss forces an immediate refresh (see _make_shard_reader)
    _SHARD_LOC_MISSING_TTL = 11.0
    _SHARD_LOC_KNOWN_TTL = 7 * 60.0

    def _shard_locations(self, vid: int, shard_id: int,
                         force: bool = False) -> list[str]:
        """Tiered-TTL cache of vid -> shard -> holder urls."""
        import json as _json
        import urllib.request
        now = time.monotonic()
        cached = self._shard_loc_cache.get(vid)
        if cached is not None and not force:
            shards, fetched = cached
            age = now - fetched
            have = str(shard_id) in shards
            if age < self._SHARD_LOC_MISSING_TTL or \
                    (have and age < self._SHARD_LOC_KNOWN_TTL):
                return [u for u in shards.get(str(shard_id), [])
                        if u != self.url]
        try:
            req = urllib.request.Request(
                f"http://{self.master_url}/col/lookup/ec?volumeId={vid}",
                headers=_retry.inject_deadline({}))
            with urllib.request.urlopen(
                    req, timeout=_retry.cap_timeout(5)) as r:
                shards = _json.load(r).get("shards", {})
            self._shard_loc_cache[vid] = (shards, now)
        except Exception as e:
            log.warning("ec shard lookup for %d failed: %s", vid, e)
            shards = cached[0] if cached else {}
        return [u for u in shards.get(str(shard_id), []) if u != self.url]

    def _make_shard_reader(self, ev):
        """Shard reader for non-local shards, used by the EC read path
        (store_ec.go:282-320). Prefers the peer's VolumeEcShardRead gRPC
        stream (volume_grpc_erasure_coding.go:270-328) and falls back to
        its /admin/ec/shard_read HTTP analog for peers running without a
        gRPC port. Synchronous (runs in executor threads); a total miss
        forces one location-cache refresh so reads survive shard moves."""
        import urllib.request

        def fetch_grpc(url: str, shard_id: int, offset: int,
                       size: int) -> Optional[bytes]:

            import grpc as grpc_mod

            from ..pb import volume_server_pb2 as vpb
            from ..pb.rpc import VolumeServerStub, grpc_address
            # peers whose +10000 gRPC port is closed/filtered go HTTP-first
            # for a while instead of paying the deadline on every shard
            if time.time() < self._peer_grpc_dead.get(url, 0):
                return None
            try:
                # channels are thread-safe and reconnect internally; one
                # per peer, not one per fetch (setdefault so racing
                # executor threads don't leak a loser channel)
                ch = self._peer_grpc_channels.get(url)
                if ch is None:
                    from ..pb.rpc import dial
                    new_ch = dial(grpc_address(url))
                    ch = self._peer_grpc_channels.setdefault(url, new_ch)
                    if ch is not new_ch:
                        new_ch.close()
                stub = VolumeServerStub(ch)
                buf = bytearray()
                for chunk in stub.VolumeEcShardRead(
                        vpb.EcShardReadRequest(
                            volume_id=ev.vid, shard_id=shard_id,
                            offset=offset, size=size),
                        timeout=5):
                    if chunk.error:
                        return None
                    buf += chunk.data
                    if chunk.is_last:
                        break
                return bytes(buf) if len(buf) == size else None
            except grpc_mod.RpcError as e:
                if e.code() in (grpc_mod.StatusCode.UNAVAILABLE,
                                grpc_mod.StatusCode.DEADLINE_EXCEEDED):
                    self._peer_grpc_dead[url] = time.time() + 60.0
                return None

        def fetch(url: str, shard_id: int, offset: int,
                  size: int) -> Optional[bytes]:
            data = fetch_grpc(url, shard_id, offset, size)
            if data is not None:
                return data
            try:
                from ..cache import shared_pool
                r = shared_pool().request(
                    "GET",
                    f"http://{url}/admin/ec/shard_read?volume="
                    f"{ev.vid}&shard={shard_id}&offset={offset}"
                    f"&size={size}", timeout=10)
                if r.status != 200:
                    return None
                return r.data if len(r.data) == size else None
            except Exception:
                return None

        def read(shard_id: int, offset: int, size: int) -> Optional[bytes]:
            for force in (False, True):
                for url in self._shard_locations(ev.vid, shard_id,
                                                 force=force):
                    data = fetch(url, shard_id, offset, size)
                    if data is not None:
                        return data
            return None

        return read

    # --- EC scrubber: bit-rot -> self-heal, closing the repair loop ---

    async def _scrub_loop(self) -> None:
        while True:
            await asyncio.sleep(self.scrub_interval_seconds)
            try:
                await self.scrub_pass()
            except asyncio.CancelledError:
                raise
            except Exception as e:
                log.warning("ec scrub pass failed: %s", e)

    async def scrub_pass(self, throttle_seconds: float = 0.05) -> dict:
        """Verify every locally mounted EC shard against the digest
        stamped into its .ecm at encode time (ec/pipeline.py). Low
        priority by construction: each shard digests in an executor
        thread and the loop sleeps between shards, so serving traffic is
        never starved. Mismatches are reported to the master, whose
        repair daemon drops the rotten copy and schedules a targeted
        rebuild. Returns {vid: [bad shard ids]}."""
        from ..ec.pipeline import read_stamped_digests, shard_file_digest
        loop = asyncio.get_event_loop()
        bad_by_vid: dict[int, list[int]] = {}
        # scrub is background by definition: its report POST (and any
        # repair traffic it triggers) tags X-Seaweed-Priority: bg and
        # sheds first under overload
        _ptok = overload.set_priority(overload.CLASS_BG)
        try:
            with observe.span("volume.scrub"):
                for loc in self.store.locations:
                    for vid, ev in list(loc.ec_volumes.items()):
                        base = ev.base_file_name()
                        stamped = read_stamped_digests(base)
                        if not stamped:
                            continue
                        bad: list[int] = []
                        for sid in ev.shard_ids():
                            want = stamped.get(sid)
                            if want is None:
                                continue
                            try:
                                got = await loop.run_in_executor(
                                    None, lambda s=sid: int(
                                        shard_file_digest(base, [s])[0]))
                            except OSError:
                                continue  # shard unmounted/moved mid-scan
                            self.metrics.count("scrub_shards_checked")
                            if got != want:
                                bad.append(sid)
                                self.metrics.count("scrub_shards_bad")
                                log.warning(
                                    "scrub: shard %d of volume %d digest "
                                    "mismatch (%d != %d)", sid, vid, got,
                                    want)
                            await asyncio.sleep(throttle_seconds)
                        if bad:
                            bad_by_vid[vid] = bad
            for vid, bad in bad_by_vid.items():
                await self._report_bad_shards(vid, bad)
        finally:
            overload.reset_priority(_ptok)
        return bad_by_vid

    async def _report_bad_shards(self, vid: int, bad: list[int]) -> None:
        try:
            async with self._session.post(
                    f"http://{self.master_url}/ec/scrub_report",
                    json={"volume_id": vid, "url": self.url,
                          "bad_shards": bad},
                    timeout=aiohttp.ClientTimeout(total=10)) as r:
                await r.read()
        except Exception as e:
            log.warning("scrub report for volume %d failed: %s", vid, e)

    async def admin_ec_mesh_status(self,
                                   request: web.Request) -> web.Response:
        """This process's device-mesh view: configured WEED_EC_MESH_
        DEVICES, live devices, and the per-chip staging counters +
        governor gauges from the shared "ec" registry (the JSON twin of
        what /metrics exposes, for the ec.mesh.status shell command)."""
        from ..parallel.mesh_coder import mesh_status
        return web.json_response(
            await asyncio.get_event_loop().run_in_executor(
                None, mesh_status))

    async def admin_ec_scrub(self, request: web.Request) -> web.Response:
        """Run one scrub pass now (operators / chaos tests)."""
        body = {}
        if request.can_read_body:
            try:
                body = await request.json()
            except Exception:
                body = {}
        bad = await self.scrub_pass(
            throttle_seconds=float(body.get("throttle_seconds", 0.0)))
        return web.json_response(
            {"ok": True,
             "bad": {str(vid): sids for vid, sids in bad.items()}})

    async def admin_file_copy(self, request: web.Request) -> web.StreamResponse:
        """Stream a volume/shard file to a pulling peer (CopyFile,
        weed/server/volume_grpc_copy.go:24-281)."""
        q = request.query
        vid = int(q["volume_id"])
        collection = q.get("collection", "")
        ext = q["ext"]
        if not ext.startswith(".") or "/" in ext or ".." in ext \
                or not safe_collection(collection):
            return web.json_response({"error": "bad ext or collection"},
                                     status=400)
        prefix = f"{collection}_" if collection else ""
        for loc in self.store.locations:
            path = os.path.join(loc.directory, f"{prefix}{vid}{ext}")
            if os.path.exists(path):
                resp = web.StreamResponse()
                resp.headers["Content-Length"] = str(os.path.getsize(path))
                await resp.prepare(request)
                with open(path, "rb") as f:
                    while True:
                        chunk = f.read(1 << 20)
                        if not chunk:
                            break
                        await resp.write(chunk)
                await resp.write_eof()
                return resp
        return web.json_response({"error": "file not found"}, status=404)

    async def admin_tail(self, request: web.Request) -> web.StreamResponse:
        """Stream needle records appended after since_ns, length-framed
        (VolumeTailSender, weed/server/volume_grpc_tail.go:16-79).
        Frame: u32 big-endian record length + raw v3 needle record."""
        from ..storage import volume_backup
        q = request.query
        vid = int(q["volume_id"])
        since_ns = int(q.get("since_ns", 0))
        v = self.store.find_volume(vid)
        if v is None:
            return web.json_response({"error": "volume not found"},
                                     status=404)
        resp = web.StreamResponse()
        resp.headers["Content-Type"] = "application/octet-stream"
        await resp.prepare(request)
        loop = asyncio.get_event_loop()
        # pull records one at a time off the executor so a full-volume tail
        # streams in O(record) memory instead of materializing the volume
        it = volume_backup.iter_needles_since(v, since_ns)

        def next_record():
            try:
                n = next(it)
            except StopIteration:
                return None
            return n.to_bytes(v.version)

        while True:
            rec = await loop.run_in_executor(None, next_record)
            if rec is None:
                break
            await resp.write(len(rec).to_bytes(4, "big") + rec)
        await resp.write_eof()
        return resp

    async def admin_volume_copy(self, request: web.Request) -> web.Response:
        """Pull a whole volume (.dat + .idx) from a source server and mount
        it (VolumeCopy pull model, weed/server/volume_grpc_copy.go:24-151)."""
        body = await request.json()
        vid = int(body["volume_id"])
        collection = body.get("collection", "")
        if not safe_collection(collection):
            return web.json_response({"error": "bad collection"},
                                     status=400)
        source = body["source"]
        if self.store.find_volume(vid) is not None:
            return web.json_response({"error": "volume exists"}, status=409)
        open_locs = [l for l in self.store.locations
                     if len(l.volumes) < l.max_volume_count]
        if not open_locs:
            return web.json_response({"error": "no free slots"}, status=500)
        loc = min(open_locs, key=lambda l: len(l.volumes))
        prefix = f"{collection}_" if collection else ""
        base = os.path.join(loc.directory, f"{prefix}{vid}")
        try:
            for ext in (".dat", ".idx"):
                async with self._session.get(
                        f"http://{source}/admin/file_copy",
                        params={"volume_id": str(vid),
                                "collection": collection, "ext": ext}) as r:
                    if r.status != 200:
                        raise IOError(f"{source} has no {vid}{ext}")
                    with open(base + ext, "wb") as f:
                        async for chunk in r.content.iter_chunked(1 << 20):
                            f.write(chunk)
            from ..storage.volume import Volume
            v = await asyncio.get_event_loop().run_in_executor(
                None, lambda: Volume(loc.directory, collection, vid,
                     needle_map_kind=self.store.needle_map_kind))
            loc.volumes[vid] = v
        except Exception as e:
            for ext in (".dat", ".idx"):
                if os.path.exists(base + ext):
                    os.remove(base + ext)
            return web.json_response({"error": str(e)}, status=500)
        await self.send_heartbeat()
        return web.json_response({"ok": True,
                                  "file_count": v.file_count()})

    async def admin_batch_delete(self, request: web.Request) -> web.Response:
        """Delete many fids in one RPC (BatchDelete,
        weed/server/volume_grpc_batch_delete.go:15)."""
        body = await request.json()
        results = []
        for fid_str in body.get("fids", []):
            try:
                fid = FileId.parse(fid_str)
                n = Needle(cookie=fid.cookie, id=fid.key)
                size = await asyncio.get_event_loop().run_in_executor(
                    None,
                    lambda f=fid, nn=n: self.store.delete_needle(
                        f.volume_id, nn))
                results.append({"fid": fid_str, "size": size})
            except Exception as e:
                results.append({"fid": fid_str, "error": str(e)})
        return web.json_response({"results": results})

    async def admin_query(self, request: web.Request) -> web.StreamResponse:
        """S3-Select-lite over needle payloads (Query,
        weed/server/volume_grpc_query.go:13-69): filter + project JSON
        documents named by fid, emitting NDJSON."""
        from ..query import QueryFilter, query_json_lines
        body = await request.json()
        flt = None
        if body.get("filter"):
            f = body["filter"]
            flt = QueryFilter(f["field"], f.get("op", "="), f.get("value"))
        projections = body.get("projections") or None
        payloads = []
        for fid_str in body.get("fids", []):
            try:
                fid = FileId.parse(fid_str)
                n = self.store.read_needle(fid.volume_id, fid.key,
                                           cookie=fid.cookie)
                payloads.append(n.data)
            except Exception:
                continue
        resp = web.StreamResponse()
        resp.headers["Content-Type"] = "application/x-ndjson"
        await resp.prepare(request)
        for line in query_json_lines(payloads, flt, projections):
            await resp.write(line.encode() + b"\n")
        await resp.write_eof()
        return resp

    async def status(self, request: web.Request) -> web.Response:
        return web.json_response({"url": self.url, **self.store.status()})

    async def metrics_handler(self, request: web.Request) -> web.Response:
        # shared registries carry non-server subsystems hosted in this
        # process (the EC feed governor's operating point + stage model)
        text = metrics_mod.exposition(self.metrics, request)
        if self.shard_ctx is not None and self.shard_ctx.shards > 1:
            # whatever shard the LB's scrape landed on appends the
            # fleet-wide per-shard series from the shared segment, so
            # one node keeps looking like one node
            text += self.shard_ctx.metrics_lines()
        return web.Response(text=text, content_type="text/plain")

    async def status_ui(self, request: web.Request) -> web.Response:
        """Status page with volume + EC tables
        (weed/server/volume_server_ui/templates.go)."""
        from ..utils.status_ui import render_status
        st = self.store.status()
        volumes = [{
            "id": v.get("id"), "collection": v.get("collection") or "-",
            "size": v.get("size"), "files": v.get("file_count"),
            "deleted": v.get("delete_count"),
            "garbage bytes": v.get("deleted_bytes"),
            "replication": v.get("replica_placement"),
            "ttl": v.get("ttl") or "-",
            "version": v.get("version"),
            "read only": v.get("read_only", False),
        } for v in st.get("volumes", [])]
        ec = [{
            "volume": s.get("id"),
            "collection": s.get("collection") or "-",
            "shards": s.get("shard_ids"),
            "shard size": s.get("shard_size"),
        } for s in st.get("ec_shards", [])]
        disks = [{
            "directory": loc.directory,
            "volumes": len(loc.volumes),
            "ec volumes": len(loc.ec_volumes),
            "max": loc.max_volume_count,
        } for loc in self.store.locations]
        return web.Response(
            text=render_status(
                "seaweedfs-tpu volume server", {
                    "server": {"master": self.master_url,
                               "volumes": len(volumes),
                               "ec volumes": len(ec)},
                    "disks": disks,
                    "volumes": volumes,
                    "ec shards": ec,
                    "metrics": self.metrics.render(),
                }, subtitle=self.url),
            content_type="text/html")


async def run_volume_server(host: str, port: int, store: Store,
                            master_url: str, fastpath: bool = True,
                            **kwargs) -> web.AppRunner:
    """Public listener is the hand-rolled data-plane protocol
    (server/fastpath.py) with the aiohttp app on an internal loopback
    port for everything it proxies; fastpath=False (or env
    SEAWEEDFS_NO_FASTPATH) serves aiohttp directly on the public port."""
    if os.environ.get("SEAWEEDFS_NO_FASTPATH"):
        fastpath = False
    server = VolumeServer(store, master_url, url=f"{host}:{port}", **kwargs)
    runner = web.AppRunner(server.app, access_log=None)
    await runner.setup()
    tls = kwargs.get("tls")
    ssl_ctx = tls.server_ssl_context() if tls is not None else None
    ctx = server.shard_ctx
    sharding = ctx is not None and ctx.shards > 1
    internal_port = 0
    if fastpath:
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        internal_port = site._server.sockets[0].getsockname()[1]
        from .fastpath import start_fastpath
        server._fast_srv = await start_fastpath(
            server, host, port, internal_port, ssl_context=ssl_ctx,
            reuse_port=sharding)
    else:
        if sharding:
            log.warning("WEED_SERVE_SHARDS>1 without the fastpath: "
                        "cross-shard volume routing is unavailable")
        site = web.TCPSite(runner, host, port, ssl_context=ssl_ctx,
                           reuse_port=sharding or None)
        await site.start()
    if sharding:
        from . import sharded

        # the loopback app port is the fleet-visible address for
        # cross-shard proxying; publish it before the first tick so
        # siblings can route immediately, and start this shard at an
        # even 1/N stripe until demand data accumulates
        ctx.publish_meta(internal_port=internal_port,
                         stripe_share=1.0 / ctx.shards)
        server.admission.apply_stripe(1.0 / ctx.shards)

        def _blob() -> dict:
            if ctx.index == 0 and ctx.child_pids:
                died = ctx.reap_children()
                if died:
                    log.warning("shard children died: %s", died)
            return {"heartbeat": server._hb_payload(include_heat=False)}

        server._stripe_task = asyncio.create_task(
            sharded.run_stripe_loop(ctx, server.admission, blob_fn=_blob))
        log.info("volume shard %d/%d on %s:%d (internal %d)",
                 ctx.index, ctx.shards, host, port, internal_port)
    log.info("volume server on %s:%d -> master %s", host, port, master_url)
    return runner
