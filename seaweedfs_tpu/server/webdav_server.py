"""WebDAV gateway over the filer (weed webdav equivalent,
weed/server/webdav_server.go:101 — golang.org/x/net/webdav FileSystem
backed by filer gRPC; here the same protocol surface over the filer's
HTTP API).

Implements the RFC 4918 subset real clients (davfs2, macOS Finder,
Windows explorer, cadaver) use: OPTIONS, PROPFIND (depth 0/1), GET/HEAD,
PUT, DELETE, MKCOL, MOVE, COPY.
"""

from __future__ import annotations

import logging
from typing import Optional
from urllib.parse import quote, unquote, urlparse
from xml.sax.saxutils import escape

import aiohttp
from aiohttp import web

from .. import observe, overload
from ..utils import metrics as metrics_mod

log = logging.getLogger("webdav")

_DAV_HEADERS = {
    "DAV": "1,2",
    "MS-Author-Via": "DAV",
    "Allow": ("OPTIONS, PROPFIND, GET, HEAD, PUT, DELETE, MKCOL, "
              "MOVE, COPY, LOCK, UNLOCK, PROPPATCH"),
}


def _rfc1123(ts: float) -> str:
    import time
    return time.strftime("%a, %d %b %Y %H:%M:%S GMT", time.gmtime(ts))


def _is_dir(entry: dict) -> bool:
    """Directory-ness lives in the mode bits of the filer's entry JSON
    (S_IFDIR, like os.stat)."""
    mode = entry.get("attr", {}).get("mode", 0)
    return (int(mode) & 0o170000) == 0o040000


_DEFAULT_LOCK_SECONDS = 3600.0
_MAX_LOCK_SECONDS = 4 * 3600.0  # memLS's infiniteTimeout stand-in


class _Lock:
    __slots__ = ("path", "token", "expires", "depth_infinity")

    def __init__(self, path: str, token: str, expires: float,
                 depth_infinity: bool):
        self.path = path
        self.token = token
        self.expires = expires
        self.depth_infinity = depth_infinity

    @property
    def depth(self) -> str:
        return "infinity" if self.depth_infinity else "0"


class LockManager:
    """Exclusive write locks with expiry — the role of x/net/webdav's
    memLS (the lock system the reference inherits,
    weed/server/webdav_server.go:101). An infinite-depth lock covers the
    whole subtree; acquiring conflicts with locks on the path, any
    ancestor with depth infinity, or (for an infinite lock) any
    descendant. Expired locks are collected lazily."""

    def __init__(self):
        self._locks: dict[str, _Lock] = {}

    def _gc(self) -> None:
        import time
        now = time.monotonic()
        for p in [p for p, lk in self._locks.items()
                  if lk.expires <= now]:
            del self._locks[p]

    def holder(self, path: str) -> Optional[_Lock]:
        """The live lock governing `path` (own or covering ancestor)."""
        self._gc()
        lk = self._locks.get(path)
        if lk is not None:
            return lk
        parts = path.rstrip("/").split("/")
        for i in range(len(parts) - 1, 0, -1):
            anc = "/".join(parts[:i]) or "/"
            lk = self._locks.get(anc)
            if lk is not None and lk.depth_infinity:
                return lk
        return None

    def descendant_holder(self, path: str) -> Optional[_Lock]:
        """A live lock held BELOW `path` — deleting/moving the ancestor
        would destroy that locked resource (RFC 4918: 423 without its
        token)."""
        self._gc()
        prefix = path.rstrip("/") + "/"
        for p, lk in self._locks.items():
            if p.startswith(prefix):
                return lk
        return None

    def acquire(self, path: str, timeout: float,
                depth_infinity: bool = True) -> Optional[_Lock]:
        import time
        import uuid
        self._gc()
        if self.holder(path) is not None:
            return None
        if depth_infinity:
            prefix = path.rstrip("/") + "/"
            if any(p.startswith(prefix) for p in self._locks):
                return None
        lk = _Lock(path, f"opaquelocktoken:{uuid.uuid4()}",
                   time.monotonic() + timeout, depth_infinity)
        self._locks[path] = lk
        return lk

    def refresh(self, path: str, tokens: set,
                timeout: float) -> Optional[_Lock]:
        import time
        lk = self.holder(path)
        if lk is None or lk.token not in tokens:
            return None
        lk.expires = time.monotonic() + timeout
        return lk

    def release(self, path: str, token: str) -> bool:
        self._gc()
        lk = self.holder(path)
        if lk is None or lk.token != token:
            return False
        del self._locks[lk.path]
        return True

    def release_subtree(self, path: str) -> None:
        """Drop every lock at `path` and below. A successful DELETE/MOVE
        destroys those resources; RFC 4918 (9.6/7.5) says their locks go
        with them — leaving them registered would 423 the recreated path
        until expiry."""
        prefix = path.rstrip("/") + "/"
        for p in [p for p in self._locks
                  if p == path or p.startswith(prefix)]:
            del self._locks[p]


class WebDavServer:
    def __init__(self, filer_url: str, url: str = ""):
        self.filer = filer_url.rstrip("/")
        self.url = url  # trace-span instance label (own host:port)
        self._session: Optional[aiohttp.ClientSession] = None
        self.locks = LockManager()
        self.metrics = metrics_mod.Registry("webdav")
        # gateway system set: only the reserved ops routes — user files
        # named like control-plane paths stay metered
        self.admission = overload.AdmissionController(
            "webdav", metrics=self.metrics,
            system_paths=(overload.GATEWAY_SYSTEM_PATHS
                          | overload.faults_admin_paths()))
        self.app = self._build_app()

    def _build_app(self) -> web.Application:
        app = web.Application(
            client_max_size=1024 * 1024 * 1024,
            middlewares=[observe.trace_middleware("webdav", self.url),
                         overload.admission_middleware(self.admission)])
        # ops surface before the catch-all (exact routes win), via
        # overload.reserve_ops so every other method answers 405 and a
        # PUT can't create a file that GET then shadows. Like the rest
        # of the webdav protocol surface, these carry no auth — deploy
        # this gateway on trusted networks only.
        from .. import faults
        from ..observe import profiler, wideevents
        for path, handler in (
                ("/healthz", overload.healthz_handler(self.admission)),
                ("/metrics", self.metrics_handler),
                ("/debug/trace", observe.trace_handler()),
                ("/debug/profile", profiler.profile_handler()),
                ("/debug/pprof", profiler.pprof_handler()),
                ("/debug/events", wideevents.events_handler())):
            overload.reserve_ops(app, path, handler)
        if faults.admin_enabled():
            # opt-in only (WEED_FAULTS_ADMIN=1): the webdav surface
            # carries no auth at all
            _faults_handler = faults.admin_handler()
            overload.reserve_ops(app, "/admin/faults", _faults_handler,
                                 post_handler=_faults_handler)
        app.router.add_route("*", "/{path:.*}", self.dispatch)
        app.on_startup.append(self._on_startup)
        app.on_cleanup.append(self._on_cleanup)
        return app

    async def metrics_handler(self, request: web.Request) -> web.Response:
        return web.Response(text=metrics_mod.exposition(self.metrics,
                                                        request),
                            content_type="text/plain")

    async def _on_startup(self, app) -> None:
        from ..observe import profiler
        profiler.ensure_started()
        await self.admission.start()
        self._session = aiohttp.ClientSession(
            # inactivity-bounded, no total cap (large file streams)
            timeout=aiohttp.ClientTimeout(total=None, sock_connect=10,
                                          sock_read=60),
            trace_configs=[observe.client_trace_config()])

    async def _on_cleanup(self, app) -> None:
        self.admission.stop()
        if self._session:
            await self._session.close()

    # --- filer meta helpers ---
    async def _lookup(self, path: str) -> Optional[dict]:
        async with self._session.get(
                f"http://{self.filer}/__meta__/lookup",
                params={"path": path or "/"}) as r:
            if r.status != 200:
                return None
            return await r.json()

    async def _list(self, path: str) -> list[dict]:
        async with self._session.get(
                f"http://{self.filer}/__meta__/list",
                params={"dir": path or "/"}) as r:
            if r.status != 200:
                return []
            return (await r.json()).get("entries", [])

    # --- dispatch ---
    async def dispatch(self, request: web.Request) -> web.StreamResponse:
        path = "/" + unquote(request.match_info["path"]).strip("/")
        method = request.method.upper()
        handler = {
            "OPTIONS": self.handle_options,
            "PROPFIND": self.handle_propfind,
            "GET": self.handle_get,
            "HEAD": self.handle_get,
            "PUT": self.handle_put,
            "DELETE": self.handle_delete,
            "MKCOL": self.handle_mkcol,
            "MOVE": self.handle_move,
            "COPY": self.handle_copy,
            "LOCK": self.handle_lock,
            "UNLOCK": self.handle_unlock,
            "PROPPATCH": self.handle_proppatch,
        }.get(method)
        if handler is None:
            return web.Response(status=405, headers=_DAV_HEADERS)
        # counted only for recognized methods: a client-chosen label
        # value would grow the registry without bound
        self.metrics.count("request", labels={"method": method})
        return await handler(request, path)

    async def handle_options(self, request, path) -> web.Response:
        return web.Response(status=200, headers=_DAV_HEADERS)

    # --- PROPFIND ---
    def _prop_xml(self, href: str, entry: dict) -> str:
        is_dir = _is_dir(entry)
        attr = entry.get("attr", {})
        size = sum(c.get("size", 0) for c in entry.get("chunks", []))
        mtime = attr.get("mtime", 0)
        ctype = attr.get("mime") or "application/octet-stream"
        if is_dir and not href.endswith("/"):
            href += "/"
        res_type = "<D:collection/>" if is_dir else ""
        length = ("" if is_dir else
                  f"<D:getcontentlength>{size}</D:getcontentlength>")
        return (
            "<D:response>"
            f"<D:href>{escape(quote(href))}</D:href>"
            "<D:propstat><D:prop>"
            f"<D:resourcetype>{res_type}</D:resourcetype>"
            f"{length}"
            f"<D:getlastmodified>{_rfc1123(mtime)}</D:getlastmodified>"
            f"<D:getcontenttype>{escape(ctype)}</D:getcontenttype>"
            f"<D:displayname>{escape(href.rstrip('/').rsplit('/', 1)[-1])}"
            "</D:displayname>"
            "</D:prop><D:status>HTTP/1.1 200 OK</D:status></D:propstat>"
            "</D:response>")

    async def handle_propfind(self, request, path) -> web.Response:
        depth = request.headers.get("Depth", "1")
        entry = await self._lookup(path)
        if entry is None:
            return web.Response(status=404)
        body = ['<?xml version="1.0" encoding="utf-8"?>',
                '<D:multistatus xmlns:D="DAV:">',
                self._prop_xml(path, entry)]
        if depth != "0" and _is_dir(entry):
            for child in await self._list(path):
                child_path = child.get("path", "")
                body.append(self._prop_xml(child_path, child))
        body.append("</D:multistatus>")
        return web.Response(status=207, text="".join(body),
                            content_type="application/xml",
                            headers={"DAV": "1,2"})

    # --- data ---
    async def handle_get(self, request, path) -> web.StreamResponse:
        entry = await self._lookup(path)
        if entry is None:
            return web.Response(status=404)
        if _is_dir(entry):
            return web.Response(status=403, text="is a collection")
        headers = {}
        if "Range" in request.headers:
            headers["Range"] = request.headers["Range"]
        async with self._session.get(
                f"http://{self.filer}{quote(path)}", headers=headers) as r:
            resp = web.StreamResponse(status=r.status)
            for h in ("Content-Type", "Content-Range", "ETag",
                      "Accept-Ranges"):
                if h in r.headers:
                    resp.headers[h] = r.headers[h]
            await resp.prepare(request)
            if request.method != "HEAD":
                async for chunk in r.content.iter_chunked(64 * 1024):
                    await resp.write(chunk)
            await resp.write_eof()
            return resp

    async def handle_put(self, request, path) -> web.Response:
        denied = self._lock_conflict(request, path)
        if denied is not None:
            return denied
        data = await request.read()
        async with self._session.put(
                f"http://{self.filer}{quote(path)}", data=data,
                headers={"Content-Type":
                         request.content_type
                         or "application/octet-stream"}) as r:
            return web.Response(status=201 if r.status < 300 else r.status)

    async def handle_delete(self, request, path) -> web.Response:
        denied = self._lock_conflict(request, path, subtree=True)
        if denied is not None:
            return denied
        async with self._session.delete(
                f"http://{self.filer}{quote(path)}",
                params={"recursive": "true"}) as r:
            if r.status == 404:
                return web.Response(status=404)
            if r.status >= 300:
                # the resource still exists: its locks must survive
                return web.Response(status=502)
            # the subtree is gone; its locks must not outlive it
            self.locks.release_subtree(path)
            return web.Response(status=204)

    async def handle_mkcol(self, request, path) -> web.Response:
        denied = self._lock_conflict(request, path)
        if denied is not None:
            return denied
        if await self._lookup(path) is not None:
            return web.Response(status=405)
        async with self._session.post(
                f"http://{self.filer}{quote(path)}",
                params={"op": "mkdir"}) as r:
            return web.Response(status=201 if r.status < 300 else r.status)

    def _dest_path(self, request) -> Optional[str]:
        dest = request.headers.get("Destination", "")
        if not dest:
            return None
        return "/" + unquote(urlparse(dest).path).strip("/")

    async def handle_move(self, request, path) -> web.Response:
        dest = self._dest_path(request)
        if dest is None:
            return web.Response(status=400, text="missing Destination")
        denied = self._lock_conflict(request, path, dest, subtree=True)
        if denied is not None:
            return denied
        existed = await self._lookup(dest) is not None
        if existed and request.headers.get("Overwrite", "T") == "F":
            return web.Response(status=412)
        async with self._session.post(
                f"http://{self.filer}{quote(path)}",
                params={"mv.to": dest}) as r:
            if r.status == 404:
                return web.Response(status=404)
            if r.status >= 300:
                # the move didn't happen: source locks must survive
                return web.Response(status=502)
            # nothing exists at the source anymore, and an overwritten
            # destination went through an implicit DELETE (RFC 4918
            # 9.9.4) — locks on either side die with the old resources
            self.locks.release_subtree(path)
            self.locks.release_subtree(dest)
            return web.Response(status=204 if existed else 201)

    async def handle_copy(self, request, path) -> web.Response:
        dest = self._dest_path(request)
        if dest is None:
            return web.Response(status=400, text="missing Destination")
        denied = self._lock_conflict(request, dest, subtree=True)
        if denied is not None:
            return denied
        entry = await self._lookup(path)
        if entry is None:
            return web.Response(status=404)
        if _is_dir(entry):
            return await self._copy_tree(request, path, dest)
        existed = await self._lookup(dest) is not None
        if existed and request.headers.get("Overwrite", "T") == "F":
            return web.Response(status=412)
        async with self._session.get(
                f"http://{self.filer}{quote(path)}") as r:
            data = await r.read()
        async with self._session.put(
                f"http://{self.filer}{quote(dest)}", data=data) as r:
            return web.Response(status=204 if existed else 201)

    async def _copy_tree(self, request, path, dest) -> web.Response:
        await self._session.post(f"http://{self.filer}{quote(dest)}",
                                 params={"op": "mkdir"})
        for child in await self._list(path):
            cp = child.get("path", "")
            name = cp.rsplit("/", 1)[-1]
            if _is_dir(child):
                await self._copy_tree(request, cp, f"{dest}/{name}")
            else:
                async with self._session.get(
                        f"http://{self.filer}{quote(cp)}") as r:
                    data = await r.read()
                await self._session.put(
                    f"http://{self.filer}{quote(dest + '/' + name)}",
                    data=data)
        return web.Response(status=201)

    # --- locks (class 2: real exclusive write locks with expiry, the
    # role x/net/webdav's memLS plays for the reference,
    # weed/server/webdav_server.go:101) ---
    def _submitted_tokens(self, request) -> set:
        """Tokens from the If header: (<opaquelocktoken:...>) groups."""
        import re
        return set(re.findall(r"<(opaquelocktoken:[^>]+)>",
                              request.headers.get("If", "")))

    def _lock_conflict(self, request, *paths,
                       subtree: bool = False) -> Optional[web.Response]:
        """423 unless every locked path among `paths` has its token in
        the request's If header. subtree=True also requires tokens for
        locks held on descendants (DELETE/MOVE of an ancestor destroys
        them)."""
        tokens = self._submitted_tokens(request)
        for p in paths:
            holders = [self.locks.holder(p)]
            if subtree:
                holders.append(self.locks.descendant_holder(p))
            for holder in holders:
                if holder is not None and holder.token not in tokens:
                    return web.Response(
                        status=423, content_type="application/xml",
                        text=('<?xml version="1.0" encoding="utf-8"?>'
                              '<D:error xmlns:D="DAV:">'
                              "<D:lock-token-submitted><D:href>"
                              f"{escape(quote(holder.path))}</D:href>"
                              "</D:lock-token-submitted></D:error>"))
        return None

    @staticmethod
    def _parse_timeout(request) -> float:
        """Timeout: Second-N | Infinite (capped like memLS's max)."""
        raw = request.headers.get("Timeout", "")
        for part in raw.split(","):
            part = part.strip()
            if part.lower().startswith("second-"):
                try:
                    return min(float(part[7:]), _MAX_LOCK_SECONDS)
                except ValueError:
                    pass
            if part.lower() == "infinite":
                return _MAX_LOCK_SECONDS
        return _DEFAULT_LOCK_SECONDS

    @staticmethod
    def _lock_body(lock: "_Lock") -> str:
        import time as time_mod
        remain = max(0, int(lock.expires - time_mod.monotonic()))
        return ('<?xml version="1.0" encoding="utf-8"?>'
                '<D:prop xmlns:D="DAV:"><D:lockdiscovery><D:activelock>'
                '<D:locktype><D:write/></D:locktype>'
                '<D:lockscope><D:exclusive/></D:lockscope>'
                f"<D:depth>{lock.depth}</D:depth>"
                f"<D:timeout>Second-{remain}</D:timeout>"
                f"<D:locktoken><D:href>{lock.token}</D:href></D:locktoken>"
                f"<D:lockroot><D:href>{escape(quote(lock.path))}</D:href>"
                "</D:lockroot></D:activelock></D:lockdiscovery></D:prop>")

    async def handle_lock(self, request, path) -> web.Response:
        timeout = self._parse_timeout(request)
        depth = request.headers.get("Depth", "infinity")
        body = await request.read()
        if not body:
            # empty body = refresh of the lock named in the If header
            tokens = self._submitted_tokens(request)
            lock = self.locks.refresh(path, tokens, timeout)
            if lock is None:
                return web.Response(status=412)  # precondition failed
            return web.Response(status=200, text=self._lock_body(lock),
                                content_type="application/xml")
        lock = self.locks.acquire(path, timeout,
                                  depth_infinity=(depth != "0"))
        if lock is None:
            return web.Response(status=423)
        return web.Response(status=200, text=self._lock_body(lock),
                            content_type="application/xml",
                            headers={"Lock-Token": f"<{lock.token}>"})

    async def handle_unlock(self, request, path) -> web.Response:
        raw = request.headers.get("Lock-Token", "").strip()
        token = raw[1:-1] if raw.startswith("<") else raw
        if not token:
            return web.Response(status=400)
        ok = self.locks.release(path, token)
        if not ok:
            # RFC 4918 9.11.1: wrong token on a locked resource
            return web.Response(status=409 if self.locks.holder(path)
                                is None else 403)
        return web.Response(status=204)

    async def handle_proppatch(self, request, path) -> web.Response:
        denied = self._lock_conflict(request, path)
        if denied is not None:
            return denied
        body = ('<?xml version="1.0" encoding="utf-8"?>'
                '<D:multistatus xmlns:D="DAV:"><D:response>'
                f"<D:href>{escape(quote(path))}</D:href>"
                "<D:propstat><D:status>HTTP/1.1 200 OK</D:status>"
                "</D:propstat></D:response></D:multistatus>")
        return web.Response(status=207, text=body,
                            content_type="application/xml")


async def run_webdav(host: str, port: int, filer_url: str,
                     **kwargs) -> web.AppRunner:
    kwargs.setdefault("url", f"{host}:{port}")
    server = WebDavServer(filer_url, **kwargs)
    runner = web.AppRunner(server.app, access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()
    log.info("webdav on %s:%d -> filer %s", host, port, filer_url)
    return runner
