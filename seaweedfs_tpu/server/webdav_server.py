"""WebDAV gateway over the filer (weed webdav equivalent,
weed/server/webdav_server.go:101 — golang.org/x/net/webdav FileSystem
backed by filer gRPC; here the same protocol surface over the filer's
HTTP API).

Implements the RFC 4918 subset real clients (davfs2, macOS Finder,
Windows explorer, cadaver) use: OPTIONS, PROPFIND (depth 0/1), GET/HEAD,
PUT, DELETE, MKCOL, MOVE, COPY.
"""

from __future__ import annotations

import logging
from typing import Optional
from urllib.parse import quote, unquote, urlparse
from xml.sax.saxutils import escape

import aiohttp
from aiohttp import web

log = logging.getLogger("webdav")

_DAV_HEADERS = {
    "DAV": "1,2",
    "MS-Author-Via": "DAV",
    "Allow": ("OPTIONS, PROPFIND, GET, HEAD, PUT, DELETE, MKCOL, "
              "MOVE, COPY"),
}


def _rfc1123(ts: float) -> str:
    import time
    return time.strftime("%a, %d %b %Y %H:%M:%S GMT", time.gmtime(ts))


def _is_dir(entry: dict) -> bool:
    """Directory-ness lives in the mode bits of the filer's entry JSON
    (S_IFDIR, like os.stat)."""
    mode = entry.get("attr", {}).get("mode", 0)
    return (int(mode) & 0o170000) == 0o040000


class WebDavServer:
    def __init__(self, filer_url: str):
        self.filer = filer_url.rstrip("/")
        self._session: Optional[aiohttp.ClientSession] = None
        self.app = self._build_app()

    def _build_app(self) -> web.Application:
        app = web.Application(client_max_size=1024 * 1024 * 1024)
        app.router.add_route("*", "/{path:.*}", self.dispatch)
        app.on_startup.append(self._on_startup)
        app.on_cleanup.append(self._on_cleanup)
        return app

    async def _on_startup(self, app) -> None:
        self._session = aiohttp.ClientSession()

    async def _on_cleanup(self, app) -> None:
        if self._session:
            await self._session.close()

    # --- filer meta helpers ---
    async def _lookup(self, path: str) -> Optional[dict]:
        async with self._session.get(
                f"http://{self.filer}/__meta__/lookup",
                params={"path": path or "/"}) as r:
            if r.status != 200:
                return None
            return await r.json()

    async def _list(self, path: str) -> list[dict]:
        async with self._session.get(
                f"http://{self.filer}/__meta__/list",
                params={"dir": path or "/"}) as r:
            if r.status != 200:
                return []
            return (await r.json()).get("entries", [])

    # --- dispatch ---
    async def dispatch(self, request: web.Request) -> web.StreamResponse:
        path = "/" + unquote(request.match_info["path"]).strip("/")
        method = request.method.upper()
        handler = {
            "OPTIONS": self.handle_options,
            "PROPFIND": self.handle_propfind,
            "GET": self.handle_get,
            "HEAD": self.handle_get,
            "PUT": self.handle_put,
            "DELETE": self.handle_delete,
            "MKCOL": self.handle_mkcol,
            "MOVE": self.handle_move,
            "COPY": self.handle_copy,
            "LOCK": self.handle_lock,
            "UNLOCK": self.handle_unlock,
            "PROPPATCH": self.handle_proppatch,
        }.get(method)
        if handler is None:
            return web.Response(status=405, headers=_DAV_HEADERS)
        return await handler(request, path)

    async def handle_options(self, request, path) -> web.Response:
        return web.Response(status=200, headers=_DAV_HEADERS)

    # --- PROPFIND ---
    def _prop_xml(self, href: str, entry: dict) -> str:
        is_dir = _is_dir(entry)
        attr = entry.get("attr", {})
        size = sum(c.get("size", 0) for c in entry.get("chunks", []))
        mtime = attr.get("mtime", 0)
        ctype = attr.get("mime") or "application/octet-stream"
        if is_dir and not href.endswith("/"):
            href += "/"
        res_type = "<D:collection/>" if is_dir else ""
        length = ("" if is_dir else
                  f"<D:getcontentlength>{size}</D:getcontentlength>")
        return (
            "<D:response>"
            f"<D:href>{escape(quote(href))}</D:href>"
            "<D:propstat><D:prop>"
            f"<D:resourcetype>{res_type}</D:resourcetype>"
            f"{length}"
            f"<D:getlastmodified>{_rfc1123(mtime)}</D:getlastmodified>"
            f"<D:getcontenttype>{escape(ctype)}</D:getcontenttype>"
            f"<D:displayname>{escape(href.rstrip('/').rsplit('/', 1)[-1])}"
            "</D:displayname>"
            "</D:prop><D:status>HTTP/1.1 200 OK</D:status></D:propstat>"
            "</D:response>")

    async def handle_propfind(self, request, path) -> web.Response:
        depth = request.headers.get("Depth", "1")
        entry = await self._lookup(path)
        if entry is None:
            return web.Response(status=404)
        body = ['<?xml version="1.0" encoding="utf-8"?>',
                '<D:multistatus xmlns:D="DAV:">',
                self._prop_xml(path, entry)]
        if depth != "0" and _is_dir(entry):
            for child in await self._list(path):
                child_path = child.get("path", "")
                body.append(self._prop_xml(child_path, child))
        body.append("</D:multistatus>")
        return web.Response(status=207, text="".join(body),
                            content_type="application/xml",
                            headers={"DAV": "1,2"})

    # --- data ---
    async def handle_get(self, request, path) -> web.StreamResponse:
        entry = await self._lookup(path)
        if entry is None:
            return web.Response(status=404)
        if _is_dir(entry):
            return web.Response(status=403, text="is a collection")
        headers = {}
        if "Range" in request.headers:
            headers["Range"] = request.headers["Range"]
        async with self._session.get(
                f"http://{self.filer}{quote(path)}", headers=headers) as r:
            resp = web.StreamResponse(status=r.status)
            for h in ("Content-Type", "Content-Range", "ETag",
                      "Accept-Ranges"):
                if h in r.headers:
                    resp.headers[h] = r.headers[h]
            await resp.prepare(request)
            if request.method != "HEAD":
                async for chunk in r.content.iter_chunked(64 * 1024):
                    await resp.write(chunk)
            await resp.write_eof()
            return resp

    async def handle_put(self, request, path) -> web.Response:
        data = await request.read()
        async with self._session.put(
                f"http://{self.filer}{quote(path)}", data=data,
                headers={"Content-Type":
                         request.content_type
                         or "application/octet-stream"}) as r:
            return web.Response(status=201 if r.status < 300 else r.status)

    async def handle_delete(self, request, path) -> web.Response:
        async with self._session.delete(
                f"http://{self.filer}{quote(path)}",
                params={"recursive": "true"}) as r:
            if r.status == 404:
                return web.Response(status=404)
            return web.Response(status=204)

    async def handle_mkcol(self, request, path) -> web.Response:
        if await self._lookup(path) is not None:
            return web.Response(status=405)
        async with self._session.post(
                f"http://{self.filer}{quote(path)}",
                params={"op": "mkdir"}) as r:
            return web.Response(status=201 if r.status < 300 else r.status)

    def _dest_path(self, request) -> Optional[str]:
        dest = request.headers.get("Destination", "")
        if not dest:
            return None
        return "/" + unquote(urlparse(dest).path).strip("/")

    async def handle_move(self, request, path) -> web.Response:
        dest = self._dest_path(request)
        if dest is None:
            return web.Response(status=400, text="missing Destination")
        existed = await self._lookup(dest) is not None
        if existed and request.headers.get("Overwrite", "T") == "F":
            return web.Response(status=412)
        async with self._session.post(
                f"http://{self.filer}{quote(path)}",
                params={"mv.to": dest}) as r:
            if r.status == 404:
                return web.Response(status=404)
            return web.Response(status=204 if existed else 201)

    async def handle_copy(self, request, path) -> web.Response:
        dest = self._dest_path(request)
        if dest is None:
            return web.Response(status=400, text="missing Destination")
        entry = await self._lookup(path)
        if entry is None:
            return web.Response(status=404)
        if _is_dir(entry):
            return await self._copy_tree(request, path, dest)
        existed = await self._lookup(dest) is not None
        if existed and request.headers.get("Overwrite", "T") == "F":
            return web.Response(status=412)
        async with self._session.get(
                f"http://{self.filer}{quote(path)}") as r:
            data = await r.read()
        async with self._session.put(
                f"http://{self.filer}{quote(dest)}", data=data) as r:
            return web.Response(status=204 if existed else 201)

    async def _copy_tree(self, request, path, dest) -> web.Response:
        await self._session.post(f"http://{self.filer}{quote(dest)}",
                                 params={"op": "mkdir"})
        for child in await self._list(path):
            cp = child.get("path", "")
            name = cp.rsplit("/", 1)[-1]
            if _is_dir(child):
                await self._copy_tree(request, cp, f"{dest}/{name}")
            else:
                async with self._session.get(
                        f"http://{self.filer}{quote(cp)}") as r:
                    data = await r.read()
                await self._session.put(
                    f"http://{self.filer}{quote(dest + '/' + name)}",
                    data=data)
        return web.Response(status=201)

    # --- lock stubs (class 2 compliance for finder/office clients) ---
    async def handle_lock(self, request, path) -> web.Response:
        token = "opaquelocktoken:seaweedfs-tpu-nolock"
        body = ('<?xml version="1.0" encoding="utf-8"?>'
                '<D:prop xmlns:D="DAV:"><D:lockdiscovery><D:activelock>'
                '<D:locktype><D:write/></D:locktype>'
                '<D:lockscope><D:exclusive/></D:lockscope>'
                f'<D:locktoken><D:href>{token}</D:href></D:locktoken>'
                "</D:activelock></D:lockdiscovery></D:prop>")
        return web.Response(status=200, text=body,
                            content_type="application/xml",
                            headers={"Lock-Token": f"<{token}>"})

    async def handle_unlock(self, request, path) -> web.Response:
        return web.Response(status=204)

    async def handle_proppatch(self, request, path) -> web.Response:
        body = ('<?xml version="1.0" encoding="utf-8"?>'
                '<D:multistatus xmlns:D="DAV:"><D:response>'
                f"<D:href>{escape(quote(path))}</D:href>"
                "<D:propstat><D:status>HTTP/1.1 200 OK</D:status>"
                "</D:propstat></D:response></D:multistatus>")
        return web.Response(status=207, text=body,
                            content_type="application/xml")


async def run_webdav(host: str, port: int, filer_url: str,
                     **kwargs) -> web.AppRunner:
    server = WebDavServer(filer_url, **kwargs)
    runner = web.AppRunner(server.app, access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()
    log.info("webdav on %s:%d -> filer %s", host, port, filer_url)
    return runner
