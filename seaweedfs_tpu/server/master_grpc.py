"""gRPC face of the master (role of weed/server/master_grpc_server.go).

Serves the Master service from proto/master.proto on HTTP port + 10000:
assign/lookup, the bidirectional heartbeat stream (a dropped stream
unregisters the node and broadcasts its DeletedVids immediately —
master_grpc_server.go:22-49), KeepConnected location push, and the admin
lease. All handlers delegate to the same MasterServer internals the
HTTP surface uses.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

import grpc

from ..ec import shard_bits
from ..pb import master_pb2 as pb
from ..pb.rpc import master_service_handler

log = logging.getLogger("master.grpc")


def _hb_to_dict(req: pb.HeartbeatRequest) -> dict:
    return {
        "node_id": req.node_id,
        "url": req.url,
        "public_url": req.public_url or req.url,
        "data_center": req.data_center,
        "rack": req.rack,
        "max_volume_count": req.max_volume_count or 8,
        "max_file_key": req.max_file_key,
        "volumes": [{
            "id": v.id, "collection": v.collection, "size": v.size,
            "file_count": v.file_count, "delete_count": v.delete_count,
            "deleted_bytes": v.deleted_bytes, "read_only": v.read_only,
            "replica_placement": v.replica_placement or "000",
            "ttl": v.ttl, "version": v.version or 3,
        } for v in req.volumes],
        "ec_shards": [{
            "id": s.id, "collection": s.collection,
            "shard_ids": shard_bits.to_ids(s.ec_index_bits),
            "shard_size": s.shard_size,
        } for s in req.ec_shards],
    }


def heartbeat_to_pb(payload: dict) -> pb.HeartbeatRequest:
    """Store heartbeat dict -> wire message (client side)."""
    return pb.HeartbeatRequest(
        node_id=payload["node_id"],
        url=payload["url"],
        public_url=payload.get("public_url", ""),
        data_center=payload.get("data_center", ""),
        rack=payload.get("rack", ""),
        max_volume_count=payload.get("max_volume_count", 8),
        max_file_key=payload.get("max_file_key", 0),
        volumes=[pb.VolumeInformation(
            id=v["id"], collection=v.get("collection", ""),
            size=v.get("size", 0), file_count=v.get("file_count", 0),
            delete_count=v.get("delete_count", 0),
            deleted_bytes=v.get("deleted_bytes", 0),
            read_only=v.get("read_only", False),
            replica_placement=str(v.get("replica_placement", "000")),
            ttl=str(v.get("ttl", "")), version=v.get("version", 3),
        ) for v in payload.get("volumes", [])],
        ec_shards=[pb.EcShardInformation(
            id=s["id"], collection=s.get("collection", ""),
            ec_index_bits=shard_bits.from_ids(s.get("shard_ids", [])),
            shard_size=s.get("shard_size", 0),
        ) for s in payload.get("ec_shards", [])])


class MasterGrpcServicer:
    def __init__(self, master):
        self.master = master

    async def Assign(self, request: pb.AssignRequest, context):
        if not await self.master.ensure_assign_ready():
            return pb.AssignResponse(error="not the leader / not ready")
        resp, status = await self.master.assign_api(
            count=request.count or 1,
            collection=request.collection,
            replication=request.replication,
            ttl=request.ttl,
            data_center=request.data_center)
        if status != 200:
            return pb.AssignResponse(error=resp.get("error", "failed"))
        return pb.AssignResponse(
            fid=resp["fid"], url=resp["url"],
            public_url=resp["publicUrl"], count=resp["count"],
            auth=resp.get("auth", ""), replicas=resp.get("replicas", []))

    async def Lookup(self, request: pb.LookupRequest, context):
        master = self.master
        if request.file_id:
            from ..storage.file_id import FileId
            try:
                fid = FileId.parse(request.file_id)
            except ValueError:
                return pb.LookupResponse(error="invalid fileId")
            vid = fid.volume_id
            auth = (master.guard.sign_read(str(fid))
                    if master.guard.read_signing_key else "")
        else:
            vid = request.volume_id
            auth = ""
        nodes = master.topology.lookup(vid, request.collection)
        if nodes:
            return pb.LookupResponse(
                volume_id=vid, auth=auth,
                locations=[pb.Location(url=n.url, public_url=n.public_url)
                           for n in nodes])
        shards = master.topology.lookup_ec_shards(vid)
        if shards:
            seen, locs = set(), []
            for nlist in shards.values():
                for n in nlist:
                    if n.url not in seen:
                        seen.add(n.url)
                        locs.append(pb.Location(url=n.url,
                                                public_url=n.public_url))
            return pb.LookupResponse(volume_id=vid, ec=True, auth=auth,
                                     locations=locs)
        return pb.LookupResponse(volume_id=vid, error="volume not found")

    async def LookupEc(self, request: pb.LookupEcRequest, context):
        shards = self.master.topology.lookup_ec_shards(request.volume_id)
        if not shards:
            return pb.LookupEcResponse(volume_id=request.volume_id,
                                       error="ec volume not found")
        return pb.LookupEcResponse(
            volume_id=request.volume_id,
            shards=[pb.EcShardLocations(
                shard_id=sid,
                locations=[pb.Location(url=n.url, public_url=n.public_url)
                           for n in nodes])
                    for sid, nodes in sorted(shards.items())])

    async def Heartbeat(self, request_iterator, context):
        """Bidi heartbeat stream: beats up, config down; a dropped stream
        unregisters the node immediately and pushes its DeletedVids."""
        master = self.master
        node_id: Optional[str] = None
        try:
            async for req in request_iterator:
                body = _hb_to_dict(req)
                node_id = body["node_id"]
                out = master.apply_heartbeat(body)
                yield pb.HeartbeatResponse(
                    volume_size_limit=out["volume_size_limit"],
                    leader=out["leader"])
        finally:
            if node_id is not None:
                ev = master.topology.unregister_node(node_id)
                master._broadcast_location(ev)
                log.info("heartbeat stream from %s closed; unregistered",
                         node_id)

    async def KeepConnected(self, request: pb.KeepConnectedRequest,
                            context):
        master = self.master
        if not master.raft.is_leader:
            yield pb.VolumeLocationMessage(
                leader=master.raft.leader_id or "")
            return
        q: asyncio.Queue = asyncio.Queue()
        master._watchers.add(q)
        try:
            for node in master.topology.nodes.values():
                vids = sorted(set(node.volumes) | set(node.ec_shards))
                yield pb.VolumeLocationMessage(
                    url=node.url, public_url=node.public_url,
                    new_vids=vids, is_snapshot=True,
                    leader=master.raft.leader_id or "")
            while True:
                msg = await q.get()
                yield pb.VolumeLocationMessage(
                    url=msg.get("url", ""),
                    public_url=msg.get("public_url", ""),
                    new_vids=msg.get("new_vids", []),
                    deleted_vids=msg.get("deleted_vids", []),
                    leader=master.raft.leader_id or "")
        finally:
            master._watchers.discard(q)

    async def ClusterStatus(self, request, context):
        raft = self.master.raft
        return pb.ClusterStatusResponse(
            is_leader=raft.is_leader, leader=raft.leader_id or "",
            peers=raft.peers, raft_term=raft.term)

    async def VolumeList(self, request, context):
        """Full per-node inventory (master_grpc_server_volume.go:117)."""
        topo = self.master.topology
        return pb.VolumeListResponse(
            volume_size_limit_mb=topo.volume_size_limit // (1024 * 1024),
            nodes=[pb.NodeVolumes(
                url=n.url, public_url=n.public_url,
                data_center=n.data_center, rack=n.rack,
                max_volume_count=n.max_volume_count,
                volumes=[pb.VolumeInformation(
                    id=v.id, collection=v.collection, size=v.size,
                    file_count=v.file_count, delete_count=v.delete_count,
                    deleted_bytes=v.deleted_bytes, read_only=v.read_only,
                    replica_placement=str(v.replica_placement),
                    ttl=str(v.ttl), version=v.version)
                    for v in n.volumes.values()],
                ec_shards=[pb.EcShardInformation(
                    id=e.id, collection=e.collection,
                    ec_index_bits=shard_bits.from_ids(e.shard_ids),
                    shard_size=e.shard_size)
                    for e in n.ec_shards.values()])
                for n in topo.nodes.values()])

    async def Statistics(self, request, context):
        """Aggregate usage, optionally filtered by collection
        (master_grpc_server_volume.go:176)."""
        topo = self.master.topology
        total = used = files = 0
        for n in topo.nodes.values():
            total += n.max_volume_count * topo.volume_size_limit
            for v in n.volumes.values():
                if request.collection and \
                        v.collection != request.collection:
                    continue
                used += v.size
                files += v.file_count
        return pb.StatisticsResponse(total_size=total, used_size=used,
                                     file_count=files)

    async def CollectionList(self, request, context):
        return pb.CollectionListResponse(
            collections=self.master.collection_names())

    async def CollectionDelete(self, request, context):
        if not request.name:
            # proto3 zero value must not match the default collection —
            # that would delete every unlabeled volume cluster-wide (the
            # HTTP twin rejects empty names the same way)
            return pb.CollectionDeleteResponse(
                ok=False, error="collection name required")
        out = await self.master.delete_collection(request.name)
        if out["errors"]:
            return pb.CollectionDeleteResponse(
                ok=False, error="; ".join(out["errors"]))
        return pb.CollectionDeleteResponse(ok=True)

    async def GetMasterConfiguration(self, request, context):
        m = self.master
        return pb.GetMasterConfigurationResponse(
            default_replication=m.default_replication,
            volume_size_limit_mb=m.topology.volume_size_limit
            // (1024 * 1024),
            garbage_threshold=m.garbage_threshold)

    async def LeaseAdminToken(self, request, context):
        resp, status = self.master.lease_admin_token(
            request.name, request.client, request.previous_token)
        if status != 200:
            return pb.LeaseAdminTokenResponse(error=resp["error"])
        return pb.LeaseAdminTokenResponse(token=resp["token"],
                                          expires_at=resp["expires_at"])

    async def ReleaseAdminToken(self, request, context):
        return pb.ReleaseAdminTokenResponse(
            ok=self.master.release_admin_token(request.name, request.token))


async def serve_master_grpc(master, host: str, port: int, tls=None):
    """Start the grpc.aio server; returns it (caller stops with
    .stop())."""
    server = grpc.aio.server()
    server.add_generic_rpc_handlers(
        (master_service_handler(MasterGrpcServicer(master),
                                guard=lambda: master.guard,
                                trace_instance=master.url),))
    creds = tls.grpc_server_credentials() if tls is not None else None
    if creds is not None:
        server.add_secure_port(f"{host}:{port}", creds)
    else:
        server.add_insecure_port(f"{host}:{port}")
    await server.start()
    log.info("master gRPC on %s:%d%s", host, port,
             " (mtls)" if creds else "")
    return server
