"""gRPC face of the filer (role of the reference's
weed/server/filer_grpc_server*.go family).

Serves the SeaweedFiler service from proto/filer.proto on HTTP port +
10000: entry CRUD, the ListEntries / SubscribeMetadata server streams,
assignment/lookup proxying, statistics, KeepConnected liveness, broker
location, and the KV surface. Handlers delegate to the same Filer
internals the /__meta__/* HTTP surface uses; SubscribeMetadata is the
real streaming backbone (filer_grpc_server_sub_meta.go) that the
ndjson /__meta__/subscribe route approximates for HTTP clients.
"""

from __future__ import annotations

import asyncio
import logging

import grpc

from ..filer.entry import Attr, Entry
from ..filer.chunks import FileChunk
from ..pb import filer_pb2 as pb
from ..pb.rpc import filer_service_handler

log = logging.getLogger("filer.grpc")


def _run(fn):
    return asyncio.get_event_loop().run_in_executor(None, fn)


def _ok() -> pb.Ok:
    return pb.Ok(ok=True)


def _err(e) -> pb.Ok:
    return pb.Ok(ok=False, error=str(e))


def entry_to_pb(e: Entry) -> pb.Entry:
    return pb.Entry(
        path=e.full_path,
        attr=pb.FuseAttributes(
            mtime=e.attr.mtime, crtime=e.attr.crtime, mode=e.attr.mode,
            uid=e.attr.uid, gid=e.attr.gid, mime=e.attr.mime,
            ttl_sec=e.attr.ttl_sec, user_name=e.attr.user_name,
            group_names=e.attr.group_names,
            symlink_target=e.attr.symlink_target, md5=e.attr.md5,
            replication=e.attr.replication, collection=e.attr.collection),
        chunks=[pb.FileChunk(
            fid=c.fid, offset=c.offset, size=c.size, mtime_ns=c.mtime,
            etag=c.etag, is_chunk_manifest=c.is_chunk_manifest,
            cipher_key=c.cipher_key) for c in e.chunks],
        extended=dict(e.extended),
        hard_link_id=e.hard_link_id)


def entry_from_pb(m: pb.Entry) -> Entry:
    a = m.attr
    return Entry(
        full_path=m.path,
        attr=Attr(mtime=a.mtime, crtime=a.crtime, mode=a.mode, uid=a.uid,
                  gid=a.gid, mime=a.mime, ttl_sec=a.ttl_sec,
                  user_name=a.user_name, group_names=list(a.group_names),
                  symlink_target=a.symlink_target, md5=a.md5,
                  replication=a.replication, collection=a.collection),
        chunks=[FileChunk(fid=c.fid, offset=c.offset, size=c.size,
                          mtime=c.mtime_ns, etag=c.etag,
                          is_chunk_manifest=c.is_chunk_manifest,
                          cipher_key=c.cipher_key) for c in m.chunks],
        extended=dict(m.extended),
        hard_link_id=m.hard_link_id)


def _event_to_pb(e) -> pb.MetaEvent:
    msg = pb.MetaEvent(tsns=e.tsns, directory=e.directory,
                       signatures=list(getattr(e, "signatures", ())))
    if e.old_entry is not None:
        msg.old_entry.CopyFrom(entry_to_pb(e.old_entry))
    if e.new_entry is not None:
        msg.new_entry.CopyFrom(entry_to_pb(e.new_entry))
    return msg


class FilerGrpcServicer:
    def __init__(self, fs):
        self.fs = fs            # FilerServer
        self.filer = fs.filer
        self._append_locks: dict[str, list] = {}

    # --- entry CRUD ---
    async def LookupDirectoryEntry(self, request: pb.LookupEntryRequest,
                                   context):
        path = request.directory.rstrip("/")
        if request.name:
            path = f"{path}/{request.name}"
        try:
            # ring-aware facade: owner-routed when the metaring is on,
            # the plain local filer otherwise
            entry = await self.fs.ring_find(path or "/")
        except FileNotFoundError:
            entry = None
        if entry is None:
            return pb.EntryResponse(error="not found")
        return pb.EntryResponse(entry=entry_to_pb(entry))

    async def ListEntries(self, request: pb.ListEntriesRequest, context):
        entries = await self.fs.ring_list(
            request.directory, request.start_from_file_name,
            request.inclusive_start_from, request.limit or 1024,
            request.prefix)
        for e in entries:
            yield pb.EntryResponse(entry=entry_to_pb(e))

    async def CreateEntry(self, request: pb.EntryRequest, context):
        entry = entry_from_pb(request.entry)
        try:
            # the facade frees replaced chunks hard-link-aware on the
            # owning peer (ring) or locally (ring off)
            await self.fs.ring_create(entry, o_excl=request.o_excl)
        except FileExistsError:
            return _err("exists")
        except (IsADirectoryError, NotADirectoryError) as e:
            return _err(e)
        return _ok()

    async def UpdateEntry(self, request: pb.EntryRequest, context):
        try:
            await self.fs.ring_update(entry_from_pb(request.entry))
            return _ok()
        except FileNotFoundError:
            return _err("not found")

    async def AppendToEntry(self, request: pb.AppendToEntryRequest,
                            context):
        # read-modify-write under a per-path lock: two concurrent appends
        # would otherwise compute the same base offset and one chunk list
        # overwrite the other's (the reference serializes in the filer
        # store transaction, filer_grpc_server_append.go)
        holder = self._append_locks.get(request.path)
        if holder is None:  # [lock, refcount]; entry dropped at zero
            holder = self._append_locks[request.path] = [asyncio.Lock(), 0]
        holder[1] += 1
        try:
            async with holder[0]:
                try:
                    entry = await self.fs.ring_find(request.path)
                except FileNotFoundError:
                    entry = None
                if entry is None:
                    return _err("not found")
                offset = entry.size()
                for c in request.chunks:
                    entry.chunks.append(FileChunk(
                        fid=c.fid, offset=offset, size=c.size,
                        mtime=c.mtime_ns, etag=c.etag,
                        is_chunk_manifest=c.is_chunk_manifest,
                        cipher_key=c.cipher_key))
                    offset += c.size
                await self.fs.ring_update(entry)
        finally:
            holder[1] -= 1
            if holder[1] == 0:
                self._append_locks.pop(request.path, None)
        return _ok()

    async def DeleteEntry(self, request: pb.DeleteEntryRequest, context):
        try:
            if self.fs._ring_on():
                await self.fs.ring_delete_entry_point(
                    request.path, recursive=request.is_recursive,
                    free_chunks=request.is_delete_data)
            else:
                await _run(lambda: self.filer.delete_entry(
                    request.path, recursive=request.is_recursive,
                    free_chunks=request.is_delete_data))
            return _ok()
        except FileNotFoundError as e:
            if request.ignore_recursive_error:
                return _ok()
            return _err(e)
        except OSError as e:
            return _err(e)

    async def AtomicRenameEntry(self, request: pb.RenameEntryRequest,
                                context):
        try:
            if self.fs._ring_on():
                await self.fs.ring_coordinator.rename(request.old_path,
                                                      request.new_path)
            else:
                await _run(lambda: self.filer.rename(request.old_path,
                                                     request.new_path))
            return _ok()
        except FileNotFoundError as e:
            return _err(e)

    # --- assignment / lookup proxy ---
    async def AssignVolume(self, request: pb.AssignVolumeRequest, context):
        from aiohttp import web
        try:
            a = await self.fs._assign(
                request.collection or self.fs.default_collection,
                request.replication or self.fs.default_replication,
                request.ttl_sec)
        except web.HTTPError as e:
            return pb.AssignVolumeResponse(error=str(e))
        return pb.AssignVolumeResponse(
            fid=a["fid"], url=a["url"],
            public_url=a.get("publicUrl", a["url"]),
            count=a.get("count", 1), auth=a.get("auth", ""))

    async def LookupVolume(self, request: pb.LookupVolumeRequest, context):
        resp = pb.LookupVolumeResponse()
        for vid_or_fid in request.volume_or_file_ids:
            vid = vid_or_fid.split(",")[0]
            try:
                urls = await self.fs._lookup(int(vid))
            except ValueError:
                urls = []
            resp.locations_map[vid_or_fid].urls.extend(urls or [])
        return resp

    # --- collections / stats / config ---
    async def CollectionList(self, request, context):
        body = await self.fs._master_get("/col/list", {})
        return pb.CollectionListResponse(
            collections=body.get("collections", []))

    async def DeleteCollection(self, request: pb.DeleteCollectionRequest,
                               context):
        body = await self.fs._master_get(
            "/col/delete", {"collection": request.collection})
        if body.get("error"):
            return _err(body["error"])
        return _ok()

    async def Statistics(self, request: pb.StatisticsRequest, context):
        """Aggregate usage from the master's full inventory (/vol/list),
        optionally filtered by collection — same computation as the
        master's own Statistics RPC."""
        body = await self.fs._master_get("/vol/list", {})
        limit = body.get("volume_size_limit_mb", 0) * 1024 * 1024
        total = used = files = 0
        for node in body.get("nodes", []):
            total += node.get("max_volume_count", 0) * limit
            for v in node.get("volumes", []):
                if request.collection and \
                        v.get("collection") != request.collection:
                    continue
                used += v.get("size", 0)
                files += v.get("file_count", 0)
        return pb.StatisticsResponse(total_size=total, used_size=used,
                                     file_count=files)

    async def GetFilerConfiguration(self, request, context):
        return pb.FilerConfigurationResponse(
            masters=self.fs.masters,
            collection=self.fs.default_collection,
            replication=self.fs.default_replication,
            max_mb=self.fs.chunk_size // (1024 * 1024),
            dir_buckets="/buckets",
            cipher=self.fs.cipher,
            signature=self.filer.signature)

    # --- metadata subscription streams ---
    async def SubscribeMetadata(self, request: pb.SubscribeMetadataRequest,
                                context):
        async for msg in self._subscribe(request):
            yield msg

    async def SubscribeLocalMetadata(self,
                                     request: pb.SubscribeMetadataRequest,
                                     context):
        # this framework's meta log is always the local log (peer events
        # are folded in by the aggregator before they reach it)
        async for msg in self._subscribe(request):
            yield msg

    async def _subscribe(self, request: pb.SubscribeMetadataRequest):
        """Replay persisted + in-memory events since since_ns, then tail
        live mutations — the gRPC twin of /__meta__/subscribe."""
        since = request.since_ns
        prefix = request.path_prefix or "/"
        exclude_sig = request.exclude_signature
        meta_log = self.filer.meta_log
        queue: asyncio.Queue = asyncio.Queue()
        loop = asyncio.get_event_loop()

        def on_event(e) -> None:
            loop.call_soon_threadsafe(queue.put_nowait, e)

        def admit(e) -> bool:
            return not (exclude_sig and exclude_sig in e.signatures)

        meta_log.subscribe(on_event)
        try:
            seen = set()
            for e in meta_log.read_persisted_since(since, prefix):
                seen.add(e.tsns)
                if admit(e):
                    yield _event_to_pb(e)
            for e in meta_log.events_since(since, prefix):
                if e.tsns in seen:
                    continue
                seen.add(e.tsns)
                if admit(e):
                    yield _event_to_pb(e)
            while True:
                e = await queue.get()
                dup = bool(seen) and e.tsns in seen
                if seen and queue.empty():
                    # the replay/live race window is over once the queue
                    # drains — stop holding every event id ever seen
                    # (long-lived subscribers would otherwise grow it
                    # unboundedly)
                    seen = set()
                if dup:
                    continue
                if not e.directory.startswith(prefix) or not admit(e):
                    continue
                yield _event_to_pb(e)
        finally:
            meta_log.unsubscribe(on_event)

    async def KeepConnected(self, request_iterator, context):
        """Bidi liveness: clients announce themselves, the filer echoes.
        The reference uses this to track attached mounts AND brokers
        (filer_grpc_server.go KeepConnected; brokers register so
        LocateBroker / consistent distribution can find them)."""
        name = None
        entry = None
        broker_addr = None
        try:
            async for req in request_iterator:
                name = req.name
                entry = list(req.resources)
                self.fs.connected_clients[name] = entry
                if name.startswith("broker@"):
                    broker_addr = name[len("broker@"):]
                    self.fs.broker_registry[broker_addr] = len(entry)
                yield pb.KeepConnectedResponse()
        finally:
            # stream end = client gone; a stale entry would report dead
            # mounts as attached forever — but only remove OUR entry: a
            # client that already reconnected under the same name has
            # replaced it, and popping would deregister the live stream
            if (name is not None
                    and self.fs.connected_clients.get(name) is entry):
                self.fs.connected_clients.pop(name, None)
                if broker_addr is not None:
                    self.fs.broker_registry.pop(broker_addr, None)

    async def LocateBroker(self, request: pb.LocateBrokerRequest, context):
        brokers = getattr(self.fs, "broker_registry", {})
        if not brokers:
            return pb.LocateBrokerResponse(found=False)
        resources = [pb.BrokerResource(grpc_address=addr,
                                       resource_count=count)
                     for addr, count in sorted(brokers.items())]
        return pb.LocateBrokerResponse(found=True, resources=resources)

    # --- kv ---
    async def KvGet(self, request: pb.KvRequest, context):
        val = await _run(lambda: self.filer.store.kv_get(
            request.key.decode()))
        if val is None:
            return pb.KvResponse(error="not found")
        return pb.KvResponse(value=val)

    async def KvPut(self, request: pb.KvRequest, context):
        await _run(lambda: self.filer.store.kv_put(
            request.key.decode(), bytes(request.value)))
        return _ok()


async def serve_filer_grpc(fs, host: str, port: int, tls=None):
    """Start the grpc.aio server for a FilerServer; returns it."""
    server = grpc.aio.server()
    server.add_generic_rpc_handlers(
        (filer_service_handler(FilerGrpcServicer(fs),
                               guard=lambda: fs.guard,
                               trace_instance=fs.url),))
    creds = tls.grpc_server_credentials() if tls is not None else None
    if creds is not None:
        server.add_secure_port(f"{host}:{port}", creds)
    else:
        server.add_insecure_port(f"{host}:{port}")
    await server.start()
    log.info("filer gRPC on %s:%d%s", host, port,
             " (mtls)" if creds else "")
    return server
