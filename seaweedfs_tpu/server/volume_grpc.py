"""gRPC face of the volume server (role of the reference's
weed/server/volume_grpc_*.go family).

Serves the VolumeServer service from proto/volume_server.proto on
HTTP port + 10000. Handlers delegate to the same Store internals the
HTTP /admin/* surface uses; the bulk surfaces (CopyFile, VolumeTail,
VolumeIncrementalCopy, VolumeEcShardRead, Query) are real server
streams, replacing their chunked-HTTP analogs for cluster-internal
traffic (volume_server.proto:10-95 in the reference defines the same
streaming shapes).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import shutil

import grpc

from ..pb import volume_server_pb2 as pb
from ..pb.rpc import volume_service_handler
from ..storage.store import safe_collection
from ..utils import durable

log = logging.getLogger("volume.grpc")

_CHUNK = 1 << 20


def _run(fn):
    return asyncio.get_event_loop().run_in_executor(None, fn)


def _ok() -> pb.Ok:
    return pb.Ok(ok=True)


def _err(e) -> pb.Ok:
    return pb.Ok(ok=False, error=str(e))


class VolumeGrpcServicer:
    def __init__(self, vs):
        self.vs = vs          # VolumeServer
        self.store = vs.store

    # --- data-plane helpers ---
    async def BatchDelete(self, request: pb.BatchDeleteRequest, context):
        from ..storage.file_id import FileId
        from ..storage.needle import Needle
        results = []
        for fid_str in request.fids:
            try:
                fid = FileId.parse(fid_str)
                n = Needle(cookie=fid.cookie, id=fid.key)
                size = await _run(
                    lambda f=fid, nn=n: self.store.delete_needle(
                        f.volume_id, nn))
                results.append(pb.DeleteResult(fid=fid_str, status=202,
                                               size=size))
            except Exception as e:
                results.append(pb.DeleteResult(fid=fid_str, status=404,
                                               error=str(e)))
        return pb.BatchDeleteResponse(results=results)

    async def VolumeNeedleStatus(self, request: pb.NeedleStatusRequest,
                                 context):
        try:
            n = await _run(lambda: self.store.read_needle(
                request.volume_id, request.needle_id))
            return pb.NeedleStatusResponse(
                cookie=n.cookie, size=len(n.data),
                last_modified=getattr(n, "last_modified", 0) or 0,
                crc=getattr(n, "checksum", 0) or 0,
                ttl=str(getattr(n, "ttl", "") or ""))
        except Exception as e:
            return pb.NeedleStatusResponse(error=str(e))

    # --- vacuum ---
    async def VacuumVolumeCheck(self, request: pb.VolumeRef, context):
        try:
            g = self.store.vacuum_check(request.volume_id)
            return pb.VacuumCheckResponse(garbage_ratio=g)
        except KeyError:
            return pb.VacuumCheckResponse(error="volume not found")

    async def VacuumVolumeCompact(self, request: pb.VacuumCompactRequest,
                                  context):
        try:
            await _run(lambda: self.store.vacuum_compact(
                request.volume_id, request.compaction_byte_per_second))
            return _ok()
        except (KeyError, RuntimeError) as e:
            return _err(e)

    async def VacuumVolumeCommit(self, request: pb.VolumeRef, context):
        try:
            await _run(lambda: self.store.vacuum_commit(request.volume_id))
            return _ok()
        except (KeyError, RuntimeError) as e:
            return _err(e)

    async def VacuumVolumeCleanup(self, request: pb.VolumeRef, context):
        try:
            self.store.vacuum_cleanup(request.volume_id)
            return _ok()
        except KeyError as e:
            return _err(e)

    # --- volume lifecycle ---
    async def AllocateVolume(self, request: pb.AllocateVolumeRequest,
                             context):
        try:
            self.store.add_volume(request.volume_id, request.collection,
                                  request.replication or "000",
                                  request.ttl)
        except (ValueError, RuntimeError) as e:
            return _err(e)
        await self._safe_heartbeat()
        return _ok()

    async def VolumeMount(self, request: pb.VolumeRef, context):
        try:
            self.store.mount_volume(request.volume_id, request.collection)
        except Exception as e:
            return _err(e)
        await self._safe_heartbeat()
        return _ok()

    async def VolumeUnmount(self, request: pb.VolumeRef, context):
        ok = self.store.unmount_volume(request.volume_id)
        await self._safe_heartbeat()
        return pb.Ok(ok=ok, error="" if ok else "volume not found")

    async def VolumeDelete(self, request: pb.VolumeRef, context):
        ok = self.store.delete_volume(request.volume_id)
        await self._safe_heartbeat()
        return pb.Ok(ok=ok, error="" if ok else "volume not found")

    async def VolumeMarkReadonly(self, request: pb.VolumeRef, context):
        ok = self.store.mark_readonly(request.volume_id, True)
        return pb.Ok(ok=ok, error="" if ok else "volume not found")

    async def VolumeMarkWritable(self, request: pb.VolumeRef, context):
        ok = self.store.mark_readonly(request.volume_id, False)
        return pb.Ok(ok=ok, error="" if ok else "volume not found")

    async def VolumeConfigure(self, request: pb.VolumeConfigureRequest,
                              context):
        try:
            self.store.configure_replication(request.volume_id,
                                             request.replication)
            return _ok()
        except Exception as e:
            return _err(e)

    async def VolumeStatus(self, request: pb.VolumeRef, context):
        v = self.store.find_volume(request.volume_id)
        if v is None:
            return pb.VolumeStatusResponse(error="volume not found")
        return pb.VolumeStatusResponse(
            is_read_only=v.read_only, volume_size=v.data_file_size(),
            file_count=v.file_count(),
            delete_count=v.nm.deleted_count)

    async def DeleteCollection(self, request: pb.DeleteCollectionRequest,
                               context):
        vids = [vid for loc in self.store.locations
                for vid, v in list(loc.volumes.items())
                if v.collection == request.collection]
        for vid in vids:
            self.store.delete_volume(vid)
        await self._safe_heartbeat()
        return _ok()

    # --- replication / move / sync ---
    async def VolumeCopy(self, request: pb.VolumeCopyRequest, context):
        """Pull a whole volume from the source server over its CopyFile
        gRPC stream and mount it (VolumeCopy pull model,
        weed/server/volume_grpc_copy.go:24-151)."""
        vid = request.volume_id
        collection = request.collection
        if not safe_collection(collection):
            return _err("bad collection")
        if self.store.find_volume(vid) is not None:
            return _err("volume exists")
        open_locs = [l for l in self.store.locations
                     if len(l.volumes) < l.max_volume_count]
        if not open_locs:
            return _err("no free slots")
        loc = min(open_locs, key=lambda l: len(l.volumes))
        prefix = f"{collection}_" if collection else ""
        base = os.path.join(loc.directory, f"{prefix}{vid}")
        try:
            for ext in (".dat", ".idx"):
                await pull_file_grpc(request.source_data_node, vid,
                                     collection, ext, base + ext)
            from ..storage.needle_map import remove_sidecars
            remove_sidecars(base + ".idx")  # never trust a leftover .sdx
            try:
                # a stale sync watermark from a prior same-id volume
                # would mis-anchor the pulled copy's recovery scan
                os.remove(base + ".swm")
            except FileNotFoundError:
                pass
            from ..storage.volume import Volume
            v = await _run(lambda: Volume(
                loc.directory, collection, vid,
                needle_map_kind=self.store.needle_map_kind))
            loc.volumes[vid] = v
        except Exception as e:
            for ext in (".dat", ".idx"):
                if os.path.exists(base + ext):
                    os.remove(base + ext)
            return _err(e)
        await self._safe_heartbeat()
        return _ok()

    async def ReadVolumeFileStatus(self, request: pb.VolumeRef, context):
        v = self.store.find_volume(request.volume_id)
        if v is None:
            return pb.VolumeFileStatusResponse(error="volume not found")
        idx_path = v.base_file_name() + ".idx"
        idx_size = os.path.getsize(idx_path) \
            if os.path.exists(idx_path) else 0
        return pb.VolumeFileStatusResponse(
            volume_id=request.volume_id,
            idx_file_size=idx_size, dat_file_size=v.data_file_size(),
            file_count=v.file_count(),
            compaction_revision=v.sb.compact_revision,
            collection=v.collection)

    async def CopyFile(self, request: pb.CopyFileRequest, context):
        """Stream one volume/shard file to a pulling peer."""
        ext = request.ext
        if not ext.startswith(".") or "/" in ext or ".." in ext \
                or not safe_collection(request.collection):
            yield pb.DataChunk(error="bad ext or collection", is_last=True)
            return
        prefix = (f"{request.collection}_" if request.collection else "")
        path = None
        for loc in self.store.locations:
            p = os.path.join(loc.directory,
                             f"{prefix}{request.volume_id}{ext}")
            if os.path.exists(p):
                path = p
                break
        if path is None:
            yield pb.DataChunk(error="file not found", is_last=True)
            return
        stop = request.stop_offset or os.path.getsize(path)
        with open(path, "rb") as f:
            sent = 0
            while sent < stop:
                chunk = await _run(
                    lambda: f.read(min(_CHUNK, stop - sent)))
                if not chunk:
                    break
                sent += len(chunk)
                yield pb.DataChunk(data=chunk)
        yield pb.DataChunk(is_last=True)

    async def VolumeTail(self, request: pb.TailRequest, context):
        """One needle record per chunk, appended after since_ns
        (VolumeTailSender, weed/server/volume_grpc_tail.go:16-79)."""
        from ..storage import volume_backup
        v = self.store.find_volume(request.volume_id)
        if v is None:
            yield pb.DataChunk(error="volume not found", is_last=True)
            return
        it = volume_backup.iter_needles_since(v, request.since_ns)

        def next_record():
            try:
                n = next(it)
            except StopIteration:
                return None
            return n.to_bytes(v.version)

        while True:
            rec = await _run(next_record)
            if rec is None:
                break
            yield pb.DataChunk(data=rec)
        yield pb.DataChunk(is_last=True)

    async def VolumeIncrementalCopy(self, request: pb.TailRequest,
                                    context):
        async for chunk in self.VolumeTail(request, context):
            yield chunk

    async def VolumeTailSender(self, request: pb.TailRequest, context):
        """Reference name for the tail stream (volume_grpc_tail.go
        VolumeTailSender); identical semantics to VolumeTail."""
        async for chunk in self.VolumeTail(request, context):
            yield chunk

    async def VolumeSyncStatus(self, request: pb.VolumeRef, context):
        """Tail offset + compaction revision for incremental sync
        (VolumeSyncStatus, volume_grpc_sync.go)."""
        v = self.store.find_volume(request.volume_id)
        if v is None:
            return pb.VolumeSyncStatusResponse(error="volume not found")
        idx_path = v.base_file_name() + ".idx"
        idx_size = (os.path.getsize(idx_path)
                    if os.path.exists(idx_path) else 0)
        return pb.VolumeSyncStatusResponse(
            volume_id=request.volume_id,
            collection=v.collection,
            tail_offset=v.data_file_size(),
            compact_revision=v.super_block.compaction_revision,
            idx_file_size=idx_size)

    async def VolumeTailReceiver(self, request: pb.TailReceiverRequest,
                                 context):
        """Pull new needle records from the source and append them
        locally (VolumeTailReceiver, volume_grpc_tail.go:81-126)."""
        from ..storage import volume_backup
        from ..storage.needle import Needle
        v = self.store.find_volume(request.volume_id)
        if v is None:
            return _err("volume not found")
        target = grpc_target(request.source_volume_server)
        n_applied = 0
        from ..pb.rpc import VolumeServerStub, aio_dial
        async with aio_dial(target) as channel:
            stub = VolumeServerStub(channel)
            async for chunk in stub.VolumeTail(pb.TailRequest(
                    volume_id=request.volume_id,
                    since_ns=request.since_ns)):
                if chunk.error:
                    return _err(chunk.error)
                if chunk.is_last:
                    break
                n = Needle.from_bytes(chunk.data, v.version)
                # empty body = tombstone -> delete, and the source's
                # append_at_ns is preserved so the replica's high-water
                # mark stays truthful for the next incremental tail
                await _run(lambda nn=n:
                           volume_backup.apply_tailed_needle(v, nn))
                n_applied += 1
        log.info("tail-receive applied %d records to %d",
                 n_applied, request.volume_id)
        return _ok()

    # --- erasure coding ---
    async def VolumeEcShardsGenerate(self, request: pb.EcGenerateRequest,
                                     context):
        try:
            await _run(lambda: self.store.ec_generate(request.volume_id))
            return _ok()
        except (KeyError, ValueError) as e:
            return _err(e)

    async def VolumeEcShardsRebuild(self, request: pb.EcRebuildRequest,
                                    context):
        try:
            rebuilt = await _run(lambda: self.store.ec_rebuild(
                request.volume_id, request.collection))
            return pb.EcRebuildResponse(rebuilt_shard_ids=rebuilt)
        except (KeyError, ValueError) as e:
            return pb.EcRebuildResponse(error=str(e))

    async def VolumeEcShardsCopy(self, request: pb.EcCopyRequest, context):
        """Pull shard files from the source server over gRPC CopyFile."""
        from .. import ec as ec_mod
        vid = request.volume_id
        collection = request.collection
        if not safe_collection(collection):
            return _err("bad collection")
        loc = self.store.locations[0]
        prefix = f"{collection}_" if collection else ""
        base = os.path.join(loc.directory, f"{prefix}{vid}")
        try:
            exts = [ec_mod.to_ext(sid) for sid in request.shard_ids]
            if request.copy_ecx_file:
                exts += [".ecx", ".ecj"]
            for ext in exts:
                try:
                    await pull_file_grpc(request.source_data_node, vid,
                                         collection, ext, base + ext)
                except FileNotFoundError:
                    if ext == ".ecj":
                        continue  # delete journal is optional
                    raise
        except Exception as e:
            return _err(e)
        return _ok()

    async def VolumeEcShardsDelete(self, request: pb.EcShardsRequest,
                                   context):
        self.store.ec_delete_shards(request.volume_id, request.collection,
                                    list(request.shard_ids))
        await self._safe_heartbeat()
        return _ok()

    async def VolumeEcShardsMount(self, request: pb.EcShardsRequest,
                                  context):
        try:
            self.store.ec_mount(request.volume_id, request.collection,
                                list(request.shard_ids))
        except (KeyError, FileNotFoundError) as e:
            return _err(e)
        await self._safe_heartbeat()
        return _ok()

    async def VolumeEcShardsUnmount(self, request: pb.EcShardsRequest,
                                    context):
        self.store.ec_unmount(request.volume_id, list(request.shard_ids))
        await self._safe_heartbeat()
        return _ok()

    async def VolumeEcShardRead(self, request: pb.EcShardReadRequest,
                                context):
        """Stream a shard byte range (VolumeEcShardRead,
        volume_grpc_erasure_coding.go:270-328) — the degraded-read path's
        peer fetch rides this stream."""
        try:
            offset, remaining = request.offset, request.size
            while remaining > 0:
                n = min(_CHUNK, remaining)
                data = await _run(
                    lambda o=offset, s=n: self.store.ec_shard_read(
                        request.volume_id, request.shard_id, o, s))
                if data:
                    yield pb.DataChunk(data=data)
                if len(data) < n:
                    # short pread = range past shard EOF; a silent
                    # truncated stream would look complete to the caller
                    yield pb.DataChunk(
                        error=f"short read at {offset + len(data)}",
                        is_last=True)
                    return
                offset += n
                remaining -= n
            yield pb.DataChunk(is_last=True)
        except KeyError as e:
            yield pb.DataChunk(error=str(e), is_last=True)

    async def VolumeEcBlobDelete(self, request: pb.EcBlobDeleteRequest,
                                 context):
        try:
            self.store.ec_blob_delete(request.volume_id, request.file_key)
            return _ok()
        except KeyError as e:
            return _err(e)

    async def VolumeEcShardsToVolume(self, request: pb.VolumeRef, context):
        try:
            await _run(lambda: self.store.ec_to_volume(
                request.volume_id, request.collection))
        except (KeyError, FileNotFoundError) as e:
            return _err(e)
        await self._safe_heartbeat()
        return _ok()

    # --- tiered storage ---
    async def VolumeTierMoveDatToRemote(self, request: pb.TierMoveRequest,
                                        context):
        """destination_backend_name carries the JSON backend spec (the
        HTTP surface takes the same dict; named-backend config resolution
        is the shell's job)."""
        try:
            spec = json.loads(request.destination_backend_name)
        except ValueError:
            return _err("destination_backend_name must be a JSON "
                        "backend spec")
        try:
            await _run(lambda: self.store.tier_upload(
                request.volume_id, spec,
                keep_local=request.keep_local_dat_file))
        except Exception as e:
            return _err(e)
        await self._safe_heartbeat()
        return _ok()

    async def VolumeTierMoveDatFromRemote(self, request: pb.TierMoveRequest,
                                          context):
        try:
            await _run(lambda: self.store.tier_download(request.volume_id))
        except (KeyError, ValueError) as e:
            return _err(e)
        await self._safe_heartbeat()
        return _ok()

    # --- server-level ---
    async def VolumeServerStatus(self, request, context):
        disks = []
        vol_count = 0
        ec_count = 0
        for loc in self.store.locations:
            try:
                u = shutil.disk_usage(loc.directory)
                disks.append(pb.DiskStatus(dir=loc.directory, all=u.total,
                                           used=u.used, free=u.free))
            except OSError:
                pass
            vol_count += len(loc.volumes)
            ec_count += sum(len(ev.shards)
                            for ev in loc.ec_volumes.values())
        return pb.VolumeServerStatusResponse(
            disk_statuses=disks, volume_count=vol_count,
            ec_shard_count=ec_count, version="seaweedfs-tpu")

    async def VolumeServerLeave(self, request, context):
        """Stop heartbeating so the master prunes this node; the admin
        shell drains it first (command_volume_server_leave.go)."""
        if self.vs._hb_task is not None:
            self.vs._hb_task.cancel()
            self.vs._hb_task = None
        return _ok()

    # --- query pushdown ---
    async def Query(self, request: pb.QueryRequest, context):
        from ..query import QueryFilter, query_json_lines
        from ..storage.file_id import FileId
        flt = None
        if request.filter_json:
            try:
                f = json.loads(request.filter_json)
                flt = QueryFilter(f["field"], f.get("op", "="),
                                  f.get("value"))
            except (ValueError, KeyError) as e:
                yield pb.DataChunk(error=f"bad filter: {e}", is_last=True)
                return
        payloads = []
        for fid_str in request.file_ids:
            try:
                fid = FileId.parse(fid_str)
                n = await _run(lambda f=fid: self.store.read_needle(
                    f.volume_id, f.key, cookie=f.cookie))
                payloads.append(n.data)
            except Exception:
                continue
        selections = list(request.selections) or None
        for line in query_json_lines(payloads, flt, selections):
            yield pb.DataChunk(data=line.encode() + b"\n")
        yield pb.DataChunk(is_last=True)

    async def _safe_heartbeat(self):
        try:
            await self.vs.send_heartbeat()
        except Exception as e:
            log.warning("post-admin heartbeat failed: %s", e)


def grpc_target(http_url: str) -> str:
    from ..pb.rpc import grpc_address
    return grpc_address(http_url)


async def pull_file_grpc(source_http_url: str, vid: int, collection: str,
                         ext: str, dest_path: str) -> None:
    """Fetch one volume/shard file from a peer's CopyFile stream into
    dest_path. Raises FileNotFoundError when the peer lacks the file."""
    from ..pb.rpc import VolumeServerStub, aio_dial
    async with aio_dial(grpc_target(source_http_url)) as channel:
        stub = VolumeServerStub(channel)
        tmp = dest_path + ".tmp"
        try:
            with open(tmp, "wb") as f:
                async for chunk in stub.CopyFile(pb.CopyFileRequest(
                        volume_id=vid, collection=collection, ext=ext)):
                    if chunk.error:
                        if "not found" in chunk.error:
                            raise FileNotFoundError(chunk.error)
                        raise IOError(chunk.error)
                    if chunk.data:
                        f.write(chunk.data)
                    if chunk.is_last:
                        break
            # a pulled replica/shard becomes load-bearing the moment the
            # repair plan counts it — commit it durably, off the loop
            await asyncio.get_event_loop().run_in_executor(
                None, durable.replace_atomic, tmp, dest_path)
        finally:
            # transport errors (RpcError) land here too — never leave a
            # partial multi-GB .tmp in the data directory
            if os.path.exists(tmp):
                os.remove(tmp)


async def serve_volume_grpc(vs, host: str, port: int, tls=None):
    """Start the grpc.aio server for a VolumeServer; returns it."""
    server = grpc.aio.server()
    server.add_generic_rpc_handlers(
        (volume_service_handler(VolumeGrpcServicer(vs),
                                guard=lambda: vs.guard,
                                trace_instance=vs.url),))
    creds = tls.grpc_server_credentials() if tls is not None else None
    if creds is not None:
        server.add_secure_port(f"{host}:{port}", creds)
    else:
        server.add_insecure_port(f"{host}:{port}")
    await server.start()
    log.info("volume gRPC on %s:%d%s", host, port,
             " (mtls)" if creds else "")
    return server
